"""Holder — root registry of all indexes under a data directory
(ref: holder.go:46-70)."""
import logging
import os
import shutil
import threading
import time
import uuid

from pilosa_tpu import errors as perr
from pilosa_tpu import faults
from pilosa_tpu import stats as stats_mod
from pilosa_tpu.storage import fragment as fragment_mod
from pilosa_tpu.storage.index import Index
from pilosa_tpu.storage.memgov import HostMemGovernor
from pilosa_tpu import lockcheck

_LOG = logging.getLogger("pilosa_tpu.storage.holder")


class Holder:
    def __init__(self, path, host_bytes=None):
        self.path = path
        self.mu = lockcheck.register("storage.Holder.mu",
                                     threading.RLock(),
                                     allow_device_sync=True)
        self.indexes = {}
        self.local_id = None
        self.broadcaster = None  # set by Server before open()
        self.stats = stats_mod.NOP
        # Flight recorder (observe.events), server-installed and
        # propagated down the index/frame/view/fragment chain like
        # .stats; None when off.
        self.events = None
        # Host-memory budget for resident fragment matrices (the
        # reference's analog is the OS evicting cold mmap pages). Env
        # override so operators can cap RSS without code changes.
        if host_bytes is None:
            env = os.environ.get("PILOSA_TPU_HOST_BYTES")
            if env:
                try:
                    host_bytes = int(env)
                    if host_bytes <= 0:
                        raise ValueError(env)
                except ValueError:
                    host_bytes = None
        self.governor = HostMemGovernor(host_bytes)
        # Deletion tombstones: ("index", name) / ("frame", idx, name)
        # -> unix deletion time. The heartbeat piggyback's create-only
        # schema union would otherwise RESURRECT deletions — any
        # in-flight or lagging peer's status re-creates the object and
        # re-propagates it cluster-wide every probe round. Tombstones
        # ride the status; an explicit local re-create clears them.
        self._tombstones = {}
        self._status_memo = None  # (monotonic, schema, digest)
        # Bumped (under mu) by EVERY schema-changing path —
        # including Index._create_frame via
        # invalidate_status_memo() — so a memo rebuild that
        # raced a DDL can detect it and decline to install a
        # pre-DDL schema over the invalidation.
        self._status_ver = 0
        # Fired with the index NAME after an index leaves self.indexes
        # by ANY path — explicit delete, heartbeat tombstone merge, or
        # replica resync. The executor hangs its plan-cache release
        # here (plancache.drop_index): the epoch bump alone only
        # invalidates lazily, and a deleted index is never queried
        # again, so its entries and unbounded universe memos would be
        # retained until evicted.
        self.on_index_drop = None

    def open(self):
        """Scan directories and open every index→frame→view→fragment
        (ref: holder.go:87-150)."""
        with self.mu:
            os.makedirs(self.path, exist_ok=True)
            self._acquire_dir_lock()
            try:
                self._set_file_limit()
                for entry in sorted(os.listdir(self.path)):
                    full = os.path.join(self.path, entry)
                    if not os.path.isdir(full) or entry.startswith("."):
                        continue
                    # Partial-boot hardening: one unreadable index must
                    # not fail the whole node (unreadable FRAGMENT
                    # files are quarantined deeper down, at fault-in —
                    # fragment._quarantine_locked; this catches the
                    # structural failures above them: meta JSON rot,
                    # permission errors, the holder.open.partial
                    # failpoint). The skipped index stays on disk for
                    # the operator; everything else serves.
                    try:
                        if faults.ACTIVE.enabled:
                            faults.ACTIVE.fire("holder.open.partial")
                        idx = Index(full, entry)
                        idx.broadcaster = self.broadcaster
                        idx.stats = self.stats.with_tags(f"index:{entry}")
                        idx.governor = self.governor
                        idx.events = self.events
                        idx.holder = self  # tombstone plumbing
                        idx.open()
                    except perr.ErrFragmentLocked:
                        # A held lock is a deliberate REFUSAL — another
                        # process owns this data (mixed-era mutual
                        # exclusion) — not rot to boot around: two
                        # writers would corrupt what a skipped index
                        # merely hides.
                        raise
                    except Exception:  # noqa: BLE001 — boot must survive
                        _LOG.warning(
                            "index %s failed to open; skipping (node "
                            "boots without it)", entry, exc_info=True)
                        self.stats.count("holder_open_errors_total", 1)
                        continue
                    self.indexes[entry] = idx
                self._load_local_id()
                self._load_tombstones_locked()
            except BaseException:
                # A failed open must not leak the dir lock: a retry in
                # this process would hit its own stale fd forever.
                self._release_dir_lock()
                raise
        return self

    def close(self):
        with self.mu:
            try:
                for idx in self.indexes.values():
                    idx.close()
                self.indexes = {}
            finally:
                self._release_dir_lock()

    def _acquire_dir_lock(self):
        """ONE exclusive flock on the data directory instead of one
        per fragment (the same cross-process guard as
        fragment.go:203-205, at 1 fd instead of ~10k at 10B-column
        scale — per-fragment lock fds exhausted RLIMIT_NOFILE on a
        2-node 10B benchmark in one process). Replica holders (worker
        read-only views of a master's files) take no lock."""
        if fragment_mod.REPLICA:
            return
        self._dir_lock = fragment_mod.try_flock(
            os.path.join(self.path, fragment_mod.HOLDER_LOCK_NAME),
            perr.ErrHolderLocked)
        fragment_mod.register_locked_root(self.path)

    def _release_dir_lock(self):
        lock = getattr(self, "_dir_lock", None)
        if lock is not None:
            fragment_mod.unregister_locked_root(self.path)
            try:
                lock.close()
            except OSError:
                pass
            self._dir_lock = None

    def refresh_replica(self):
        """Replica worker resync (server/workers.py): reconcile the
        in-memory tree against the master's on-disk state — new
        indexes open, deleted ones close, survivors re-fault lazily."""
        with self.mu:
            try:
                on_disk = {
                    e for e in os.listdir(self.path)
                    if os.path.isdir(os.path.join(self.path, e))
                    and not e.startswith(".")}
            except FileNotFoundError:
                on_disk = set()
            for entry in sorted(on_disk - self.indexes.keys()):
                full = os.path.join(self.path, entry)
                idx = Index(full, entry)
                idx.broadcaster = self.broadcaster
                idx.stats = self.stats.with_tags(f"index:{entry}")
                idx.governor = self.governor
                idx.events = self.events
                idx.holder = self
                idx.open()
                self.indexes[entry] = idx
            dropped = []
            for entry in list(self.indexes.keys() - on_disk):
                self.indexes.pop(entry).close()
                dropped.append(entry)
            indexes = list(self.indexes.values())
        if self.on_index_drop is not None:
            for entry in dropped:
                self.on_index_drop(entry)
        for idx in indexes:
            idx.refresh_replica()

    @staticmethod
    def _set_file_limit(target=262144):
        """Raise RLIMIT_NOFILE toward ~262k (ref: setFileLimit
        holder.go:385-431): every open fragment holds its data-file and
        lock-file descriptors, so big schemas exhaust the default soft
        limit (often 1024) fast."""
        try:
            import resource

            soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
            if soft == resource.RLIM_INFINITY:  # already unlimited (-1
                return                          # in Python — never lower)
            want = target if hard == resource.RLIM_INFINITY \
                else min(target, hard)
            if soft < want:
                try:
                    resource.setrlimit(resource.RLIMIT_NOFILE, (want, hard))
                except (ValueError, OSError):
                    # Some kernels (darwin kern.maxfilesperproc) cap below
                    # the reported hard limit; retry with the reference's
                    # darwin fallback (holder.go:418-424).
                    fallback = 10240
                    if soft < fallback:
                        resource.setrlimit(resource.RLIMIT_NOFILE,
                                           (fallback, hard))
        except (ImportError, ValueError, OSError):
            pass  # non-POSIX or insufficient privilege: keep defaults

    def _load_local_id(self):
        """Persist a node UUID at <data>/.id (ref: holder.go:435-453)."""
        id_path = os.path.join(self.path, ".id")
        if os.path.exists(id_path):
            with open(id_path) as f:
                self.local_id = f.read().strip()
        else:
            self.local_id = str(uuid.uuid4())
            with open(id_path, "w") as f:
                f.write(self.local_id)

    # ----------------------------------------------------------- indexes

    def index_path(self, name):
        return os.path.join(self.path, name)

    def index(self, name):
        with self.mu:
            return self.indexes.get(name)

    def indexes_list(self):
        with self.mu:
            return [self.indexes[k] for k in sorted(self.indexes)]

    TOMBSTONE_TTL = 24 * 3600

    def _tombstone_path(self):
        return os.path.join(self.path, ".tombstones")

    def _save_tombstones_locked(self):
        """Persist live tombstones: a node that deletes and then
        restarts must still refuse a lagging peer's resurrection."""
        import json as _json

        now = time.time()
        live = [list(k) + [ts] for k, ts in self._tombstones.items()
                if now - ts < self.TOMBSTONE_TTL]
        try:
            with open(self._tombstone_path(), "w") as f:
                _json.dump(live, f)
        except OSError:
            pass

    def _load_tombstones_locked(self):
        import json as _json

        try:
            with open(self._tombstone_path()) as f:
                entries = _json.load(f)
        except (OSError, ValueError):
            return
        now = time.time()
        for entry in entries:
            *key_parts, ts = entry
            if now - ts < self.TOMBSTONE_TTL:
                self._tombstones[tuple(key_parts)] = ts

    def _record_tombstone(self, key):
        with self.mu:
            self._tombstones[key] = time.time()
            self._invalidate_status_memo_locked()  # schema changed
            self._save_tombstones_locked()

    def _clear_tombstone(self, key):
        with self.mu:
            if self._tombstones.pop(key, None) is not None:
                self._save_tombstones_locked()
            self._invalidate_status_memo_locked()

    def _tombstone_live(self, key):
        ts = self._tombstones.get(key)
        # Tombstone stamps are PERSISTED (.tombstones, heartbeats)
        # and compared against peer/meta createdAt wall stamps —
        # monotonic can't survive a restart or cross a node.
        # pilint: disable=deadline-clock
        return ts is not None and time.time() - ts < self.TOMBSTONE_TTL

    def _admit_tombstoned(self, key, created_at):
        """Schema-merge gate: False when a live deletion tombstone
        blocks this name. An advertised creation NEWER than the
        tombstone is a legitimate re-create — it clears the tombstone
        and is admitted (last-write-wins reconciliation)."""
        if not self._tombstone_live(key):
            return True
        if created_at > self._tombstones.get(key, 0):
            self._clear_tombstone(key)
            return True
        return False

    def create_index(self, name, column_label="", time_quantum=""):
        with self.mu:
            if name in self.indexes:
                raise perr.ErrIndexExists()
            # An explicit local re-create overrides any deletion
            # tombstone (the tombstone only blocks MERGE resurrection).
            self._tombstones.pop(("index", name), None)
            return self._create_index(name, column_label, time_quantum)

    def create_index_if_not_exists(self, name, column_label="", time_quantum=""):
        with self.mu:
            return self.indexes.get(name) or self._create_index(
                name, column_label, time_quantum)

    def _create_index(self, name, column_label, time_quantum):
        """Caller holds self.mu."""
        if not name:
            raise perr.ErrIndexRequired()
        idx = Index(self.index_path(name), name)
        idx.broadcaster = self.broadcaster
        idx.stats = self.stats.with_tags(f"index:{name}")
        idx.governor = self.governor
        idx.events = self.events
        idx.holder = self  # frame create/delete tombstone plumbing
        idx.open()
        if column_label:
            idx.set_column_label(column_label)
        if time_quantum:
            idx.set_time_quantum(time_quantum)
        idx.save_meta()
        self.indexes[name] = idx
        self._invalidate_status_memo_locked()  # schema changed
        # DDL is durable on disk now — let replica workers discover it
        # (the published epoch is their only schema-change signal).
        fragment_mod._bump_epoch(name)
        return idx

    def delete_index(self, name):
        with self.mu:
            idx = self.indexes.pop(name, None)
            if idx is None:
                raise perr.ErrIndexNotFound()
            self._tombstones[("index", name)] = time.time()
            self._invalidate_status_memo_locked()  # schema changed
            self._save_tombstones_locked()
        # close() takes idx.mu — never while holding holder.mu (the
        # frame tombstone path takes the locks in the other order).
        idx.close()
        shutil.rmtree(idx.path, ignore_errors=True)
        fragment_mod._bump_epoch(name)  # replicas drop the index
        if self.on_index_drop is not None:
            self.on_index_drop(name)

    # ------------------------------------------------------------ schema

    def schema(self, include_meta=False):
        """(ref: holder.go:173) — [{name, frames:[{name, views}]}].

        ``include_meta`` adds index/frame options + BSI fields — the
        payload used for rejoin reconciliation, where name-only schema
        would recreate frames with default options."""
        with self.mu:
            out = []
            for idx in self.indexes_list():
                frames = []
                # list() snapshots: holder.mu does not guard idx.frames
                # (idx.mu does) — heartbeat merges mutate them from
                # other threads while this walk runs.
                for fname in sorted(list(idx.frames)):
                    frame = idx.frames.get(fname)
                    if frame is None:
                        continue
                    info = {
                        "name": fname,
                        "views": [{"name": v}
                                  for v in sorted(list(frame.views))],
                    }
                    if include_meta:
                        # Creation stamp lets receivers reconcile a
                        # re-create against their deletion tombstone
                        # (newer creation wins).
                        info["createdAt"] = getattr(
                            frame, "created_at", 0)
                        info["options"] = {
                            "rowLabel": frame.row_label,
                            "inverseEnabled": frame.inverse_enabled,
                            "rangeEnabled": frame.range_enabled,
                            "cacheType": frame.cache_type,
                            "cacheSize": frame.cache_size,
                            "timeQuantum": frame.time_quantum,
                            "fields": [fd.to_dict() for fd in frame.fields],
                        }
                    frames.append(info)
                info = {"name": idx.name, "frames": frames}
                if include_meta:
                    info["createdAt"] = getattr(idx, "created_at", 0)
                    info["options"] = {"columnLabel": idx.column_label,
                                       "timeQuantum": idx.time_quantum}
                out.append(info)
            return out

    def apply_schema(self, schema):
        """Merge a remote schema (ref: Index.MergeSchemas index.go:576).
        Create-only, like the reference — but deletion tombstones are
        honored: a merged schema can never resurrect an object deleted
        locally within the tombstone TTL."""
        from pilosa_tpu.storage.index import FrameOptions

        for idx_info in schema:
            if not self._admit_tombstoned(("index", idx_info["name"]),
                                          idx_info.get("createdAt", 0)):
                continue
            opts = idx_info.get("options", {})
            idx = self.create_index_if_not_exists(
                idx_info["name"],
                column_label=opts.get("columnLabel", ""),
                time_quantum=opts.get("timeQuantum", ""))
            for f_info in idx_info.get("frames", []):
                if not self._admit_tombstoned(
                        ("frame", idx_info["name"], f_info["name"]),
                        f_info.get("createdAt", 0)):
                    continue
                fopts = f_info.get("options")
                frame = idx.create_frame_if_not_exists(
                    f_info["name"],
                    FrameOptions.from_dict(fopts) if fopts else None)
                for v_info in f_info.get("views", []):
                    frame.create_view_if_not_exists(v_info["name"])

    def node_status_compact(self, host):
        """Compact NodeStatus for heartbeat piggyback: full meta schema
        (apply_schema merges it idempotently), a stable schema digest,
        and the max-slice maps. The analog of what memberlist exchanges
        in gossip push/pull (gossip.go LocalState/MergeRemoteState, end
        of file) — schema and slice convergence rides every probe
        instead of waiting for the rejoin push or the 60 s poll.

        Senders strip the ``schema`` field when the other side's digest
        already matches, so steady-state probes stay O(bytes of the
        max-slice map) on the wire, not O(schema)."""
        schema, digest = self._schema_and_digest()
        now = time.time()
        with self.mu:  # snapshot: handler threads mutate under mu
            items = list(self._tombstones.items())
        tombs = [list(k) + [ts] for k, ts in items
                 if now - ts < self.TOMBSTONE_TTL]
        return {
            "host": host,
            "schema": schema,
            "schemaDigest": digest,
            "tombstones": tombs,
            "maxSlices": self.max_slices(),
            "maxInverseSlices": self.max_inverse_slices(),
        }

    def _invalidate_status_memo_locked(self):
        """Drop the schema/digest memo after a schema change. Caller
        holds self.mu. The version bump lets a concurrently-running
        _schema_and_digest rebuild detect that its walk predates this
        change and decline to install — without it, the rebuild's
        re-stamp silently overwrote the invalidation and re-served
        the pre-DDL digest for a full memo TTL (found by pilint's
        guarded-state pass: _status_memo written both under and
        outside mu)."""
        self._status_ver += 1
        self._status_memo = None

    def invalidate_status_memo(self):
        """Cross-class invalidation hook (Index._create_frame runs
        under idx.mu and must take holder.mu to touch the memo —
        idx.mu -> holder.mu is the established frame-path order, see
        Index.create_frame)."""
        with self.mu:
            self._invalidate_status_memo_locked()

    def _schema_and_digest(self):
        """(schema, digest), memoized for 2 s: the status is built per
        probe per peer plus per inbound heartbeat — O(schema) walks +
        hashing every few seconds in steady state otherwise. The short
        TTL means a just-changed schema ships at most one round late.

        The memo is read and installed under mu, versioned against
        concurrent invalidations; the O(schema) walk itself runs
        outside the lock (schema() re-enters the RLock as needed)."""
        import hashlib
        import json as _json

        now = time.monotonic()
        with self.mu:
            memo = self._status_memo
            ver = self._status_ver
        if memo is not None and now - memo[0] < 2.0:
            return memo[1], memo[2]
        schema = self.schema(include_meta=True)

        # Digest the LOGICAL schema only: the meta-level createdAt is
        # node-local (two nodes creating the same object independently
        # — or one via broadcast — stamp different times), and hashing
        # it made such digests stable-but-unequal forever, which both
        # defeated the steady-state schema-strip optimization and
        # tripped the divergence warning on healthy clusters. Strip
        # ONLY the known index/frame meta slots — never recurse into
        # arbitrary values, where a user key happening to be named
        # 'createdAt' must keep counting as real content.
        scrubbed = []
        for idx in schema:
            idx = {k: v for k, v in idx.items() if k != "createdAt"}
            idx["frames"] = [
                {k: v for k, v in fr.items() if k != "createdAt"}
                for fr in idx.get("frames", [])]
            scrubbed.append(idx)
        digest = hashlib.sha1(
            _json.dumps(scrubbed, sort_keys=True)
            .encode()).hexdigest()[:16]
        with self.mu:
            if self._status_ver == ver:
                self._status_memo = (now, schema, digest)
            # else: a DDL landed mid-walk — serve this (still
            # self-consistent) snapshot but leave the memo cold so
            # the next probe rebuilds post-DDL.
        return schema, digest

    def merge_remote_status(self, st):
        """Merge a peer's compact NodeStatus (heartbeat piggyback):
        deletion tombstones first (they gate the union), then the
        create-only schema union and monotonic max-slice maxima — all
        idempotent, so repeated exchanges are free."""
        now = time.time()
        for entry in st.get("tombstones") or []:
            *key_parts, ts = entry
            key = tuple(key_parts)
            if now - ts >= self.TOMBSTONE_TTL:
                continue
            with self.mu:
                if self._tombstones.get(key, 0) < ts:
                    self._tombstones[key] = ts
                    self._invalidate_status_memo_locked()
                    self._save_tombstones_locked()
            # Apply the deletion locally unless our object was created
            # AFTER the tombstone (a legitimate re-create wins). The
            # removal keeps the PEER's original stamp — going through
            # delete_index/delete_frame would re-stamp at local time,
            # inflating the tombstone past legitimate re-creates and
            # deleting them back off the cluster.
            if key[0] == "index" and len(key) == 2:
                with self.mu:
                    idx = self.indexes.get(key[1])
                    if idx is None or getattr(idx, "created_at",
                                              now) > ts:
                        idx = None
                    else:
                        self.indexes.pop(key[1])
                        self._invalidate_status_memo_locked()
                if idx is not None:
                    idx.close()
                    shutil.rmtree(idx.path, ignore_errors=True)
                    if self.on_index_drop is not None:
                        self.on_index_drop(key[1])
            elif key[0] == "frame" and len(key) == 3:
                idx = self.index(key[1])
                if idx is not None:
                    fr = idx.frame(key[2])
                    if fr is not None and getattr(
                            fr, "created_at", now) <= ts:
                        idx.delete_frame(key[2],
                                         record_tombstone=False)
                        with self.mu:
                            self._invalidate_status_memo_locked()
        self.apply_schema(st.get("schema") or [])
        for index, n in (st.get("maxSlices") or {}).items():
            idx = self.index(index)
            if idx is not None:
                idx.set_remote_max_slice(int(n))
        for index, n in (st.get("maxInverseSlices") or {}).items():
            idx = self.index(index)
            if idx is not None:
                idx.set_remote_max_inverse_slice(int(n))

    def fragment(self, index, frame, view, slice_num):
        """Accessor chain (ref: holder.go:196-338)."""
        idx = self.index(index)
        if idx is None:
            return None
        fr = idx.frame(frame)
        if fr is None:
            return None
        v = fr.view(view)
        if v is None:
            return None
        return v.fragment(slice_num)

    def fragments(self, index, frame, view, slices):
        """Bulk accessor: resolve index→frame→view ONCE, then one
        lookup per slice. Batched executors fetch whole slice lists
        (1B columns = 954 fragments per leaf per query); the per-call
        chain walk was a measurable slice of query latency."""
        idx = self.index(index)
        fr = idx.frame(frame) if idx is not None else None
        v = fr.view(view) if fr is not None else None
        if v is None:
            return [None] * len(slices)
        return [v.fragment(s) for s in slices]

    def prune_fragments(self, keep_fn):
        """Drop every local fragment whose ``(index_name, slice)``
        fails ``keep_fn`` — the post-rebalance removal pass
        (cluster/rebalancer.py): a committed resize leaves the old
        owners holding verified-elsewhere copies that should stop
        costing disk. Walks snapshots of the inner maps (fragments can
        be created concurrently — those are by definition owned, the
        write path routed them here). Returns fragments removed."""
        removed = 0
        for idx in self.indexes_list():
            for frame in list(idx.frames.values()):
                for v in list(frame.views.values()):
                    with v.mu:
                        slices = list(v.fragments)
                    for s in slices:
                        if not keep_fn(idx.name, s):
                            if v.drop_fragment(s):
                                removed += 1
        return removed

    def max_slices(self):
        """{index: max_slice} (ref: handler /slices/max)."""
        with self.mu:
            return {name: idx.max_slice() for name, idx in self.indexes.items()}

    def max_inverse_slices(self):
        with self.mu:
            return {name: idx.max_inverse_slice()
                    for name, idx in self.indexes.items()}

    # ------------------------------------------------- memory accounting

    _MEM_KEYS = ("hostBytes", "deviceBytes", "lazyBytes", "diskBytes",
                 "cacheEntries")

    def memory_stats(self):
        """Per-index and total memory occupancy — packed block bytes
        resident on host, device (HBM) mirror bytes, evicted-read memo
        bytes, roaring bytes on disk, TopN cache entries — plus the
        governor's view. Serves ``GET /debug/memory`` and the
        ``pilosa_memory_*`` gauges. The fragment walk reads gauges
        lock-free (Fragment.memory_stats); the index list snapshots
        under holder.mu like schema().

        Memoized for 2 s (the _schema_and_digest discipline): the walk
        is O(total fragments) with a stat() syscall each for the disk
        gauge, and a scraped node answers /metrics, /cluster/metrics
        fan-in, and /debug/vars back to back — gauges tolerate 2 s of
        staleness, a 10k-fragment stat storm per surface does not."""
        now = time.monotonic()
        memo = getattr(self, "_mem_memo", None)
        if memo is not None and now - memo[0] < 2.0:
            return memo[1]
        with self.mu:
            indexes = [(name, self.indexes[name])
                       for name in sorted(self.indexes)]
        per_index = {}
        totals = dict.fromkeys(self._MEM_KEYS, 0)
        totals["fragments"] = totals["residentFragments"] = 0
        totals["containers"] = self._empty_container_agg()
        for name, idx in indexes:
            agg = dict.fromkeys(self._MEM_KEYS, 0)
            agg["fragments"] = agg["residentFragments"] = 0
            cagg = self._empty_container_agg()
            for frame in list(idx.frames.values()):
                for view in list(frame.views.values()):
                    for frag in list(view.fragments.values()):
                        m = frag.memory_stats()
                        agg["fragments"] += 1
                        if m["resident"]:
                            agg["residentFragments"] += 1
                        for k in self._MEM_KEYS:
                            agg[k] += m[k]
                        c = m["containers"]
                        for fmt, fv in c["formats"].items():
                            cagg["formats"][fmt]["blocks"] += fv["blocks"]
                            cagg["formats"][fmt]["bytes"] += fv["bytes"]
                        cagg["denseEquivBytes"] += c["denseEquivBytes"]
                        cagg["conversions"] += c["conversions"]
            agg["containers"] = cagg
            per_index[name] = agg
            for k, v in agg.items():
                if k == "containers":
                    for fmt, fv in v["formats"].items():
                        t = totals["containers"]["formats"][fmt]
                        t["blocks"] += fv["blocks"]
                        t["bytes"] += fv["bytes"]
                    totals["containers"]["denseEquivBytes"] += (
                        v["denseEquivBytes"])
                    totals["containers"]["conversions"] += (
                        v["conversions"])
                else:
                    totals[k] += v
        out = {"indexes": per_index, "totals": totals,
               "governor": self.governor.snapshot()}
        self._mem_memo = (now, out)
        return out

    @staticmethod
    def _empty_container_agg():
        """Zeroed per-format container rollup (the /debug/memory and
        pilosa_memory_container_* shape — dense/array/run block counts
        + payload bytes, the dense-tier-equivalent bytes for the same
        blocks, and conversion totals)."""
        return {"formats": {f: {"blocks": 0, "bytes": 0}
                            for f in ("dense", "array", "run")},
                "denseEquivBytes": 0, "conversions": 0}

    def memory_metrics(self):
        """Flat ``name;index:...`` dict for the /metrics ``memory``
        group (pilosa_memory_* series): per-index gauges plus governor
        totals."""
        ms = self.memory_stats()
        out = {}
        for name, agg in ms["indexes"].items():
            out[f"fragment_bytes;index:{name}"] = agg["hostBytes"]
            out[f"device_bytes;index:{name}"] = agg["deviceBytes"]
            out[f"lazy_bytes;index:{name}"] = agg["lazyBytes"]
            out[f"disk_bytes;index:{name}"] = agg["diskBytes"]
            out[f"cache_entries;index:{name}"] = agg["cacheEntries"]
            out[f"resident_fragments;index:{name}"] = agg[
                "residentFragments"]
            # Compressed container tier (ops/containers.py): per-format
            # resident block counts + payload bytes, the dense-tier
            # equivalent for the same blocks, and conversion totals.
            c = agg["containers"]
            for fmt, fv in c["formats"].items():
                out[f"container_blocks;index:{name},format:{fmt}"] = (
                    fv["blocks"])
                out[f"container_bytes;index:{name},format:{fmt}"] = (
                    fv["bytes"])
            out[f"container_dense_equiv_bytes;index:{name}"] = (
                c["denseEquivBytes"])
            out[f"container_conversions_total;index:{name}"] = (
                c["conversions"])
        gov = ms["governor"]
        out["governor_resident_bytes"] = gov["residentBytes"]
        out["governor_budget_bytes"] = gov["budgetBytes"]
        out["governor_evictions_total"] = gov["evictions"]
        out["governor_faults_total"] = gov["faults"]
        return out

    def flush_caches(self):
        """(ref: monitorCacheFlush holder.go:340-376). The inner maps
        are snapshotted: holder.mu guards index creation/deletion, but
        writes create fragments under the frame/view locks, so a bulk
        load mutates ``view.fragments`` mid-walk otherwise."""
        with self.mu:
            for idx in list(self.indexes.values()):
                for frame in list(idx.frames.values()):
                    for view in list(frame.views.values()):
                        for frag in list(view.fragments.values()):
                            frag.flush_cache()

    def recalculate_caches(self):
        """Rebuild every fragment's TopN cache from storage, then
        persist (ref: handleRecalculateCaches handler.go:2016). Holds
        holder.mu for the whole walk, like flush_caches, so concurrent
        index deletion can't pull directories out from under the
        sidecar writes."""
        with self.mu:
            for idx in list(self.indexes.values()):
                for frame in list(idx.frames.values()):
                    for view in list(frame.views.values()):
                        for frag in list(view.fragments.values()):
                            frag.recalculate_cache()
                            frag.flush_cache()
