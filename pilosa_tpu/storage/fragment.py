"""Fragment — the unit of storage, compute, and replication.

A fragment is one (index, frame, view, slice): a 2^20-column bitmap
matrix (ref: fragment.go:50 SliceWidth, :157-247 storage lifecycle).

TPU-first design
----------------
The reference mmaps a roaring file and computes on containers in place.
Here the fragment keeps **two mirrors** of the same bits:

- a host ``numpy uint64[capacity, 16384]`` row matrix — the mutation
  target, serialization source, and iteration surface (ascending-position
  iteration order matches the reference's container walk, which the
  anti-entropy block checksums require);
- a device ``uint32[capacity, 32768]`` copy in HBM — the compute surface
  for every query kernel. A little-endian view makes the two layouts
  identical, so refresh is a pure DMA with no repacking.

Mutations follow the reference's durability design exactly: every
set/clear appends a 13-byte op-log record to the open roaring file
(roaring.go:740), and once the log outgrows the amortized threshold
(``_op_log_room`` — scales with fragment cardinality, unlike the
reference's fixed 2000-op cadence that makes sustained writes O(n²))
the whole file is rewritten via an atomic temp-file rename
(``snapshot()``, fragment.go:1369-1438).
Device refresh is batched: dirty rows are scattered into HBM only when a
query actually needs the device matrix — the mutation path never blocks
on the TPU (the analog of the reference's opN write-buffer cadence).

Row capacity grows in powers of two so jitted kernel shapes are bucketed
and recompilation is bounded.
"""
import itertools
import json
import logging
import os
import threading
import time

import numpy as np
import jax.numpy as jnp

from pilosa_tpu import SLICE_WIDTH, WORDS_PER_SLICE
from pilosa_tpu import errors as perr
from pilosa_tpu import faults
from pilosa_tpu import querystats
from pilosa_tpu import stats as stats_mod
from pilosa_tpu import tracing
from pilosa_tpu import native
from pilosa_tpu.observe import heatmap as heatmap_mod
from pilosa_tpu.observe import kerneltime as kerneltime_mod
from pilosa_tpu.ops import bitops
from pilosa_tpu.ops import bsi as bsi_ops
from pilosa_tpu.roaring import codec
from pilosa_tpu.storage.cache import new_cache
from pilosa_tpu.utils.xxhash import xxhash64

from pilosa_tpu import lockcheck

_LOG = logging.getLogger("pilosa_tpu.storage.fragment")

WORDS64 = SLICE_WIDTH // 64  # 16384 host words per row

# Snapshot after this many op-log records (ref: fragment.go:67 MaxOpN).
MAX_OPN = 2000
# A snapshot rewrites the whole file — O(cardinality) — so gating it at
# the reference's FIXED op cadence (fragment.go:67 MaxOpN=2000) makes
# sustained writes and batched bulk loads O(total²): every 2000 ops
# re-serializes everything written so far. The snapshot threshold here
# scales with the cardinality at the last snapshot instead (append
# while ops ≤ max(MAX_OPN, card/2)), so rewrites land at geometrically
# growing sizes — O(total) amortized — capped by OPLOG_MAX_OPS to keep
# the on-disk op region (13 B/op) and reopen replay bounded; replay is
# a vectorized parse + two scatters (codec.parse_ops/final_ops), not a
# per-record walk, so a full log replays in well under a second.
OPLOG_MAX_OPS = 4_000_000

# Rows per anti-entropy checksum block (ref: fragment.go:62 HashBlockSize).
HASH_BLOCK_SIZE = 100

_CONTAINERS_PER_ROW = SLICE_WIDTH // (1 << 16)  # 16
_WORDS64_PER_CONTAINER = 1024

# Rows allocate only a power-of-2 WINDOW of 64-bit words covering the
# touched column span — width from 64 words (4096 columns) up, base
# width-aligned anywhere in the slice. Row-heavy / column-narrow
# datasets (e.g. 500k molecule rows x 4096 fingerprint bits, the
# reference's chemical-similarity showcase) cost megabytes instead of
# 128 KB per row, and data clustered in HIGH columns costs its
# cluster's width, not the full slice (VERDICT r1: within-row paging).
# Words outside the window are zero by construction; external APIs pad
# on the way out.
_MIN_W64 = 64

# Sentinel: a lazy (evicted, container-granular) read declined; the
# caller must take the resident path instead. Distinct from None and
# from any legitimate zero-filled result.
_NOT_LAZY = object()

# Process-wide cap on live LazyReaders. CPython's mmap holds a dup'd
# file descriptor for the mapping's lifetime, so at 100B scale (~95k
# evicted fragments) READERS — not bytes — are the scarce resource:
# unbounded lazy reads exhaust RLIMIT_NOFILE (20k here) long before
# the host-byte governor sees pressure. LRU over fragments holding a
# reader; creating one past the cap drops the oldest fragment's
# reader ONLY — its compressed containers, count memos, and block
# memos stay, and the memo-first read paths serve without it.
try:
    MAX_LAZY_READERS = int(os.environ.get("PILOSA_TPU_MAX_READERS",
                                          "8192"))
except ValueError:  # malformed env must not crash import (cli/server)
    MAX_LAZY_READERS = 8192
_reader_mu = lockcheck.register("storage.fragment._reader_mu",
                                threading.Lock())
_reader_lru = {}  # Fragment -> None (dict preserves insertion order)


def _note_reader(frag):
    """Record reader use (LRU recency) and evict past the cap.
    Victims are acquired non-blocking — a contended fragment is
    skipped, never deadlocked on (the governor's unload discipline);
    the next creation retries the eviction."""
    global _reader_lru
    victims = []
    with _reader_mu:
        _reader_lru.pop(frag, None)
        _reader_lru[frag] = None
        while len(_reader_lru) > max(MAX_LAZY_READERS, 1):
            v = next(iter(_reader_lru))
            if v is frag:
                break
            del _reader_lru[v]
            victims.append(v)
    for v in victims:
        if not v._drop_reader() and v._lazy is not None:
            # Lock-contended victim still holds its reader: put it
            # back at the OLDEST end so the very next eviction retries
            # it — dropping it from the LRU while the fd lives would
            # erode the cap silently, and re-inserting at the
            # recently-used end would defer the retry for a whole LRU
            # cycle. O(n) rebuild, but contended victims are rare.
            with _reader_mu:
                if v not in _reader_lru:
                    _reader_lru = {v: None, **_reader_lru}


def _forget_reader(frag):
    with _reader_mu:
        _reader_lru.pop(frag, None)

# Process-wide mutation epoch: bumped on EVERY fragment version change
# and on fragment open/close. Executors use it as an O(1) "has anything
# changed since I cached this?" test — at 10k-slice scale, re-checking
# per-fragment version tokens on every query costs more than the query's
# device work. Epoch equality is sufficient (never necessary) for cache
# validity: any mutation anywhere invalidates the fast path and falls
# back to the precise per-fragment tokens. The increment is locked —
# a bare `+= 1` is a read-modify-write that can lose counts under
# concurrent writers (readers need no lock: they only compare values).
_index_epochs = {}   # index name -> bump count
_unattributed = 0    # bumps whose index scope is unknown (attr stores)
_epoch_mu = lockcheck.register("storage.fragment._epoch_mu",
                               threading.Lock())

# Replica mode (PILOSA_TPU_READ_ONLY=1, set by WorkerPool for
# exec-reads worker processes — see server/workers.py): this process
# serves reads from the master's data files and must never write them
# — no flock (the master holds LOCK_EX for its lifetime), no
# torn-tail repair snapshot (a live master mid-append is not a crash),
# no cache-sidecar flush, no op-log appends.
REPLICA = os.environ.get("PILOSA_TPU_READ_ONLY", "0") == "1"

# Cross-process epoch publication: the master mmaps two u64 counters
# that replica workers poll per request to decide whether their cached
# state is still valid (read-your-writes: a write bumps word 0 BEFORE
# its HTTP response, so the same client's next read sees a newer count
# and triggers a refresh). Word 0 is this process's epoch total;
# word 1 is the CLUSTER epoch version (cluster/epochs.py registry
# observations, 0 = single-node/cold) so multi-node worker caches go
# cold — never stale — when peer visibility lapses.
_epoch_total = 0     # all bumps, any scope (maintained under _epoch_mu)
_epoch_mm = None
_cluster_version = 0

_PUBLISH_BYTES = 16


def publish_epochs(path):
    """Master side: mirror every epoch bump into an mmap'd counter
    file readable by replica workers."""
    global _epoch_mm
    with open(path, "ab") as f:
        pass
    f = open(path, "r+b")
    f.truncate(_PUBLISH_BYTES)
    import mmap as _mmap

    _epoch_mm = _mmap.mmap(f.fileno(), _PUBLISH_BYTES)
    f.close()
    with _epoch_mu:
        _publish_locked()


def publish_cluster_version(version):
    """Master side, multi-node: publish the cluster epoch-vector
    version (word 1). ``0`` means COLD — worker caches must not
    replay. Called by the epoch registry on every observed change and
    by the staleness monitor."""
    global _cluster_version
    with _epoch_mu:
        _cluster_version = int(version)
        _publish_locked()


def open_published_epochs(path):
    """Replica side: read-only mmap of the master's counters; returns
    a zero-arg reader yielding ``(local_total, cluster_version)``."""
    import mmap as _mmap
    import os as _os
    import struct as _struct

    size = min(_os.path.getsize(path), _PUBLISH_BYTES)
    f = open(path, "rb")
    mm = _mmap.mmap(f.fileno(), size, prot=_mmap.PROT_READ)
    f.close()
    if size < _PUBLISH_BYTES:  # legacy 8-byte file from an old master
        return lambda: (_struct.unpack_from("<Q", mm, 0)[0], 0)
    return lambda: _struct.unpack_from("<QQ", mm, 0)


def epoch_total():
    """Process-wide bump total (any index, any scope) — the memo key
    for cheap has-anything-changed checks (epoch header caching)."""
    return _epoch_total


def _publish_locked():
    if _epoch_mm is not None:
        import struct as _struct

        _struct.pack_into("<QQ", _epoch_mm, 0, _epoch_total,
                          _cluster_version)


_LOCKED_ROOTS = set()  # dir prefixes covered by a holder-level flock

HOLDER_LOCK_NAME = ".holder.lock"


def register_locked_root(path):
    """Announce that ``path`` (a holder data dir) is protected by one
    directory-level flock: fragments beneath it skip their per-file
    lock fd (see Fragment._acquire_lock)."""
    _LOCKED_ROOTS.add(os.path.abspath(path) + os.sep)


def unregister_locked_root(path):
    _LOCKED_ROOTS.discard(os.path.abspath(path) + os.sep)


def try_flock(path, err_cls, transient=False):
    """Nonblocking exclusive flock on ``path`` — THE shared
    implementation for holder-level and per-fragment locks (one copy
    of the BlockingIOError / non-POSIX handling). Returns the held
    file handle; ``transient`` probes and releases immediately
    (returns None) — used to detect a conflicting owner without
    holding an fd. Raises ``err_cls`` when another process holds it."""
    lock = open(path, "ab")
    try:
        import fcntl

        fcntl.flock(lock.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
    except BlockingIOError:
        lock.close()
        raise err_cls()
    except ImportError:  # non-POSIX platform
        pass
    if transient:
        lock.close()  # close releases the flock
        return None
    return lock


_EMPTY_DIGEST = b"\x00" * 8
_MIX_C0 = np.uint64(0x9E3779B97F4A7C15)
_MIX_C1 = np.uint64(0xBF58476D1CE4E5B9)
_MIX_C2 = np.uint64(0x94D049BB133111EB)


def _mix64(x):
    """Vectorized splitmix64 finalizer over a uint64 ndarray: the
    per-global-word-position pseudorandom constants of the digest's
    multilinear hash (public splitmix64 constants; uint64 arithmetic
    wraps mod 2^64 by numpy's C semantics)."""
    z = x + _MIX_C0
    z = (z ^ (z >> np.uint64(30))) * _MIX_C1
    z = (z ^ (z >> np.uint64(27))) * _MIX_C2
    return z ^ (z >> np.uint64(31))


def _bump_epoch(index=None):
    global _unattributed, _epoch_total
    with _epoch_mu:
        _epoch_total += 1
        if index is None:
            _unattributed += 1
        else:
            _index_epochs[index] = _index_epochs.get(index, 0) + 1
        _publish_locked()


def mutation_epoch(index=None):
    """Mutation epoch for validity checks. With ``index``, the scoped
    view: per-index bump count + every unattributed bump — so a
    write-heavy index no longer flushes the epoch-validated memos of
    other (e.g. read-only dashboard) indexes, while an index-blind
    writer still invalidates everything. Both counters are monotone,
    so the sum changes on every relevant bump. Without ``index``, the
    process-wide count (any mutation anywhere)."""
    if index is None:
        # Snapshot under the lock: sum() iterates the dict, and a
        # concurrent first bump of a NEW index resizes it mid-iteration
        # (per-index reads stay lockless — they are single lookups).
        with _epoch_mu:
            return sum(_index_epochs.values()) + _unattributed
    return _index_epochs.get(index, 0) + _unattributed


class TopOptions:
    """TopN options (ref: fragment.go:1004-1021)."""

    def __init__(self, n=0, src=None, row_ids=None, filter_row_ids=None,
                 min_threshold=0, tanimoto_threshold=0):
        self.n = n
        self.src = src                      # np.uint64[WORDS64] filter bitmap
        self.row_ids = row_ids              # explicit candidate rows
        self.filter_row_ids = filter_row_ids  # attr-filtered allowed rows
        self.min_threshold = min_threshold
        self.tanimoto_threshold = tanimoto_threshold


class _ResidencyLock:
    """Re-entrant fragment lock that faults host state in on entry.

    Every fragment operation (internal and the executor's external
    ``with frag.mu:`` uses) serializes on this lock, which makes its
    ``__enter__`` the single choke point where an unloaded fragment —
    lazily opened at holder startup, or evicted by the host-memory
    governor — reloads its row matrix from the roaring file. The
    analog of the OS faulting an mmap'd page back in."""

    def __init__(self, frag):
        self._frag = frag
        self._lock = lockcheck.register("storage.Fragment.mu",
                                        threading.RLock(),
                                        allow_device_sync=True)

    def __enter__(self):
        self._lock.acquire()
        try:
            self._frag._fault_in_locked()
        except BaseException:
            self._lock.release()
            raise
        return self

    def __exit__(self, *exc):
        self._lock.release()

    def acquire_raw(self, blocking=True):
        """Acquire WITHOUT faulting in (open/unload bookkeeping).
        With blocking=False returns whether the lock was taken."""
        return self._lock.acquire(blocking=blocking)

    def release_raw(self):
        self._lock.release()

    def owned(self):
        """True iff the CURRENT thread holds this lock."""
        return self._lock._is_owned()


class Fragment:
    _UID_SEQ = itertools.count()

    def __init__(self, path, index, frame, view, slice_num,
                 cache_type="ranked", cache_size=50000):
        self.path = path
        self.index = index
        self.frame = frame
        self.view = view
        self.slice = slice_num
        self.cache_type = cache_type
        self._cache = new_cache(cache_type, cache_size)
        self.stats = stats_mod.NOP
        self.events = None  # flight recorder, view-propagated
        # process-unique id: cache validity tokens pair it with _version
        # so a deleted+recreated fragment can never alias a cache entry
        self._uid = next(self._UID_SEQ)
        # Host-memory governor (storage/memgov.py) wired by the owning
        # View; None = standalone fragment, always resident once used.
        self.governor = None
        self._last_used = 0
        self._opened = False      # open() ran (files + flock held)
        self._resident = False    # host matrices loaded
        self._faulting = False    # re-entrancy guard during fault-in
        self._cache_loaded = False

        self.mu = _ResidencyLock(self)
        self._cap = 0
        self._w64 = _MIN_W64   # window width in 64-bit words (power of 2)
        self._w64_base = 0     # window base word (multiple of _w64)
        self._matrix = np.zeros((0, _MIN_W64), dtype=np.uint64)
        self._row_counts = np.zeros(0, dtype=np.int64)
        self._row_index = {}      # rowID -> physical row
        self._phys_rows = []      # physical row -> rowID
        self.max_row_id = 0

        self.op_n = 0
        self._snap_card = None    # cardinality at last snapshot
        self._failed = None       # fail-stop latch: first storage fault
        self._op_file = None
        self._lock_file = None
        self._version = 0         # bumped on every mutation
        self._dev = None
        self._dev_version = -1
        self._dirty = set()       # physical rows stale on device
        self._planes_cache = {}   # (start_row, depth) -> (version, jnp planes)
        self._row_dev = {}        # phys -> (version, jnp row) dirty-row memo
        self._rc_dev = None       # (version, jnp int32 row counts) memo
        # Container-granular read path for EVICTED fragments: an mmap-
        # backed codec.LazyReader + per-row host memo, so a query
        # touching one row of an unloaded fragment decodes O(that
        # row's containers), not the whole file — and never faults the
        # fragment in (ref: mmap page granularity, fragment.go:190-247).
        self._lazy = None
        self._lazy_rows = {}      # row_id -> {sub: uint64[1024]}
        self._lazy_bytes = 0      # memoized lazy block bytes
        self._lazy_cache_ids = None  # sidecar TopN ids (evicted reads)
        self._lazy_counts = {}    # row_id -> exact count (evicted reads)
        self._win32_memo = None   # (version, (base32, width32) | None)
        self._digest_memo = None  # (version, 8-byte digest)
        # Compressed serving tier (ops/containers.py): phys ->
        # (version, Container) for ARRAY/RUN rows (dense rows wrap the
        # existing device mirrors per call — memoizing them here would
        # pin 128 KB rows past the _row_dev cap), plus the last format
        # each row served as (conversion detection) and the
        # pilosa_container_conversions_total contribution.
        self._cont_dev = {}
        self._cont_fmt = {}
        self._conversions = 0

    # ------------------------------------------------------------------ io

    @property
    def cache(self):
        """TopN cache; reading it faults the fragment in (the sidecar
        ids are only re-counted against loaded row data)."""
        if self._opened and not self._resident:
            with self.mu:  # __enter__ runs the fault-in
                pass
        return self._cache

    @property
    def cache_path(self):
        return self.path + ".cache"

    def open(self):
        """Open files + flock; host state loads lazily on first touch
        (the reference's mmap likewise reads no page at open —
        fragment.go:190-247)."""
        self.mu.acquire_raw()
        try:
            if self._opened:
                return self
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            if not REPLICA:
                if not (os.path.exists(self.path)
                        and os.path.getsize(self.path) > 0):
                    with open(self.path, "wb") as f:
                        f.write(codec.serialize({}))
                self._acquire_lock()
            # Op append handle opens lazily on first WRITE: an eager
            # fd per fragment exhausts RLIMIT_NOFILE (20k hard cap
            # here) at 10k-slice scale when most fragments only serve
            # reads.
            self._op_file = None
            self.op_n = 0  # the fault-in / lazy parse sets the real value
            self._failed = None  # reopen clears the fail-stop latch
            self._opened = True
            _bump_epoch(self.index)  # a new fragment object is now reachable
        finally:
            self.mu.release_raw()
        return self

    def _fault_in_locked(self):
        """Load host state from the roaring file (runs under the
        fragment lock, via _ResidencyLock.__enter__)."""
        if self._resident or self._faulting or not self._opened:
            if self._resident and self.governor is not None:
                self.governor.touch(self)
            return
        self._faulting = True
        try:
            # Becoming resident means mutations (and snapshots) may
            # follow — the lazy reader's view of the file goes stale.
            self._drop_lazy_locked()
            # open/read stay OUTSIDE the quarantine scope: an OSError
            # here is the ENVIRONMENT failing (EMFILE, EIO, EACCES),
            # not the file's content — quarantining a healthy file on
            # a transient fd-exhaustion would silently vanish its data
            # behind an empty replacement. I/O errors propagate (and
            # at boot, partial-open skips the index instead).
            with open(self.path, "rb") as f:
                raw = f.read()
            if (faults.ACTIVE.enabled
                    and faults.ACTIVE.fire("fragment.read.corrupt")):
                raw = bytes(255 - b for b in raw)  # mutilate in place
            try:
                blocks, self.op_n, torn = codec.deserialize(raw)
            except Exception as e:  # noqa: BLE001 — ANY undecodable
                # CONTENT quarantines: corruption surfaces as
                # ValueError from the decoder's own checks but as
                # struct.error (NOT a ValueError subclass) from a
                # truncated meta region — a narrow catch here turned
                # the most common real rot into a 500-forever
                # fragment.
                if REPLICA:
                    # Never rewrite a master's files from a replica; a
                    # transient mid-write read can also land here.
                    raise
                blocks, torn = self._quarantine_locked(e), False
                self.op_n = 0
            self._load_blocks(blocks)
            if self._snap_card is None:
                # Back-fill the amortized-snapshot reference point
                # HERE, before any new mutation lands: the loaded
                # cardinality approximates the last snapshot (off only
                # by the existing log's net effect) — back-filling
                # later, at the gate, would fold the in-flight batch
                # into the threshold and double the op-log bound.
                self._snap_card = int(self._row_counts.sum())
            if torn and not REPLICA:
                # Crash mid-append left a partial op record; rewrite
                # the file from the recovered state so future appends
                # are valid. A replica may read a LIVE master
                # mid-append — the valid prefix is simply the
                # pre-append state, never repaired from here.
                try:
                    self.snapshot()
                except OSError as e:
                    # The repair couldn't land (ENOSPC): serve the
                    # recovered prefix read-only rather than append
                    # after a tail of unknown validity.
                    self._fail_stop_locked(e)
            self._resident = True
            if not self._cache_loaded:
                self._open_cache()
                self._cache_loaded = True
        finally:
            self._faulting = False
        if self.governor is not None:
            self.governor.touch(self)
            self.governor.note_fault()
            self.governor.update(self, self.host_bytes())

    def _op_handle(self):
        """Append handle for the op log, opened on first write and
        closed by snapshot/unload/close — read-only fragments hold no
        descriptor for it."""
        if REPLICA:
            raise RuntimeError(
                "write reached a read-only replica fragment — writes "
                "must route to the master (server/workers.py)")
        if self._op_file is None:
            self._op_file = open(self.path, "ab")
        return self._op_file

    # -------------------------------------------------- fail-stop contract

    def _check_writable(self):
        """Every mutation entry point calls this first: a fragment
        that fail-stopped once rejects ALL further writes (503 at the
        handler) until a close()+open() reloads the durable prefix —
        after an append error the on-disk tail's validity is unknown,
        and appending after it would corrupt the log for real."""
        if self._failed is not None:
            raise perr.ErrFragmentFailStop()

    def _fail_stop_locked(self, exc):
        """Latch the fragment read-only after a storage fault. Reads
        keep serving (the in-memory mirrors and the on-disk prefix are
        both intact); writes raise ErrFragmentFailStop until reopen.
        Caller holds ``self.mu``."""
        if self._failed is not None:
            return
        self._failed = exc
        self.stats.count("fragment_failstop_total", 1)
        ev = self.events
        if ev is not None:
            ev.emit("fragment.failstop", index=self.index,
                    frame=self.frame, slice=self.slice,
                    error=str(exc))
        # Epoch bump: plan-cache / memo entries over this index must
        # recompute — a latched fragment changes what the executor may
        # assume about residency and writability.
        _bump_epoch(self.index)
        _LOG.warning("fragment %s fail-stopped (writes rejected until "
                     "reopen): %s", self.path, exc)
        if self._op_file is not None:
            try:
                self._op_file.close()
            except OSError:
                pass
            self._op_file = None

    def _append_ops_locked(self, data, fsync=False):
        """Append encoded op records under the fail-stop contract.
        Callers must NOT have mutated in-memory state yet: an
        ENOSPC/EIO here (or the ``fragment.append.fsync`` failpoint)
        latches the fragment read-only and raises — memory stays on
        the acknowledged prefix, the write is never acknowledged, and
        any torn bytes the failed flush left are the reopen path's
        (already-tested) torn-tail problem."""
        op = self._op_handle()
        try:
            if faults.ACTIVE.enabled:
                faults.ACTIVE.fire("fragment.append.fsync")
            op.write(data)
            op.flush()
            if fsync:
                os.fsync(op.fileno())
        except OSError as e:
            self._fail_stop_locked(e)
            raise perr.ErrFragmentFailStop() from e

    def _ack_snapshot_locked(self):
        """Ack-bearing snapshot, shared by every bulk install path:
        the batch's durability IS this snapshot, so a failure
        fail-stops the fragment AND rolls memory back to the durable
        file — an errored import must never read back as acknowledged
        (ack-then-lose). Caller holds ``self.mu``."""
        try:
            self.snapshot()
        except OSError as e:
            self._fail_stop_locked(e)
            self._rollback_from_disk_locked()
            raise perr.ErrFragmentFailStop() from e

    def _commit_caches_locked(self, touched):
        """Post-install cache/epoch tail shared by the bulk install
        paths: refresh the TopN cache for every touched physical row,
        then bump the mutation epoch AFTER the bytes flushed (see
        _mutate — the published counter must never lead the file).
        Caller holds ``self.mu``."""
        for p in touched:
            self.cache.bulk_add(self._phys_rows[p],
                                int(self._row_counts[p]))
        self.cache.invalidate()
        _bump_epoch(self.index)

    def _maybe_snapshot_locked(self):
        """Post-append snapshot housekeeping: the write that got us
        here is already durable in the op log, so a failed rewrite
        (ENOSPC) must not fail the acknowledged write — the log just
        stays long and the next threshold crossing retries."""
        if self._op_log_room(0):
            return
        try:
            self.snapshot()
        except OSError as e:
            _LOG.warning("fragment %s deferred snapshot failed "
                         "(op log kept): %s", self.path, e)

    def _rollback_from_disk_locked(self):
        """Reload the durable file after a failed ack-bearing snapshot:
        the in-memory mirrors hold bits the disk never accepted, and
        serving them would turn an errored import into a phantom
        acknowledged one. Best-effort — if even the read-back fails,
        the (already fail-stopped) fragment keeps serving memory."""
        try:
            with open(self.path, "rb") as f:
                blocks, self.op_n, _ = codec.deserialize(f.read())
        except Exception:  # noqa: BLE001 — see the fault-in catch:
            return         # struct.error etc. are not ValueError
        self._reset_storage()
        self._load_blocks(blocks)
        self._snap_card = int(self._row_counts.sum())

    def _quarantine_locked(self, exc):
        """An unreadable fragment file must not take the node down
        (the lazy holder boot means it would otherwise surface as a
        failed query or a failed fault-in): move it aside as
        ``<path>.corrupt`` for the operator, start empty, keep
        serving — anti-entropy refills the bits from replicas. Returns
        the (empty) block map the caller loads."""
        _LOG.warning("fragment %s unreadable, quarantined to "
                     "%s.corrupt: %s", self.path, self.path, exc)
        self.stats.count("fragment_quarantined_total", 1)
        ev = self.events
        if ev is not None:
            ev.emit("fragment.quarantine", index=self.index,
                    frame=self.frame, slice=self.slice,
                    error=str(exc))
        # The fragment's servable content just changed (to empty):
        # every epoch-validated entry over this index — plans,
        # preludes, result memos, response replays — must drop.
        _bump_epoch(self.index)
        if self._op_file is not None:
            try:
                self._op_file.close()
            except OSError:
                pass
            self._op_file = None
        try:
            os.replace(self.path, self.path + ".corrupt")
        except OSError:
            pass
        try:
            with open(self.path, "wb") as f:
                f.write(codec.serialize({}))
        except OSError:
            pass
        return {}

    def host_bytes(self):
        """Host bytes this fragment holds (governor unit): the
        resident matrices, or — when evicted — the lazy-read memos."""
        return int(self._matrix.nbytes + self._row_counts.nbytes
                   + self.lazy_bytes())

    def _mem_changed(self):
        """Report a matrix reallocation to the governor."""
        if self.governor is not None and self._resident:
            self.governor.update(self, self.host_bytes())

    def memory_stats(self):
        """Where this fragment's bytes live, for the holder's
        ``/debug/memory`` rollup and the ``pilosa_memory_*`` gauges:
        packed uint64 block bytes resident on the host, device (HBM)
        mirror bytes (full matrix + per-row/plane/row-count memos),
        evicted-read memo bytes, roaring file bytes on disk, and the
        TopN row-cache entry count. Lock-free by design — gauges
        tolerate a racing mutation reading the pre-write snapshot, the
        same linearizability stance as win32()."""
        dev = 0
        d = self._dev
        if d is not None:
            dev += int(getattr(d, "nbytes", 0))
        rc = self._rc_dev
        if rc is not None:
            dev += int(getattr(rc[1], "nbytes", 0))
        for memo in list(self._row_dev.values()):
            dev += int(getattr(memo[1], "nbytes", 0))
        for memo in list(self._planes_cache.values()):
            dev += int(getattr(memo[1], "nbytes", 0))
        for memo in list(self._cont_dev.values()):
            dev += memo[1].device_bytes()
        resident = self._resident
        host = (int(self._matrix.nbytes + self._row_counts.nbytes)
                if resident else 0)
        try:
            disk = os.path.getsize(self.path)
        except OSError:
            disk = 0
        try:
            cache_n = len(self._cache)
        except TypeError:
            cache_n = 0
        return {
            "resident": resident,
            "hostBytes": host,
            "deviceBytes": dev,
            "lazyBytes": int(self.lazy_bytes()),
            "diskBytes": int(disk),
            "cacheEntries": cache_n,
            "containers": self.container_stats(),
        }

    def unload(self, blocking=True):
        """Drop host matrices and device mirrors; the roaring file +
        op log remain the durable source (every mutation is already on
        disk), so the next touch faults everything back in. Called by
        the host-memory governor on LRU eviction — with blocking=False
        there (a busy fragment is skipped, not waited on: the evictor
        may itself hold another fragment's lock, and blocking both ways
        would be an ABBA deadlock). Returns True when resident state
        was actually dropped, False when there was nothing to drop,
        None when the lock was contended under blocking=False."""
        if not blocking and self.mu.owned():
            # Re-entrant acquire would "succeed" and gut state an outer
            # frame of THIS thread is using.
            return None
        if not self.mu.acquire_raw(blocking=blocking):
            return None
        try:
            if not self._resident:
                # Evicted, but possibly holding lazy-read memos — the
                # governor charges those too (compressed containers
                # included: they are version-keyed and cheap to rebuild
                # from the file), so one eviction frees everything.
                if (self._lazy is None and not self._lazy_rows
                        and self._lazy_cache_ids is None
                        and not self._lazy_planes_bytes()
                        and not any(isinstance(k, tuple)
                                    for k in self._cont_dev)):
                    return False
                self._drop_lazy_locked()
            else:
                self._drop_lazy_locked()
                if self._op_file is not None:
                    # Release the append fd with the matrices; the next
                    # write reopens it (10k evicted fragments must not
                    # pin 10k descriptors).
                    self._op_file.close()
                    self._op_file = None
                if self._cache_loaded:
                    self._flush_cache_locked()
                self._cap = 0
                self._w64 = _MIN_W64
                self._w64_base = 0
                self._matrix = np.zeros((0, _MIN_W64), dtype=np.uint64)
                self._row_counts = np.zeros(0, dtype=np.int64)
                self._row_index = {}
                self._phys_rows = []
                self._dev = None
                self._dev_version = -1
                self._dirty = set()
                self._planes_cache = {}
                self._row_dev = {}
                self._rc_dev = None
                self._cont_dev = {}
                self._cont_fmt = {}
                self._resident = False
                # _version keeps counting across unload/reload so
                # executor stack-cache tokens never alias across the
                # gap.
                self._version += 1
                _bump_epoch(self.index)
        finally:
            self.mu.release_raw()
        if self.governor is not None:
            self.governor.update(self, 0)
        return True

    def replica_resync(self):
        """Replica-refresh invalidation (view.refresh_replica): drop
        every cached view of the file and advance the executor tokens.
        unload() alone is not enough — its non-resident branch drops
        lazy-read memos WITHOUT bumping ``_version``/epoch (governor
        evictions don't change file contents, so cached stacks stay
        valid there), but a replica resync means the MASTER's bytes
        moved underneath us and everything derived must go."""
        self.unload()
        with self.mu:
            self._version += 1
            _bump_epoch(self.index)

    # ------------------------------------------- evicted-read fast path

    def _drop_lazy_locked(self):
        """Invalidate the container-granular reader (file about to be
        rewritten/appended, the fragment is closing, or the governor
        is evicting this fragment's memos — compressed containers
        included; the reader-only MAX_LAZY_READERS eviction goes
        through ``_drop_reader`` instead)."""
        if self._lazy is not None:
            self._lazy.close()
            self._lazy = None
            _forget_reader(self)
        self._lazy_rows = {}
        self._lazy_bytes = 0
        self._lazy_cache_ids = None
        self._lazy_counts = {}
        if any(isinstance(k, tuple) and k and k[0] == "lazy"
               for k in self._planes_cache):
            self._planes_cache = {
                k: v for k, v in self._planes_cache.items()
                if not (isinstance(k, tuple) and k and k[0] == "lazy")}
        if any(isinstance(k, tuple) and k and k[0] == "lazy"
               for k in self._cont_dev):
            self._cont_dev = {
                k: v for k, v in self._cont_dev.items()
                if not (isinstance(k, tuple) and k and k[0] == "lazy")}
        if any(isinstance(k, tuple) and k and k[0] == "lazy"
               for k in self._cont_fmt):
            self._cont_fmt = {
                k: v for k, v in self._cont_fmt.items()
                if not (isinstance(k, tuple) and k and k[0] == "lazy")}

    def _drop_reader(self):
        """Release the mmap reader ONLY (MAX_LAZY_READERS eviction):
        containers, count memos, and block memos stay — the memo-first
        paths serve without the reader, and a miss recreates it.
        Returns False when the fragment lock was contended (reader
        still live; the caller re-queues it)."""
        if not self.mu.acquire_raw(blocking=False):
            return False
        try:
            if self._lazy is not None:
                self._lazy.close()
                self._lazy = None
        finally:
            self.mu.release_raw()
        return True

    def lazy_bytes(self):
        """Host bytes the evicted-read path holds — block memos, plane
        memos, count/cache-id memos, and a rough reader-header
        estimate — all charged to the governor so bounded residency
        stays bounded even for read-heavy workloads over evicted
        fragments."""
        reader = self._lazy
        overhead = 0
        if reader is not None:
            # Amortized snapshotting can leave multi-MB op tails; the
            # reader's parsed op index (per-key typ/bit arrays) is real
            # host memory and must count against the cap.
            overhead = len(reader.metas) * 64 + reader.op_index_bytes
        overhead += len(self._lazy_counts) * 64
        if self._lazy_cache_ids is not None:
            overhead += 32 + len(self._lazy_cache_ids) * 32
        overhead += self._lazy_planes_bytes()
        # Compressed containers built from lazy decodes: small
        # payloads, but governor-charged like every other lazy memo so
        # an evicted index's serving tier stays inside the budget.
        overhead += sum(v[1].nbytes()
                        for k, v in list(self._cont_dev.items())
                        if isinstance(k, tuple))
        return self._lazy_bytes + overhead

    def _lazy_planes_bytes(self):
        return sum(v[1].nbytes for k, v in self._planes_cache.items()
                   if isinstance(k, tuple) and k and k[0] == "lazy")

    def _lazy_serve(self, fn):
        """Serve one read from the container-granular reader when the
        fragment is open but evicted. Returns _NOT_LAZY when the
        fragment is resident (or unreadable lazily) — the caller then
        takes the normal resident path, which faults the matrix in.
        The whole serve runs under the raw lock (no fault-in), so a
        governor-evicted fragment answers row reads while holding only
        O(touched containers) host bytes — which are themselves
        governor-charged and evictable (unload drops them)."""
        if self._resident or not self._opened:
            return _NOT_LAZY  # cheap pre-check; verified under lock
        self.mu.acquire_raw()
        try:
            if self._resident or not self._opened:
                return _NOT_LAZY
            created = False
            if self._lazy is None:
                try:
                    self._lazy = codec.LazyReader(self.path)
                except (OSError, ValueError):
                    return _NOT_LAZY
                created = True
                # The reader parses the op log anyway; surface the
                # count so open()+read without a full fault-in still
                # reports op_n (snapshot-cadence monitors read it).
                self.op_n = self._lazy.op_n
            # LRU-bound the process-wide reader population (each mmap
            # pins a dup'd fd — see MAX_LAZY_READERS above).
            _note_reader(self)
            before = self.lazy_bytes()
            out = fn(self._lazy)
            changed = created or self.lazy_bytes() != before
            charge = self.host_bytes() if changed else None
        finally:
            self.mu.release_raw()
        if self.governor is not None:
            self.governor.touch(self)
            if charge is not None:
                # Only on actual growth/shrink: update() probes the
                # budget under a global lock — memo hits must not pay
                # that per row read.
                self.governor.update(self, charge)
        return out

    def _lazy_row_blocks(self, reader, row_id):
        """{sub: uint64[1024]} populated containers for one row,
        decoded from O(row) containers and memoized (8 KB per block —
        proportional to the data actually touched, never full row
        width)."""
        memo = self._lazy_rows.get(row_id)
        qs = querystats.active()
        if memo is not None:
            if qs is not None:
                qs.add("cacheHits", 1)
            return memo
        if qs is not None:
            qs.add("cacheMisses", 1)
        blocks = {}
        base_key = row_id * _CONTAINERS_PER_ROW
        for sub in range(_CONTAINERS_PER_ROW):
            block = reader.container(base_key + sub)
            if block is not None:
                blocks[sub] = block
        if len(self._lazy_rows) >= 16:
            # Evict the OLDEST single memo (dict preserves insertion
            # order) — clearing everything would re-decode the whole
            # working set each pass for 17+-row cycles.
            old = self._lazy_rows.pop(next(iter(self._lazy_rows)))
            self._lazy_bytes -= sum(b.nbytes for b in old.values())
        self._lazy_rows[row_id] = blocks
        self._lazy_bytes += sum(b.nbytes for b in blocks.values())
        return blocks

    @staticmethod
    def _blit_block(dst, block, sub, b64, w64):
        """Copy container ``sub``'s overlap with the word span
        [b64, b64+w64) into ``dst`` (uint64[w64]) — the ONE copy of
        the container→span window math, shared by the lazy row and
        lazy plane assemblies."""
        cbase = sub * _WORDS64_PER_CONTAINER
        lo = max(cbase, b64)
        hi = min(cbase + _WORDS64_PER_CONTAINER, b64 + w64)
        if lo < hi:
            dst[lo - b64 : hi - b64] = block[lo - cbase : hi - cbase]

    def _lazy_row64_span(self, reader, row_id, b64, w64):
        """uint64[w64] host row span [b64, b64+w64) assembled from the
        row's populated container blocks."""
        row = np.zeros(w64, dtype=np.uint64)
        for sub, block in self._lazy_row_blocks(reader, row_id).items():
            self._blit_block(row, block, sub, b64, w64)
        return row

    def cache_entry_ids(self):
        """TopN candidate row ids (cache membership) WITHOUT forcing
        residency: the loaded cache when resident (snapshotted under
        the fragment lock — concurrent imports mutate the dict), else
        the memoized sidecar ids through the lazy path. Batched TopN
        phase 1 reads this for every fragment of a slice list; going
        through the ``cache`` property would fault each one in."""
        from pilosa_tpu.storage.cache import NopCache

        if isinstance(self._cache, NopCache):
            return frozenset()
        if not self._resident and self._opened:
            # Unlike _lazy_serve this never constructs the container
            # reader — the candidate ids come from the JSON sidecar
            # (or the already-loaded cache), so an all-empty phase 1
            # over a cold slice list costs no header parses.
            self.mu.acquire_raw()
            try:
                if not self._resident and self._opened:
                    fresh = (self._lazy_cache_ids is None
                             and not self._cache_loaded)
                    out = frozenset(self._lazy_cache_ids_locked())
                else:
                    fresh, out = False, None
            finally:
                self.mu.release_raw()
            if out is not None:
                if self.governor is not None:
                    # Touch on EVERY read (LRU recency — a hot TopN
                    # candidate list must not age to the tail and get
                    # its sidecar memo evicted each cycle); charge
                    # only on first load.
                    self.governor.touch(self)
                    if fresh:
                        self.governor.update(self, self.host_bytes())
                return out
        with self.mu:
            return frozenset(self.cache.entries)

    def _lazy_cache_ids_locked(self):
        if self._cache_loaded:
            return list(self._cache.entries)
        ids = self._lazy_cache_ids
        if ids is None:
            try:
                with open(self.cache_path) as f:
                    ids = json.load(f)
            except (OSError, ValueError):
                ids = []
            self._lazy_cache_ids = ids
        return ids

    def _lazy_top(self, reader, opt):
        """Src-less TopN on an evicted fragment: candidate ids from
        the loaded cache or its sidecar, exact counts from header
        cardinalities (+ op-touched container decodes) — same
        semantics as the resident walk in top(), zero fault-in."""
        from pilosa_tpu.storage.cache import NopCache

        if opt.row_ids is not None:
            allowed = set(opt.row_ids)
        else:
            if isinstance(self._cache, NopCache):
                return []
            allowed = set(self._lazy_cache_ids_locked())
        if opt.filter_row_ids is not None:
            allowed &= set(opt.filter_row_ids)
        pairs = []
        for rid in allowed:
            cnt = self._lazy_row_count(reader, rid)
            if cnt <= 0 or cnt < opt.min_threshold:
                continue
            pairs.append((int(rid), int(cnt)))
        pairs.sort(key=lambda rc: (-rc[1], rc[0]))
        if opt.n and opt.row_ids is None:
            pairs = pairs[: opt.n]
        return pairs

    def _lazy_planes(self, reader, depth, base32, width32):
        """Windowed BSI plane matrix from lazy row decodes, memoized
        in _planes_cache exactly like the resident build (the version
        is stable while the reader lives — file immutable)."""
        key = ("lazy", depth, base32, width32)
        cached = self._planes_cache.get(key)
        if cached and cached[0] == self._version:
            return cached[1]
        b64, w64 = base32 // 2, width32 // 2
        mat = np.zeros((depth + 1, w64), dtype=np.uint64)
        # Decode containers directly — routing 20+ plane rows through
        # the 16-entry shared row memo would cycle it every build and
        # flush the memos concurrent Count/TopN lazy reads rely on.
        for i in range(depth + 1):
            base_key = i * _CONTAINERS_PER_ROW
            for sub in range(_CONTAINERS_PER_ROW):
                block = reader.container(base_key + sub)
                if block is not None:
                    self._blit_block(mat[i], block, sub, b64, w64)
        planes = jnp.asarray(mat.view(np.uint32))
        self._planes_cache = {key: (self._version, planes)}
        return planes

    def _lazy_win32(self, reader):
        """Column window from container SPANS, not just keys: the
        header alone bounds each key to its whole 1,024-word container,
        which for clustered data over-covers by up to 16x — at
        10k-slice scale that inflated every device stack and the fused
        kernels' compute by the same factor (measured 53 ms vs 3 ms per
        10B-col Count on the CPU backend). word_span peeks 4 bytes for
        sorted array/run payloads and scans bitmap containers' own 8 KB
        once, so the bound is word-exact for the outermost containers;
        interior containers never affect the window."""
        keys = reader.keys()
        if not keys:
            return None
        by_sub = {}
        for k in keys:
            by_sub.setdefault(k % _CONTAINERS_PER_ROW, []).append(k)

        def edge(reverse, pick, side):
            # First sub (in the given direction) with any non-empty
            # span holds that edge of the global window.
            for sub in sorted(by_sub, reverse=reverse):
                spans = [s for s in (reader.word_span(k)
                                     for k in by_sub[sub])
                         if s is not None]
                if spans:
                    return sub * _WORDS64_PER_CONTAINER + pick(
                        s[side] for s in spans)
            return None

        lo = edge(False, min, 0)
        if lo is None:
            return None
        hi = edge(True, max, 1)
        w = _MIN_W64
        while True:
            b = lo // w * w
            if hi < b + w or w >= WORDS64:
                break
            w *= 2
        if w >= WORDS64:
            return 0, WORDS_PER_SLICE
        return b * 2, w * 2

    def close(self):
        self.mu.acquire_raw()
        try:
            _bump_epoch(self.index)  # this object stops being servable
            # Advance the executor stack-cache token too (same
            # discipline as unload/_reset_storage): after a
            # close()+open() recovery cycle the next read must fault
            # in from disk — the durable prefix may differ from the
            # device mirrors a pre-close stack cached (fail-stop
            # rollback, external repair, quarantine).
            self._version += 1
            self._drop_lazy_locked()
            if self._cache_loaded:
                self._flush_cache_locked()
            if self._op_file:
                self._op_file.close()
                self._op_file = None
            if self._lock_file:
                self._lock_file.close()
                self._lock_file = None
            self._opened = False
            self._resident = False
            self._matrix = np.zeros((0, _MIN_W64), dtype=np.uint64)
            self._row_counts = np.zeros(0, dtype=np.int64)
            self._row_index = {}
            self._phys_rows = []
            self._cap = 0
            self._w64 = _MIN_W64
            self._w64_base = 0
            self._dev = None
            self._planes_cache = {}
            self._row_dev = {}
            self._rc_dev = None
            self._cont_dev = {}
            self._cont_fmt = {}
        finally:
            self.mu.release_raw()
        if self.governor is not None:
            self.governor.update(self, 0)

    def _load_blocks(self, blocks):
        rows = sorted({key // _CONTAINERS_PER_ROW for key in blocks})
        # One pass for the global word span (so the window is sized and
        # placed once, not re-grown per block), one pass to fill.
        spans = {}
        lo_w = hi_w = None
        for key, block in blocks.items():
            nz = np.flatnonzero(block)
            if len(nz) == 0:
                continue
            spans[key] = (int(nz.min()), int(nz.max()))
            cbase = (key % _CONTAINERS_PER_ROW) * _WORDS64_PER_CONTAINER
            glo, ghi = cbase + spans[key][0], cbase + spans[key][1]
            lo_w = glo if lo_w is None else min(lo_w, glo)
            hi_w = ghi if hi_w is None else max(hi_w, ghi)
        if lo_w is not None:
            self._ensure_window(lo_w, hi_w)
        base = self._w64_base
        for row_id in rows:
            phys = self._ensure_row(row_id)
            for sub in range(_CONTAINERS_PER_ROW):
                key = row_id * _CONTAINERS_PER_ROW + sub
                if key in spans:
                    lo, hi = spans[key]
                    dst = sub * _WORDS64_PER_CONTAINER + lo - base
                    self._matrix[phys, dst : dst + hi - lo + 1] = (
                        blocks[key][lo : hi + 1])
        if len(self._phys_rows):
            self._recount_rows(range(len(self._phys_rows)))
        self._version += 1
        _bump_epoch(self.index)
        self._dirty.update(range(len(self._phys_rows)))

    def _to_arrays(self):
        """(sorted uint64[n] container keys, uint64[n, 1024] blocks) —
        one vectorized nonzero-container scan + one gather."""
        n = len(self._phys_rows)
        if n == 0:
            return (np.zeros(0, dtype=np.uint64),
                    np.zeros((0, _WORDS64_PER_CONTAINER), dtype=np.uint64))
        w = self._w64
        base = self._w64_base
        if w >= _WORDS64_PER_CONTAINER:
            # base is a multiple of w ≥ 1024, hence container-aligned.
            c0 = base // _WORDS64_PER_CONTAINER
            tiled = self._matrix[:n].reshape(
                n, w // _WORDS64_PER_CONTAINER, _WORDS64_PER_CONTAINER)
        else:
            # A sub-container window lies inside ONE container (base is
            # w-aligned and w divides 1024): pad only the PRESENT rows'
            # blocks, not the whole matrix.
            tiled = None
        if tiled is not None:
            present = tiled.any(axis=2)
            phys_idx, sub_idx = np.nonzero(present)
            row_ids = np.asarray(self._phys_rows, dtype=np.uint64)
            keys = (row_ids[phys_idx] * _CONTAINERS_PER_ROW
                    + (sub_idx + c0).astype(np.uint64))
            order = np.argsort(keys, kind="stable")  # phys != key order
            return keys[order], tiled[phys_idx[order], sub_idx[order]]
        present = self._matrix[:n].any(axis=1)
        phys_idx = np.flatnonzero(present)
        row_ids = np.asarray(self._phys_rows, dtype=np.uint64)
        c0 = base // _WORDS64_PER_CONTAINER
        off = base - c0 * _WORDS64_PER_CONTAINER
        keys = (row_ids[phys_idx] * _CONTAINERS_PER_ROW
                + np.uint64(c0))
        order = np.argsort(keys, kind="stable")
        if off == 0:
            # Container-aligned narrow window: hand the serializer the
            # NARROW rows directly (words beyond the width implicitly
            # zero) — zero-padding every container to 1024 words made
            # the snapshot scan up to 16× the data's actual bytes, the
            # dominant bulk-load cost on row-heavy narrow fragments.
            return keys[order], np.ascontiguousarray(
                self._matrix[:n][phys_idx[order]])
        blocks = np.zeros((len(phys_idx), _WORDS64_PER_CONTAINER),
                          dtype=np.uint64)
        blocks[:, off : off + w] = self._matrix[:n][phys_idx[order]]
        return keys[order], blocks

    def _acquire_lock(self):
        """Guard against two processes opening the same fragment
        (ref: syscall.Flock fragment.go:203-205). The lock lives on a
        sidecar ``.lock`` file whose fd stays open for the fragment's
        whole lifetime, so snapshot()/read_from() can freely close and
        reopen the data file without a release→reacquire window.

        Fragments under a HOLDER-level lock hold no per-file fd: one
        flock fd per fragment exhausted RLIMIT_NOFILE (20k here) at
        10B-column scale — ~9.5k lock fds per holder for a guard one
        directory-level flock provides (holder.py registers the root).
        Mixed-era safety, both directions, via TRANSIENT probes (no
        held fd): under a locked root we still probe our own ``.lock``
        so a standalone tool/old binary holding it is refused; outside
        any locked root we probe an enclosing ``.holder.lock`` so a
        running holder process refuses us."""
        me = os.path.abspath(self.path)
        if any(me.startswith(root) for root in _LOCKED_ROOTS):
            # Our process's holder owns the tree; refuse if some OTHER
            # process still holds this fragment's per-file lock. Probe
            # only when a .lock file exists (probing would otherwise
            # recreate the files this path exists to avoid).
            if os.path.exists(self.path + ".lock"):
                try_flock(self.path + ".lock", perr.ErrFragmentLocked,
                          transient=True)
            return
        # Standalone open: if an enclosing holder (this or another
        # process... but ours would be in _LOCKED_ROOTS) holds the
        # directory lock, the probe fails — refuse rather than write
        # under a live holder. Fragment paths sit ≤ 5 levels below
        # the holder root (<root>/<index>/<frame>/views/<view>/
        # fragments/<slice>).
        d = os.path.dirname(me)
        for _ in range(6):
            marker = os.path.join(d, HOLDER_LOCK_NAME)
            if os.path.exists(marker):
                try_flock(marker, perr.ErrFragmentLocked, transient=True)
                break
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent
        self._lock_file = try_flock(self.path + ".lock",
                                    perr.ErrFragmentLocked)

    def snapshot(self):
        """Atomic full rewrite + op-log reset (ref: fragment.go:1393-1438;
        duration histogram per track() :1387-1392).

        Failure contract: the temp-file + rename design makes a failed
        snapshot ATOMIC — the previous on-disk file (snapshot + op
        tail) is untouched and remains the durable source. On
        ENOSPC/EIO (or the ``fragment.snapshot.rename`` failpoint) the
        debris is removed, ``pilosa_snapshot_failed_total`` counts it,
        and the OSError propagates: housekeeping callers swallow it
        (the triggering write is already in the op log), while import
        paths whose durability DEPENDS on this snapshot fail-stop."""
        if REPLICA or self._failed is not None:
            return
        with stats_mod.Timer(self.stats, "SnapshotDurationSeconds"), \
                self.mu:
            self._drop_lazy_locked()  # file is about to be rewritten
            data = codec.serialize_arrays(*self._to_arrays())
            tmp = self.path + ".snapshotting"
            try:
                with open(tmp, "wb") as f:
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
                if faults.ACTIVE.enabled:
                    faults.ACTIVE.fire("fragment.snapshot.rename")
                if self._op_file:
                    self._op_file.close()
                    self._op_file = None
                os.replace(tmp, self.path)
            except OSError:
                self.stats.count("snapshot_failed_total", 1)
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
            self.op_n = 0
            self._snap_card = int(self._row_counts.sum())

    def _op_log_room(self, extra):
        """True while appending ``extra`` more ops beats snapshotting
        (see OPLOG_MAX_OPS above). Callers hold ``self.mu``;
        ``_snap_card`` is set by snapshot()/read_from() and back-filled
        at fault-in (every mutation faults in first)."""
        if self._snap_card is None:
            # Fault-in back-fills this before any mutation can reach a
            # gate; a still-unset value here means an exotic path, so
            # be conservative (reference cadence) rather than derive a
            # threshold from a post-mutation cardinality.
            self._snap_card = 0
        limit = max(MAX_OPN, min(self._snap_card // 2, OPLOG_MAX_OPS))
        return self.op_n + extra <= limit

    def _open_cache(self):
        """Restore the TopN cache sidecar (ref: fragment.go:250-289);
        counts are recomputed from storage, the sidecar only carries ids."""
        if not os.path.exists(self.cache_path):
            return
        try:
            with open(self.cache_path) as f:
                ids = json.load(f)
        except (ValueError, OSError):
            return
        for row_id in ids:
            phys = self._row_index.get(row_id)
            if phys is not None:
                self.cache.bulk_add(row_id, int(self._row_counts[phys]))
        self.cache.invalidate()

    def flush_cache(self):
        # Raw lock: flushing the sidecar of an evicted/never-touched
        # fragment must not fault its whole matrix back in (the
        # periodic holder cache-flush monitor walks EVERY fragment —
        # reloading each would defeat the host-bytes budget).
        self.mu.acquire_raw()
        try:
            if self._cache_loaded:
                self._flush_cache_locked()
        finally:
            self.mu.release_raw()

    def _flush_cache_locked(self):
        if REPLICA:
            return
        with open(self.cache_path, "w") as f:
            json.dump(self._cache.ids(), f)

    def recalculate_cache(self):
        """Rebuild the TopN cache from storage counts — recovers ranked
        TopN after a crash lost the cache sidecar (ref: Cache.
        Recalculate via handleRecalculateCaches handler.go:2016)."""
        with self.mu:
            for phys, row_id in enumerate(self._phys_rows):
                n = int(self._row_counts[phys])
                if n:
                    self.cache.bulk_add(row_id, n)
            self.cache.invalidate()

    # ------------------------------------------------------- row plumbing

    def _ensure_row(self, row_id):
        phys = self._row_index.get(row_id)
        if phys is not None:
            return phys
        n = len(self._phys_rows)
        if n >= self._cap:
            self._grow_rows_locked(n + 1)
        self._row_index[row_id] = n
        self._phys_rows.append(row_id)
        self.max_row_id = max(self.max_row_id, row_id)
        return n

    def _grow_rows_locked(self, need):
        """Grow row capacity (powers of two) to hold ``need`` physical
        rows — THE one copy of the matrix/counts reallocation (bulk
        installs pre-grow once instead of doubling per row). Caller
        holds ``self.mu``."""
        if need <= self._cap:
            return
        new_cap = max(8, self._cap or 8)
        while new_cap < need:
            new_cap *= 2
        grown = np.zeros((new_cap, self._w64), dtype=np.uint64)
        grown[: self._cap] = self._matrix
        self._matrix = grown
        counts = np.zeros(new_cap, dtype=np.int64)
        counts[: self._cap] = self._row_counts
        self._row_counts = counts
        self._cap = new_cap
        self._dev = None  # shape changed; full re-upload
        self._mem_changed()

    def _ensure_window(self, lo_word, hi_word):
        """Grow (or, while still empty, relocate) the column window to
        cover global 64-bit word indices [lo_word, hi_word]. Width is a
        power of two and the base stays width-aligned, so an all-zero
        fragment whose first data lands in high containers allocates
        only its cluster's width — never the full slice."""
        base, w = self._w64_base, self._w64
        if base <= lo_word and hi_word < base + w:
            return
        if self._cap and self._matrix.any():
            # Existing data pins the current window inside the new one.
            lo_word = min(lo_word, base)
            hi_word = max(hi_word, base + w - 1)
            w2 = w
        else:
            w2 = _MIN_W64
        while True:
            b2 = lo_word // w2 * w2
            if hi_word < b2 + w2 or w2 >= WORDS64:
                break
            w2 *= 2
        if w2 >= WORDS64:
            w2, b2 = WORDS64, 0
        grown = np.zeros((self._cap, w2), dtype=np.uint64)
        if self._cap and self._matrix.any():
            off = base - b2
            grown[:, off : off + w] = self._matrix
        self._matrix = grown
        self._w64 = w2
        self._w64_base = b2
        self._dev = None          # device mirror shape changed
        self._row_dev.clear()
        self._planes_cache = {}
        self._mem_changed()

    def _recount_rows(self, phys_iter):
        idx = list(phys_iter)
        if not idx:
            return
        counts = native.popcount_rows(self._matrix, idx)
        if counts is None:
            counts = np.bitwise_count(self._matrix[idx]).sum(
                axis=-1, dtype=np.int64)
        self._row_counts[idx] = counts

    def rows(self):
        """Row ids present in storage. Served from container keys on
        an EVICTED fragment (no fault-in); a resident-allocated row
        whose bits were all cleared before the last snapshot is
        omitted there — observably equivalent, since zero-bit rows
        contribute nothing to any consumer (export, TopN walks,
        iteration)."""
        lazy = self._lazy_serve(self._lazy_row_ids)
        if lazy is not _NOT_LAZY:
            return lazy
        with self.mu:
            return sorted(self._row_index)

    def _lazy_row_count(self, reader, row_id):
        """Exact count for one row on an evicted fragment, memoized —
        TopN cache walks re-read the same rows every query, and 16
        header lookups per row per call is Python-loop-bound at
        1,000-slice scale."""
        cnt = self._lazy_counts.get(row_id)
        if cnt is None:
            cnt = sum(
                reader.cardinality(row_id * _CONTAINERS_PER_ROW + sub)
                for sub in range(_CONTAINERS_PER_ROW))
            # FIFO-evict one (never clear-all: a wipe would recompute
            # ~the whole working set every query for big caches). The
            # bound covers the reference's 50k default cache size.
            while len(self._lazy_counts) >= 65536:
                self._lazy_counts.pop(next(iter(self._lazy_counts)))
            self._lazy_counts[row_id] = cnt
        return cnt

    def row_count(self, row_id):
        lazy = self._lazy_serve(
            lambda r: self._lazy_row_count(r, row_id))
        if lazy is not _NOT_LAZY:
            return lazy
        with self.mu:
            phys = self._row_index.get(row_id)
            return int(self._row_counts[phys]) if phys is not None else 0

    def row_words(self, row_id):
        """Host uint64[WORDS64] for one row (zero if absent, padded to
        full slice width). The analog of Fragment.row's OffsetRange
        extraction (fragment.go:355-384)."""
        querystats.add("blocks", 1)
        hm = heatmap_mod.ACTIVE
        if hm.enabled:
            hm.touch_read(self.index, self.frame, row_id, self.slice,
                          weight=WORDS64 * 8)
        lazy = self._lazy_serve(
            lambda r: self._lazy_row64_span(r, row_id, 0, WORDS64))
        if lazy is not _NOT_LAZY:
            return lazy
        with self.mu:
            phys = self._row_index.get(row_id)
            if phys is None:
                return np.zeros(WORDS64, dtype=np.uint64)
            if self._w64 == WORDS64:
                return self._matrix[phys]
            out = np.zeros(WORDS64, dtype=np.uint64)
            base = self._w64_base
            out[base : base + self._w64] = self._matrix[phys]
            return out

    # ------------------------------------------- compressed serving tier

    def row_container(self, row_id):
        """``containers.Container`` for one row at FULL slice width —
        the compressed serving tier. The per-row format is chosen from
        the density stats the fragment already keeps (``_row_counts``
        plus one vectorized run scan), the roaring thresholds verbatim
        (containers.choose_format): ≤4096 set bits → sorted-position
        ARRAY, few long runs → RUN, else the existing DENSE device
        mirror wrapped with its (host-known) cardinality. ARRAY/RUN
        containers memoize per (phys, version); a mutation bumps
        ``_version`` and the next read rebuilds — when the rebuild
        lands in a different format, that's a conversion
        (``pilosa_container_conversions_total``).

        EVICTED fragments classify from the lazy row decode: compressed
        results memoize (tiny payloads — the 100B-scale case is exactly
        an evicted-host, compressed-device index), dense rows re-wrap
        per call like the existing lazy device_row path."""
        from pilosa_tpu.ops import containers

        hm = heatmap_mod.ACTIVE
        if hm.enabled:
            hm.touch_read(self.index, self.frame, row_id, self.slice)

        if not self._resident and self._opened:
            # Memo-first, BEFORE _lazy_serve: a warm compressed tier
            # must serve without recreating the mmap reader (each
            # reader pins a dup'd fd — the resource that bounds
            # resident fragments at 100B scale). Lock-free racy read,
            # version-keyed like win32().
            memo = self._cont_dev.get(("lazy", row_id))
            if memo is not None and memo[0] == self._version:
                if self.governor is not None:
                    # Lock-free recency stamp: without it the HOTTEST
                    # compressed fragments would keep their stalest
                    # stamps (only _lazy_serve touches) and be evicted
                    # FIRST under budget pressure — LRU inversion
                    # thrashing the warm tier.
                    self.governor.touch(self)
                querystats.add("blocks", 1)
                querystats.add("containerBlocks"
                               + memo[1].fmt.capitalize(), 1)
                return memo[1]
            out = self._lazy_serve(
                lambda r: self._lazy_container(r, row_id, containers))
            if out is not _NOT_LAZY:
                querystats.add("blocks", 1)
                querystats.add("containerBlocks"
                               + out.fmt.capitalize(), 1)
                return out
        with self.mu:
            phys = self._row_index.get(row_id)
            if phys is None:
                querystats.add("blocks", 1)
                querystats.add("containerBlocksArray", 1)
                return containers.empty_container(WORDS_PER_SLICE)
            memo = self._cont_dev.get(phys)
            if memo is not None and memo[0] == self._version:
                querystats.add("blocks", 1)
                querystats.add("containerBlocks"
                               + memo[1].fmt.capitalize(), 1)
                return memo[1]
            fm = self._cont_fmt.get(phys)
            if fm is not None and fm == (self._version, bitops.FMT_DENSE):
                # Classified DENSE at this version already: skip the
                # run scan and wrap the existing device mirror — a
                # repeated serial-path read of a hot dense row must
                # stay a dict-hit + wrap, not a window re-scan
                # (device_row_win charges this read's "blocks").
                row_id = self._phys_rows[phys]
                cont = containers.dense_container(
                    self.device_row_win(row_id, 0, WORDS_PER_SLICE),
                    WORDS_PER_SLICE, int(self._row_counts[phys]))
                querystats.add("containerBlocksDense", 1)
                return cont
            cont = self._build_container_locked(phys, containers)
            if cont.fmt != bitops.FMT_DENSE:
                # The dense branch's device_row_win already charged
                # this read's "blocks" — formats on/off must report
                # identical block counts for the same query.
                querystats.add("blocks", 1)
            if fm is not None and fm[1] != cont.fmt:
                self._conversions += 1
                containers.note_conversion()
                self.stats.count("container_conversions_total", 1)
                if hm.enabled:
                    hm.note_conversion(self.index, self.frame)
            self._cont_fmt[phys] = (self._version, cont.fmt)
            if cont.fmt != bitops.FMT_DENSE:
                self._memo_container(phys, cont)
            querystats.add("containerBlocks" + cont.fmt.capitalize(), 1)
            return cont

    def _lazy_container(self, reader, row_id, containers):
        """Container for one row of an EVICTED fragment, classified
        from the lazy container decode — a sparse row costs one
        transient 128 KB host assembly and then lives as its compressed
        payload. Only compressed results memoize (a dense wrap would
        pin a 128 KB device row per entry; the dense lazy path already
        re-uploads per call, backed by the _lazy_rows decode memo)."""
        key = ("lazy", row_id)
        memo = self._cont_dev.get(key)
        if memo is not None and memo[0] == self._version:
            return memo[1]
        words = self._lazy_row64_span(reader, row_id, 0, WORDS64)
        fm = self._cont_fmt.get(key)
        if fm is not None and fm == (self._version, bitops.FMT_DENSE):
            # Classified DENSE at this version already: skip the
            # popcount + run scan and wrap the assembled words — a
            # repeated read of a hot dense evicted row then pays only
            # what the formats-off lazy path pays (assembly + upload),
            # with the count from the evicted-read memo when present.
            cnt = self._lazy_counts.get(row_id)
            if cnt is None:
                cnt = int(np.bitwise_count(
                    np.ascontiguousarray(words, np.uint64)).sum())
            import jax.numpy as jnp

            return containers.dense_container(
                jnp.asarray(np.ascontiguousarray(
                    words, np.uint64).view(np.uint32)),
                WORDS_PER_SLICE, cnt)
        cont = containers.build_container(words, WORDS_PER_SLICE)
        if fm is not None and fm[1] != cont.fmt:
            self._conversions += 1
            containers.note_conversion()
            self.stats.count("container_conversions_total", 1)
            hm = heatmap_mod.ACTIVE
            if hm.enabled:
                hm.note_conversion(self.index, self.frame)
        self._cont_fmt[key] = (self._version, cont.fmt)
        if cont.fmt != bitops.FMT_DENSE:
            self._memo_container(key, cont)
        return cont

    def _memo_container(self, key, cont):
        """Memoize a compressed container, oldest-evicting one entry
        past the cap (insertion order) — payloads are small, but the
        tier must not grow unbounded under row churn."""
        if len(self._cont_dev) >= 8192:
            self._cont_dev.pop(next(iter(self._cont_dev)))
        self._cont_dev[key] = (self._version, cont)

    def row_compressed(self, row_id):
        """Cheap probe: should this row be served from the compressed
        tier rather than staged into a dense device stack? True only
        for an EVICTED fragment whose row passes the density check
        (count ≤ ARRAY_MAX_BITS, or absent) — the 100B-scale shape,
        where the host matrix is cold and re-densifying rows into HBM
        stacks is exactly the memory cliff the container tier removes.
        Resident (hot) fragments keep the fused batched path: their
        dense mirrors are already paid for and budget-bounded. A
        dense-count row the run scan would still compress (all-full)
        reads as dense here — that only routes it to the batched dense
        path, never changes results."""
        from pilosa_tpu.ops import containers

        if not containers.enabled():
            return False
        if self._resident or not self._opened:
            return False
        # Memo-first: a warm compressed tier answers the probe from
        # the served container's own format without touching the
        # (possibly evicted) reader.
        memo = self._cont_dev.get(("lazy", row_id))
        if memo is not None and memo[0] == self._version:
            return memo[1].fmt != bitops.FMT_DENSE
        return self.row_count(row_id) <= containers.ARRAY_MAX_BITS

    def row_format_probe(self, row_id):
        """Read-only classification guess for one row — "dense",
        "array" or "run" — for the query inspector's per-leaf format
        mix and the cost model's cell selection. Answers from the
        serving memos when warm (exact), else from the density stats
        (count ≤ ARRAY_MAX_BITS → array; the run/array distinction
        needs a scan the probe refuses to pay). Never builds a
        container and never writes a serving memo — the explain-only
        contract. Lock-free racy reads, version-keyed like
        container_stats."""
        from pilosa_tpu.ops import containers

        if not containers.enabled():
            return bitops.FMT_DENSE
        version = self._version
        if not self._resident and self._opened:
            memo = self._cont_dev.get(("lazy", row_id))
            if memo is not None and memo[0] == version:
                return memo[1].fmt
            fm = self._cont_fmt.get(("lazy", row_id))
            if fm is not None and fm[0] == version:
                return fm[1]
            return (bitops.FMT_ARRAY
                    if self.row_count(row_id) <= containers.ARRAY_MAX_BITS
                    else bitops.FMT_DENSE)
        phys = self._row_index.get(row_id)
        if phys is None:
            return bitops.FMT_ARRAY  # absent rows serve empty arrays
        memo = self._cont_dev.get(phys)
        if memo is not None and memo[0] == version:
            return memo[1].fmt
        fm = self._cont_fmt.get(phys)
        if fm is not None and fm[0] == version:
            return fm[1]
        # Resident, unclassified: the batched/dense mirror serves it.
        return bitops.FMT_DENSE

    def _build_container_locked(self, phys, containers):
        """Classify + build one row's container from its window words
        via the ONE shared pipeline (containers.build_container):
        positions/runs rebase by the window offset to slice-global bit
        coordinates so the container is window-agnostic, and the dense
        outcome wraps the existing device mirror instead of
        re-uploading. Caller holds ``self.mu``."""
        row_id = self._phys_rows[phys]
        return containers.build_container(
            self._matrix[phys], WORDS_PER_SLICE,
            count=int(self._row_counts[phys]),
            offset=self._w64_base * 64,
            dense_fn=lambda: self.device_row_win(
                row_id, 0, WORDS_PER_SLICE))

    def container_stats(self):
        """Per-format snapshot of the compressed serving tier: block
        counts + resident payload bytes by format, the bytes the dense
        tier would hold for those same blocks (this fragment's window
        width — dense rows already page to their window), and the
        conversion count. Lock-free like memory_stats: gauges tolerate
        a racing mutation's pre-write snapshot."""
        out = {bitops.FMT_DENSE: {"blocks": 0, "bytes": 0},
               bitops.FMT_ARRAY: {"blocks": 0, "bytes": 0},
               bitops.FMT_RUN: {"blocks": 0, "bytes": 0}}
        dense_row_bytes = 2 * self._w64 * 4
        equiv = 0
        version = self._version
        for key, memo in list(self._cont_dev.items()):
            if memo[0] != version:
                continue
            c = memo[1]
            out[c.fmt]["blocks"] += 1
            out[c.fmt]["bytes"] += c.nbytes()
            # Resident rows' dense equivalent is this fragment's
            # window width (the dense tier pages rows to it); evicted
            # ("lazy"-keyed) rows would densify at full container
            # width, which is what the wrap charges.
            equiv += (c.dense_equiv_bytes() if isinstance(key, tuple)
                      else dense_row_bytes)
        for key, (ver, fmt) in list(self._cont_fmt.items()):
            if fmt == bitops.FMT_DENSE and ver == version:
                # Resident dense rows page to this fragment's window;
                # evicted ("lazy"-keyed) dense rows serve full-width
                # uploads per call.
                b = (WORDS_PER_SLICE * 4 if isinstance(key, tuple)
                     else dense_row_bytes)
                out[fmt]["blocks"] += 1
                out[fmt]["bytes"] += b
                equiv += b
        return {"formats": out, "denseEquivBytes": equiv,
                "conversions": self._conversions}

    # ------------------------------------------------------ device mirror

    def win32(self):
        """Current column window as (base, width) in uint32 device
        words, or None when the fragment holds no rows. Executors union
        these across a plan's fragments to size device stacks to the
        data instead of the full 32,768-word slice (the HBM analog of
        the reference's containers never materializing empty space,
        roaring.go:1011-1024).

        Version-keyed memo, read without the lock: batched executors
        call this once per (fragment, query) — 954 locked window
        computations per query measured as ~half of a billion-column
        count's latency. A racing mutation serves the consistent
        pre-write snapshot (same linearizability as the stack caches'
        token race)."""
        memo = self._win32_memo
        if memo is not None and memo[0] == self._version:
            return memo[1]
        version = self._version
        lazy = self._lazy_serve(self._lazy_win32)
        if lazy is not _NOT_LAZY:
            self._win32_memo = (version, lazy)
            return lazy
        with self.mu:
            val = ((self._w64_base * 2, self._w64 * 2)
                   if self._row_index else None)
            self._win32_memo = (self._version, val)
            return val

    def device_matrix(self):
        """uint32[cap, 2·width] HBM copy, refreshed lazily — NARROW
        when the fragment is (width ≤ 32768 device words); callers must
        trim full-slice operands to match, as top() does."""
        with self.mu:
            if self._cap == 0:
                return jnp.zeros((0, WORDS_PER_SLICE), dtype=jnp.uint32)
            qs = querystats.active()
            obs = kerneltime_mod.ACTIVE
            if (self._dev is None or self._dev.shape[0] != self._cap
                    or self._dev.shape[1] != 2 * self._w64):
                t0 = time.perf_counter()
                with tracing.span("fragment.device_put", rows=self._cap,
                                  words32=2 * self._w64, slice=self.slice):
                    self._dev = jnp.asarray(self._matrix.view(np.uint32))
                self._dirty.clear()
                if qs is not None:
                    qs.add("deviceTransfers", 1)
                    qs.add("deviceTransferBytes",
                           int(self._matrix.nbytes))
                if obs.enabled:
                    obs.note_transfer(int(self._matrix.nbytes),
                                      time.perf_counter() - t0)
            elif self._dev_version != self._version and self._dirty:
                idx = sorted(self._dirty)
                t0 = time.perf_counter()
                with tracing.span("fragment.device_update",
                                  rows=len(idx), slice=self.slice):
                    vals = jnp.asarray(self._matrix[idx].view(np.uint32))
                    self._dev = self._dev.at[jnp.asarray(idx)].set(vals)
                self._dirty.clear()
                if qs is not None:
                    qs.add("deviceTransfers", 1)
                    qs.add("deviceTransferBytes",
                           len(idx) * 2 * self._w64 * 8)
                if obs.enabled:
                    obs.note_transfer(len(idx) * 2 * self._w64 * 8,
                                      time.perf_counter() - t0)
            self._dev_version = self._version
            return self._dev

    def _row_counts_device(self, n_phys):
        """Device copy of the per-row cardinalities, memoized against
        the mutation version — the Tanimoto denominator reads it every
        query and a per-query upload costs a relay round trip. The
        version check subsumes every invalidation site (any mutation
        bumps ``_version``); callers hold ``self.mu``."""
        rc = self._rc_dev
        if (rc is None or rc[0] != self._version
                or rc[1].shape[0] != n_phys):
            arr = jnp.asarray(self._row_counts[:n_phys].astype(np.int32))
            self._rc_dev = rc = (self._version, arr)
        return rc[1]

    def device_row(self, row_id):
        """uint32[32768] device bitmap for one row (full slice width —
        the window-agnostic API; batched executors use device_row_win
        to stay narrow)."""
        return self.device_row_win(row_id, 0, WORDS_PER_SLICE)

    def device_row_win(self, row_id, base32, width32):
        """uint32[width32] device bitmap for one row, rebased into the
        requested column window [base32, base32+width32) of uint32
        device words; bits outside the request read as zero. Serves
        from the HBM matrix mirror when the row is clean and the
        request matches the fragment's own window; otherwise builds
        (and memoizes per (row, window, version)) one rebased copy —
        never forcing the full-matrix dirty refresh, whose functional
        update copies the entire buffer (ruinous for single-row reads
        after small writes).

        On an EVICTED fragment this serves from the container-granular
        reader — O(row) containers decoded, no fault-in — so batched
        executor stacks over cold fragments never pull whole matrices
        into host memory."""
        querystats.add("blocks", 1)  # one row-block read per call
        hm = heatmap_mod.ACTIVE
        if hm.enabled:
            # Per-slice/per-row heat from the read layer: only work
            # that touches INDIVIDUAL slices reaches here (serial
            # loops, stack-cache misses, lane builds) — the uniform
            # batched warm path never does, by design. Stride-sampled
            # inside touch_read so the hottest read loops pay one
            # counter increment per call, not decay math.
            hm.touch_read(self.index, self.frame, row_id, self.slice,
                          weight=width32 * 4)
        lazy = self._lazy_serve(
            lambda r: jnp.asarray(
                self._lazy_row64_span(r, row_id, base32 // 2,
                                      width32 // 2).view(np.uint32)))
        if lazy is not _NOT_LAZY:
            return lazy
        with self.mu:
            phys = self._row_index.get(row_id)
            if phys is None:
                return jnp.zeros(width32, dtype=jnp.uint32)
            fb, fw = self._w64_base * 2, self._w64 * 2
            clean = (self._dev is not None
                     and self._dev.shape[0] == self._cap
                     and self._dev.shape[1] == fw
                     and phys not in self._dirty)
            if clean and fb == base32 and fw == width32:
                return self._dev[phys]
            key = (phys, base32, width32)
            memo = self._row_dev.get(key)
            if memo is not None and memo[0] == self._version:
                return memo[1]
            raw = (self._dev[phys] if clean
                   else jnp.asarray(self._matrix[phys].view(np.uint32)))
            lo = max(fb, base32)
            hi = min(fb + fw, base32 + width32)
            if lo >= hi:
                row = jnp.zeros(width32, dtype=jnp.uint32)
            elif fb == base32 and fw == width32:
                row = raw
            else:
                row = jnp.zeros(width32, dtype=jnp.uint32).at[
                    lo - base32 : hi - base32].set(raw[lo - fb : hi - fb])
            if len(self._row_dev) >= 64:
                self._row_dev.clear()
            self._row_dev[key] = (self._version, row)
            return row

    # ---------------------------------------------------------- mutations

    def _pos(self, row_id, column_id):
        """pos = row·2^20 + col%2^20 (ref: fragment.go:800-809, Pos :1904)."""
        if column_id // SLICE_WIDTH != self.slice:
            raise ValueError(
                f"column:{column_id} out of bounds for slice {self.slice}")
        return row_id * SLICE_WIDTH + column_id % SLICE_WIDTH

    def _mutate(self, row_id, column_id, set_value):
        pos = self._pos(row_id, column_id)
        self._check_writable()
        if self._opened:
            # Secure the op-log fd BEFORE touching state: a lazy open
            # failing (EMFILE) after the matrix flipped would diverge
            # durable state from memory.
            self._op_handle()
        phys = self._ensure_row(row_id)
        col = column_id % SLICE_WIDTH
        word, mask = col >> 6, np.uint64(1 << (col & 63))
        if not (self._w64_base <= word < self._w64_base + self._w64):
            if not set_value:
                return False  # out-of-window bits are zero: no-op clear
            self._ensure_window(word, word)
        word -= self._w64_base
        cur = bool(self._matrix[phys, word] & mask)
        if cur == set_value:
            return False
        if self._opened:
            # Op record BEFORE the in-memory flip (fail-stop
            # contract): an append error must leave memory on the
            # acknowledged prefix, not holding a bit the log never
            # recorded.
            self._append_ops_locked(codec.op_record(
                codec.OP_ADD if set_value else codec.OP_REMOVE, pos))
            self.op_n += 1
        if set_value:
            self._matrix[phys, word] |= mask
            self._row_counts[phys] += 1
        else:
            self._matrix[phys, word] &= ~mask
            self._row_counts[phys] -= 1
        self._version += 1
        self._dirty.add(phys)
        if self._opened:
            self._maybe_snapshot_locked()
        # Epoch bump AFTER the bytes are flushed: the published counter
        # (replica workers, server/workers.py) must never lead the
        # file, or a refresh racing this write latches the new epoch
        # against the old bytes and the write stays invisible until
        # the next unrelated bump.
        _bump_epoch(self.index)
        self.cache.add(row_id, int(self._row_counts[phys]))
        return True

    def set_bit(self, row_id, column_id):
        """Returns True iff the bit changed (ref: fragment.go:388-434)."""
        with self.mu:
            changed = self._mutate(row_id, column_id, True)
        if changed:  # emission point (ref: fragment.go:427)
            self.stats.count("setBit", 1)
        return changed

    def clear_bit(self, row_id, column_id):
        with self.mu:
            changed = self._mutate(row_id, column_id, False)
        if changed:
            self.stats.count("clearBit", 1)
        return changed

    def bulk_set_bits(self, row_ids, column_ids):
        """Vectorized SetBit burst: per-bit changed flags (original
        order; within-batch duplicates change at most once) with
        set_bit's per-op semantics — op record per changed bit,
        snapshot when the op log exceeds MaxOpN, cache/count updates
        (ref: fragment.go:388-434 applied per bit)."""
        return self._bulk_bits(row_ids, column_ids, set_value=True)

    def bulk_clear_bits(self, row_ids, column_ids):
        """Vectorized ClearBit burst: AND-NOT apply + OP_REMOVE
        records; rows absent from storage are never allocated."""
        return self._bulk_bits(row_ids, column_ids, set_value=False)

    def _bulk_bits(self, row_ids, column_ids, set_value):
        with self.mu:
            self._check_writable()
            row_ids = np.asarray(row_ids, dtype=np.uint64)
            column_ids = np.asarray(column_ids, dtype=np.uint64)
            bad = column_ids // SLICE_WIDTH != self.slice
            if bad.any():
                raise ValueError(
                    f"column:{int(column_ids[bad][0])} out of bounds for "
                    f"slice {self.slice}")
            if self._opened:
                self._op_handle()  # secure the fd before any mutation
            cols = column_ids % SLICE_WIDTH
            changed = np.zeros(len(row_ids), dtype=bool)
            if set_value:
                sub = np.arange(len(row_ids))
                uniq_rows, inverse = np.unique(row_ids, return_inverse=True)
                phys = np.asarray(
                    [self._ensure_row(int(r)) for r in uniq_rows],
                    dtype=np.int64)[inverse]
            else:
                # Clears touch only rows that exist — never allocate.
                present = np.asarray(
                    [int(r) in self._row_index for r in row_ids.tolist()])
                if not present.any():
                    return changed
                sub = np.flatnonzero(present)
                phys = np.asarray([self._row_index[int(r)]
                                   for r in row_ids[sub].tolist()],
                                  dtype=np.int64)
            scols = cols[sub]
            words = (scols >> np.uint64(6)).astype(np.int64)
            if len(words):
                if set_value:
                    self._ensure_window(int(words.min()), int(words.max()))
                else:
                    # Out-of-window bits are zero: clears there are
                    # no-ops and must not grow the narrow matrix.
                    base = self._w64_base
                    keep = (words >= base) & (words < base + self._w64)
                    if not keep.all():
                        sub = sub[keep]
                        phys = phys[keep]
                        scols = scols[keep]
                        words = words[keep]
                        if not len(words):
                            return changed
                words = words - self._w64_base
            masks = np.uint64(1) << (scols & np.uint64(63))
            cur = (self._matrix[phys, words] & masks) != 0
            # Only the first occurrence of each (row, col) can change,
            # like the serial per-op loop applied in order.
            key = phys * np.int64(SLICE_WIDTH) + scols.astype(np.int64)
            order = np.argsort(key, kind="stable")
            k_sorted = key[order]
            first_sorted = np.concatenate(
                ([True], k_sorted[1:] != k_sorted[:-1]))
            first = np.zeros(len(key), dtype=bool)
            first[order] = first_sorted
            sub_changed = first & (~cur if set_value else cur)
            n_changed = int(sub_changed.sum())
            changed[sub] = sub_changed
            if n_changed == 0:
                return changed
            if self._opened:
                # Op records BEFORE the in-memory apply — the
                # _mutate fail-stop contract, batched.
                positions = (row_ids[sub][sub_changed]
                             * np.uint64(SLICE_WIDTH)
                             + scols[sub_changed]).astype(np.uint64)
                typs = np.full(
                    len(positions),
                    codec.OP_ADD if set_value else codec.OP_REMOVE,
                    dtype=np.uint8)
                self._append_ops_locked(codec.op_records(typs, positions))
                self.op_n += n_changed
            target = (phys[sub_changed], words[sub_changed])
            if set_value:
                np.bitwise_or.at(self._matrix, target, masks[sub_changed])
            else:
                np.bitwise_and.at(self._matrix, target, ~masks[sub_changed])
            per_row = np.bincount(
                phys[sub_changed],
                minlength=len(self._row_counts)).astype(
                    self._row_counts.dtype)
            if set_value:
                self._row_counts += per_row
            else:
                self._row_counts -= per_row
            touched = np.unique(phys[sub_changed])
            self._version += 1
            self._dirty.update(touched.tolist())
            if self._opened:
                self._maybe_snapshot_locked()
            _bump_epoch(self.index)  # after the flush — see _mutate
            for p in touched.tolist():
                self.cache.add(self._phys_rows[p],
                               int(self._row_counts[p]))
        self.stats.count("setBit" if set_value else "clearBit", n_changed)
        return changed

    def import_bits(self, row_ids, column_ids):
        """Bulk import: vectorized host write + one snapshot
        (ref: fragment.go:1266-1333)."""
        with self.mu:
            self._check_writable()
            row_ids = np.asarray(row_ids, dtype=np.uint64)
            column_ids = np.asarray(column_ids, dtype=np.uint64)
            if len(row_ids) != len(column_ids):
                raise ValueError("row/column id length mismatch")
            if len(row_ids) == 0:
                return
            if self._opened:
                self._op_handle()  # secure the fd before any mutation
            bad = column_ids // SLICE_WIDTH != self.slice
            if bad.any():
                raise ValueError(
                    f"column:{int(column_ids[bad][0])} out of bounds for "
                    f"slice {self.slice}")
            cols = column_ids % SLICE_WIDTH
            # Small batches append to the op log (one batch-encoded
            # write, replayed idempotently on open) instead of paying a
            # full-file snapshot; large batches snapshot once, as the
            # reference always does (fragment.go:1331).
            use_oplog = self._opened and self._op_log_room(len(row_ids))
            if use_oplog:
                positions = (row_ids * np.uint64(SLICE_WIDTH)
                             + cols).astype(np.uint64)
                typs = np.full(len(positions), codec.OP_ADD, dtype=np.uint8)
                # Log BEFORE the scatter (fail-stop contract), fsync'd:
                # bulk imports are acknowledged durable (the snapshot
                # path they replace fsync'd); single set_bit stays
                # flush-only, as the reference's op writer does.
                self._append_ops_locked(codec.op_records(typs, positions),
                                        fsync=True)
                self.op_n += len(positions)
            uniq_rows, inverse = np.unique(row_ids, return_inverse=True)
            phys_u = np.asarray(
                [self._ensure_row(int(r)) for r in uniq_rows],
                dtype=np.int64)
            phys = phys_u[inverse]
            self._ensure_window(int(cols.min()) >> 6, int(cols.max()) >> 6)
            # Window-local columns: subtracting the base keeps word AND
            # in-word bit math intact (the base is 64-word-aligned).
            lcols = cols - np.uint64(self._w64_base * 64)
            if not native.scatter_or(self._matrix, phys, lcols):
                words = (lcols >> np.uint64(6)).astype(np.int64)
                masks = np.uint64(1) << (lcols & np.uint64(63))
                # OR-fold duplicate (row, word) hits before touching the
                # matrix: one sort + reduceat beats an unbuffered ufunc.at.
                w = self._w64
                key = phys * np.int64(w) + words
                order, starts, _, folded = codec.group_sorted(key)
                ored = np.bitwise_or.reduceat(masks[order], starts)
                self._matrix[folded // w, folded % w] |= ored
            touched = sorted(phys_u.tolist())
            self._recount_rows(touched)
            self._version += 1
            self._dirty.update(touched)
            if not use_oplog:
                self._ack_snapshot_locked()
            self._commit_caches_locked(touched)

    def install_batch(self, row_ids, column_ids, containers_by_row=None,
                      counts_by_row=None, positions=None):
        """Batch-install path for the streaming ingest pipeline
        (ingest/pipeline.py). Same durability contract as import_bits
        — op records appended (fsync'd) BEFORE the in-memory apply,
        fail-stop + rollback on a failed ack-bearing snapshot, ONE
        epoch bump so every epoch-validated tier (plan cache, result
        memos, response replays) invalidates exactly once — but built
        for the pipeline's PRE-SORTED, DEDUPLICATED input:

        - no re-sort: (row, column) groups come off one boundary scan
          of the already-ordered batch, and the matrix scatter is a
          single reduceat OR-fold;
        - bulk op-log rule: a batch appends while the log stays under
          OPLOG_MAX_OPS (the documented replay/region bound) instead
          of the card/2 housekeeping cadence — one 13 B/op sequential
          append + fsync beats re-serializing the whole fragment per
          batch, which is exactly the O(total²) the legacy cadence
          cost bulk loads;
        - row cardinalities for rows the batch CREATED come from the
          device classify stats (``counts_by_row``) — no post-install
          recount scan; pre-existing rows recount as usual;
        - compressed-container landing: pre-classified ARRAY/RUN
          containers seed the serving memos for created rows, so the
          first read serves compressed with zero re-scan and zero
          conversion churn. Rows that already held bits are left for
          the read path (a batch-only container would miss their
          pre-existing bits).

        ``containers_by_row``: row_id -> (fmt, Container|None); None
        seeds the format memo only (the DENSE cell — such rows serve
        from the fragment's own device mirrors). Input NOT sorted by
        (row, column) or not deduplicated falls back to import_bits —
        correctness never depends on the caller's ordering claim."""
        row_ids = np.asarray(row_ids, dtype=np.uint64)
        column_ids = np.asarray(column_ids, dtype=np.uint64)
        if len(row_ids) != len(column_ids):
            raise ValueError("row/column id length mismatch")
        if len(row_ids) == 0:
            return
        with self.mu:
            self._check_writable()
            bad = column_ids // SLICE_WIDTH != self.slice
            if bad.any():
                raise ValueError(
                    f"column:{int(column_ids[bad][0])} out of bounds "
                    f"for slice {self.slice}")
            cols = column_ids % SLICE_WIDTH
            if positions is None:
                # The global bit positions double as the (row, column)
                # sort key; the pipeline passes its own copy through.
                positions = (row_ids * np.uint64(SLICE_WIDTH)
                             + cols).astype(np.uint64)
            if len(positions) > 1 and not (
                    positions[1:] > positions[:-1]).all():
                # Ordering claim violated: the general path re-sorts.
                self.import_bits(row_ids, column_ids)
                return self._seed_containers_locked(containers_by_row)
            if self._opened:
                self._op_handle()  # secure the fd before any mutation
            use_oplog = (self._opened
                         and self.op_n + len(positions) <= OPLOG_MAX_OPS)
            if use_oplog:
                typs = np.full(len(positions), codec.OP_ADD,
                               dtype=np.uint8)
                # Log BEFORE the scatter (fail-stop contract), fsync'd:
                # bulk installs are acknowledged durable.
                self._append_ops_locked(codec.op_records(typs, positions),
                                        fsync=True)
                self.op_n += len(positions)
            # Per-row groups off the sorted batch: one boundary scan.
            row_bounds = np.flatnonzero(
                np.concatenate(([True], row_ids[1:] != row_ids[:-1])))
            uniq_rows = row_ids[row_bounds]
            # Pre-grow row capacity ONCE for every new row in the
            # batch — per-row doubling would reallocate (and zero +
            # copy) the matrix log2(new/old) times per bulk batch.
            n_new = sum(1 for r in uniq_rows.tolist()
                        if r not in self._row_index)
            self._grow_rows_locked(len(self._phys_rows) + n_new)
            fresh = []
            phys_u = np.empty(len(uniq_rows), dtype=np.int64)
            for i, r in enumerate(uniq_rows.tolist()):
                phys = self._row_index.get(r)
                if phys is None or self._row_counts[phys] == 0:
                    fresh.append(i)
                phys_u[i] = self._ensure_row(int(r))
            self._ensure_window(int(cols.min()) >> 6,
                                int(cols.max()) >> 6)
            lcols = cols - np.uint64(self._w64_base * 64)
            counts_per_row = np.diff(np.append(row_bounds,
                                               len(row_ids)))
            phys = np.repeat(phys_u, counts_per_row)
            words = (lcols >> np.uint64(6)).astype(np.int64)
            masks = np.uint64(1) << (lcols & np.uint64(63))
            # One reduceat OR-fold over (row, word) groups — the batch
            # is sorted, so groups are contiguous and each (row, word)
            # target is unique: plain fancy |= needs no unbuffered
            # ufunc.at.
            key = phys * np.int64(self._w64) + words
            starts = np.flatnonzero(
                np.concatenate(([True], key[1:] != key[:-1])))
            ored = np.bitwise_or.reduceat(masks, starts)
            folded = key[starts]
            self._matrix[folded // self._w64,
                         folded % self._w64] |= ored
            # Cardinalities: created rows take the batch counts (the
            # device classify stats — their final truth); pre-existing
            # rows recount.
            fresh_set = set(fresh)
            recount = [int(phys_u[i]) for i in range(len(uniq_rows))
                       if i not in fresh_set]
            for i in fresh_set:
                r = int(uniq_rows[i])
                cnt = (counts_by_row or {}).get(r)
                if cnt is None:
                    cnt = int(counts_per_row[i])
                self._row_counts[phys_u[i]] = cnt
            self._recount_rows(recount)
            touched = sorted(phys_u.tolist())
            self._version += 1
            self._dirty.update(touched)
            if not use_oplog:
                self._ack_snapshot_locked()
            self._commit_caches_locked(touched)
            return self._seed_containers_locked(
                containers_by_row,
                fresh={int(uniq_rows[i]) for i in fresh_set})

    def _seed_containers_locked(self, containers_by_row, fresh=None):
        """Seed pre-classified containers into the serving memos for
        rows the batch created; returns {format: count} of what
        actually seeded (the pilosa_ingest_containers_seeded_total
        truth). Caller holds ``self.mu``; ``fresh`` None means compute
        freshness as rows whose only bits are the batch's (the
        fallback path already installed, so 'count equals the memo's
        count' is the test)."""
        seeded = {}
        if not containers_by_row:
            return seeded
        from pilosa_tpu.ops import containers as containers_mod

        if not containers_mod.enabled():
            return seeded
        ver = self._version
        for row_id, (fmt, cont) in containers_by_row.items():
            phys = self._row_index.get(row_id)
            if phys is None:
                continue
            if fresh is not None:
                if row_id not in fresh:
                    continue
            elif cont is None or int(self._row_counts[phys]) != cont.count:
                continue
            self._cont_fmt[phys] = (ver, fmt)
            if cont is not None and fmt != bitops.FMT_DENSE:
                self._memo_container(phys, cont)
            seeded[fmt] = seeded.get(fmt, 0) + 1
        return seeded

    def import_value_bits(self, column_ids, base_values, bit_depth):
        """Bulk BSI import: vectorized plane writes — the analog of
        ImportValue (ref: fragment.go:1335-1367). Overwrites any
        previous value (stale plane bits are cleared). Durability rides
        the op log while the amortized threshold allows (a value write
        is one ADD/REMOVE per plane bit, and replay is last-op-wins, so
        overwrite semantics round-trip) — but ONLY when every column is
        a fresh insert: a torn group replays as null, which for an
        overwrite would destroy the previously acknowledged value. The
        reference's snapshot + atomic rename guarantees old-or-new,
        never neither (fragment.go:1335-1367), so batches touching any
        existing value snapshot too. Larger fresh loads also snapshot —
        the reference's per-call snapshot made chunked BSI loads
        O(total²), exactly like the set-bit cadence."""
        with self.mu:
            self._check_writable()
            column_ids = np.asarray(column_ids, dtype=np.uint64)
            base_values = np.asarray(base_values, dtype=np.uint64)
            if len(column_ids) == 0:
                return
            bad = column_ids // SLICE_WIDTH != self.slice
            if bad.any():
                raise ValueError(
                    f"column:{int(column_ids[bad][0])} out of bounds for "
                    f"slice {self.slice}")
            cols = column_ids % SLICE_WIDTH
            self._ensure_window(int(cols.min()) >> 6, int(cols.max()) >> 6)
            # Last write wins for duplicate columns within one batch
            # (the reference applies pairs sequentially,
            # fragment.go:1335); without this the clear-then-set plane
            # writes would OR the duplicate values' bits together.
            _, last_rev = np.unique(cols[::-1], return_index=True)
            if len(last_rev) != len(cols):
                keep = np.sort(len(cols) - 1 - last_rev)
                cols = cols[keep]
                base_values = base_values[keep]
            lcols = cols - np.uint64(self._w64_base * 64)
            words = (lcols >> np.uint64(6)).astype(np.int64)
            masks = np.uint64(1) << (lcols & np.uint64(63))
            # Overwrite check BEFORE mutation: any target column whose
            # not-null bit is already set holds an acknowledged value.
            # Those batches must snapshot — the op-log group's torn-tail
            # semantics (null) may only erase unacknowledged writes.
            nn_phys = self._row_index.get(bit_depth)
            any_overwrite = (nn_phys is not None and bool(
                (self._matrix[nn_phys, words] & masks).any()))
            n_ops = (bit_depth + 2) * len(cols)
            use_oplog = (self._opened and not any_overwrite
                         and self._op_log_room(n_ops))
            if use_oplog:
                # Fresh inserts only (checked above). COLUMN-MAJOR
                # records with a null sandwich per value: [REMOVE
                # not-null, plane ops..., ADD not-null]. A crash can
                # tear the appended group at any byte; replay is
                # last-op-wins, so a column whose group is torn before
                # its final ADD ends with the not-null bit CLEARED — it
                # reads as null (unacknowledged write absent), never as
                # a phantom mix of old and new plane bits. Plane-major
                # order would leave exactly that mix. Appended BEFORE
                # the plane writes (fail-stop contract).
                plane_ids = np.arange(bit_depth, dtype=np.uint64)
                sel = ((base_values[None, :] >> plane_ids[:, None])
                       & np.uint64(1)) == 1
                nn_pos = np.uint64(bit_depth * SLICE_WIDTH) + cols
                # Rows of the record matrix: 0 = REMOVE nn, 1..depth =
                # plane ops, depth+1 = ADD nn; ravel(order="F") lays
                # the records out column-by-column.
                pos_m = np.empty((bit_depth + 2, len(cols)),
                                 dtype=np.uint64)
                typ_m = np.empty((bit_depth + 2, len(cols)),
                                 dtype=np.uint8)
                pos_m[0] = nn_pos
                typ_m[0] = codec.OP_REMOVE
                pos_m[1:-1] = (plane_ids[:, None]
                               * np.uint64(SLICE_WIDTH) + cols[None, :])
                typ_m[1:-1] = np.where(sel, codec.OP_ADD,
                                       codec.OP_REMOVE)
                pos_m[-1] = nn_pos
                typ_m[-1] = codec.OP_ADD
                self._append_ops_locked(
                    codec.op_records(typ_m.ravel(order="F"),
                                     pos_m.ravel(order="F")),
                    fsync=True)  # acknowledged durable, as import
                self.op_n += n_ops
            touched = []
            for i in range(bit_depth + 1):
                phys = self._ensure_row(i)
                touched.append(phys)
                if i == bit_depth:
                    sel = np.ones(len(cols), dtype=bool)  # not-null row
                else:
                    sel = ((base_values >> np.uint64(i)) & np.uint64(1)) == 1
                # Clear all stale bits for these columns, then set selected.
                np.bitwise_and.at(self._matrix, (phys, words), ~masks)
                np.bitwise_or.at(self._matrix, (phys, words[sel]), masks[sel])
            self._recount_rows(touched)
            self._version += 1
            self._dirty.update(touched)
            if not use_oplog:
                self._ack_snapshot_locked()
            self._commit_caches_locked(touched)

    # ------------------------------------------------------------ queries

    def count(self):
        with self.mu:
            return int(self._row_counts[: len(self._phys_rows)].sum())

    def checksum(self):
        """Hash of block hashes (ref: fragment.go:1023)."""
        h = b"".join(cs for _, cs in self.blocks())
        return xxhash64(h).to_bytes(8, "little")

    def _block_pairs(self, block_id):
        lo, hi = block_id * HASH_BLOCK_SIZE, (block_id + 1) * HASH_BLOCK_SIZE
        rows, cols = [], []
        for row_id in self.rows():
            if row_id < lo or row_id >= hi:
                continue
            phys = self._row_index[row_id]
            if not self._row_counts[phys]:
                continue
            bits = self._extract_bits(self._matrix[phys])
            bits = bits + np.uint64(self._w64_base * 64)  # window → global
            rows.append(np.full(len(bits), row_id, dtype=np.uint64))
            cols.append(bits)
        if not rows:
            return np.empty(0, np.uint64), np.empty(0, np.uint64)
        return np.concatenate(rows), np.concatenate(cols)

    def _lazy_row_full(self, reader, row_id):
        """uint64[WORDS64] full-width row streamed straight from the
        container reader — NO memoization: anti-entropy walks every
        row once, and caching them would cycle the shared memo and
        hold bytes the walk never reuses."""
        row = np.zeros(WORDS64, dtype=np.uint64)
        base_key = row_id * _CONTAINERS_PER_ROW
        for sub in range(_CONTAINERS_PER_ROW):
            block = reader.container(base_key + sub)
            if block is not None:
                row[sub * _WORDS64_PER_CONTAINER
                    : (sub + 1) * _WORDS64_PER_CONTAINER] = block
        return row

    @staticmethod
    def _extract_bits(words64):
        """Bit positions of a uint64 row (native fast path, NumPy
        fallback) — the ONE extraction used by both resident and lazy
        block walks, so their checksums can never drift."""
        from pilosa_tpu import native

        if native.available():
            bits = native.extract_positions(words64)
            if bits is not None:
                return np.asarray(bits, dtype=np.uint64)
        return np.flatnonzero(np.unpackbits(
            words64.view(np.uint8), bitorder="little")).astype(np.uint64)

    @staticmethod
    def _block_checksum(rows, cols):
        """Anti-entropy checksum over one block's (row, col) pairs —
        shared by resident and lazy walks (layout drift between the
        two would make a node's replicas disagree every pass)."""
        buf = np.stack([rows, cols], axis=1).astype("<u8").tobytes()
        return xxhash64(buf).to_bytes(8, "little")

    def _lazy_row_ids(self, reader):
        return sorted({k // _CONTAINERS_PER_ROW for k in reader.keys()})

    def _lazy_block_pairs(self, reader, block_id, row_ids=None):
        """(rowIDs, colIDs) for one 100-row block from streamed lazy
        rows — same ascending order and global positions as the
        resident _block_pairs. ``row_ids`` lets _lazy_blocks pass the
        pre-grouped list so the key set isn't re-enumerated per
        block."""
        if row_ids is None:
            lo = block_id * HASH_BLOCK_SIZE
            hi = (block_id + 1) * HASH_BLOCK_SIZE
            row_ids = [r for r in self._lazy_row_ids(reader)
                       if lo <= r < hi]
        rows, cols = [], []
        for row_id in row_ids:
            bits = self._extract_bits(self._lazy_row_full(reader, row_id))
            if len(bits) == 0:
                continue
            rows.append(np.full(len(bits), row_id, dtype=np.uint64))
            cols.append(bits)
        if not rows:
            return np.empty(0, np.uint64), np.empty(0, np.uint64)
        return np.concatenate(rows), np.concatenate(cols)

    def _lazy_blocks(self, reader):
        by_block = {}
        for r in self._lazy_row_ids(reader):
            by_block.setdefault(r // HASH_BLOCK_SIZE, []).append(r)
        out = []
        for block_id in sorted(by_block):
            rows, cols = self._lazy_block_pairs(reader, block_id,
                                                by_block[block_id])
            if len(rows) == 0:
                continue
            out.append((block_id, self._block_checksum(rows, cols)))
        return out

    def digest(self):
        """8-byte CONTENT-TRUE fragment-level anti-entropy digest: a
        multilinear hash Σ word·mix64(global word index) mod 2^64 over
        the fragment's decoded 64-bit words (all-zero content — the
        empty fragment — digests to the canonical zero bytes a replica
        404 maps to).

        Content-deterministic across replicas regardless of on-disk
        encoding, op-log state, or residency: both paths hash the
        DECODED words, never file bytes (two replicas holding identical
        bits can differ physically — one snapshotted, one with pending
        op-log records — so payload-byte hashing would force walks on
        every unsnapshotted fragment). The syncer compares this one
        value per replica and skips the whole per-block checksum walk
        on agreement (ref contrast: syncFragment walks unconditionally,
        fragment.go:1703-1782; Checksum() hash-of-block-hashes,
        fragment.go:1023, is the content-true shape this follows).
        Unlike the earlier (key, cardinality) digest — whose blind spot
        was SYSTEMATIC: any cardinality-preserving divergence passed
        forever, requiring a periodic unconditional walk — a collision
        here needs Σ Δword·c_i = 0 mod 2^64 against fixed pseudorandom
        constants: ~2^-64 for any fixed divergence, no structured
        class, so the skip is exact and unconditional (replicas are
        same-installation peers, not adversaries).

        Version-keyed memo (the _win32_memo pattern): the syncer calls
        this for EVERY fragment each pass; an unchanged fragment —
        resident or evicted — answers from the memo without touching
        its words again.

        Consistency invariant (tested): resident global word index
        row·16384 + w64_base + w equals lazy key·1024 + word-in-
        container for the same bit, because key = row·16 + sub and
        16·1024 = 16384 = WORDS64."""
        memo = self._digest_memo
        if memo is not None and memo[0] == self._version:
            return memo[1]
        version = self._version
        lazy = self._lazy_serve(self._lazy_digest)
        if lazy is not _NOT_LAZY:
            self._digest_memo = (version, lazy)
            return lazy
        with self.mu:
            n = len(self._phys_rows)
            if n == 0:
                val = _EMPTY_DIGEST
            else:
                base = np.uint64(self._w64_base) + np.arange(
                    self._w64, dtype=np.uint64)
                rows = np.asarray(self._phys_rows, dtype=np.uint64)
                total = 0  # Python int: np scalar += warns on wrap
                # Row-chunked: the constants matrix is as large as the
                # matrix slice it multiplies, so bound the transient.
                for i in range(0, n, 256):
                    chunk = self._matrix[i : min(i + 256, n)]
                    gwid = (rows[i : i + len(chunk), None]
                            * np.uint64(WORDS64) + base[None, :])
                    total += int(
                        (chunk * _mix64(gwid)).sum(dtype=np.uint64))
                val = (total & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "little")
            self._digest_memo = (self._version, val)
            return val

    def _lazy_digest(self, reader):
        """Content hash over an evicted fragment without materializing
        8 KB blocks per container (the naive container() loop cost
        ~90 µs/container in per-key numpy overhead — ~19 s for a
        400-fragment identical-replica pass).

        Vectorization identities: for ARRAY containers, distinct bit
        positions within one word sum without carry, so
        word·C = Σ_bits 2^(p&63)·C — the whole fragment's array
        positions batch into ONE (shift, mix, multiply, sum) pass.
        BITMAP containers multiply their mmap'd words directly against
        their constants in chunks. RUN containers and op-touched keys
        (both rare on an evicted snapshot) take the exact container()
        path. All paths feed the same Σ word·mix64(gwid) mod 2^64."""
        wpos = np.arange(codec.BITMAP_N, dtype=np.uint64)
        total = 0  # Python int: np scalar += warns on wrap
        mm = reader._mm

        arr_keys, arr_metas = [], []
        for key in reader.keys():
            meta = reader.metas.get(key)
            if meta is None or key in reader._ops:
                block = reader.container(key)
                if block is None:
                    continue
                gwid = np.uint64(key) * np.uint64(codec.BITMAP_N) + wpos
                total += int((block * _mix64(gwid)).sum(dtype=np.uint64))
                continue
            ctype, n, coff = meta
            if ctype == codec.TYPE_ARRAY:
                arr_keys.append(key)
                arr_metas.append((n, coff))
            elif ctype == codec.TYPE_BITMAP:
                words = np.frombuffer(mm, dtype="<u8",
                                      count=codec.BITMAP_N, offset=coff)
                gwid = np.uint64(key) * np.uint64(codec.BITMAP_N) + wpos
                total += int((words * _mix64(gwid)).sum(dtype=np.uint64))
            else:  # RUN: decode exactly (rare)
                block = reader.container(key)
                gwid = np.uint64(key) * np.uint64(codec.BITMAP_N) + wpos
                total += int((block * _mix64(gwid)).sum(dtype=np.uint64))

        if arr_keys:
            # One batched pass over every array container's positions.
            counts = np.asarray([n for n, _ in arr_metas])
            pos = np.empty(int(counts.sum()), dtype=np.uint16)
            off = 0
            for (n, coff) in arr_metas:
                pos[off:off + n] = np.frombuffer(mm, dtype="<u2",
                                                 count=n, offset=coff)
                off += n
            keys64 = np.repeat(
                np.asarray(arr_keys, dtype=np.uint64), counts)
            p64 = pos.astype(np.uint64)
            gwid = keys64 * np.uint64(codec.BITMAP_N) + (
                p64 >> np.uint64(6))
            vals = np.uint64(1) << (p64 & np.uint64(63))
            total += int((vals * _mix64(gwid)).sum(dtype=np.uint64))
        return (total & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "little")

    def blocks(self):
        """[(block_id, checksum bytes)] for non-empty 100-row blocks
        (ref: fragment.go:1046-1125). Served container-granularly on
        evicted fragments: the periodic anti-entropy walk must not
        fault a whole cold index's matrices in every pass."""
        lazy = self._lazy_serve(self._lazy_blocks)
        if lazy is not _NOT_LAZY:
            return lazy
        with self.mu, tracing.span("fragment.block_pack",
                                   slice=self.slice):
            out = []
            if not self._phys_rows:
                return out
            for block_id in sorted({r // HASH_BLOCK_SIZE for r in self.rows()}):
                rows, cols = self._block_pairs(block_id)
                if len(rows) == 0:
                    continue
                out.append((block_id, self._block_checksum(rows, cols)))
            return out

    def block_data(self, block_id):
        """(rowIDs, columnIDs) in ascending position order
        (ref: fragment.go:1127-1137)."""
        lazy = self._lazy_serve(
            lambda r: self._lazy_block_pairs(r, block_id))
        if lazy is not _NOT_LAZY:
            return lazy
        with self.mu:
            return self._block_pairs(block_id)

    def merge_block(self, block_id, pair_sets):
        """Majority-consensus merge (ref: fragment.go:1144-1253).

        ``pair_sets`` is a list of (rowIDs, colIDs) from remote replicas.
        Applies the local diff and returns per-remote (sets, clears)
        lists of (rowIDs, colIDs) needed to bring each remote to
        consensus. Even splits resolve to set.
        """
        with self.mu:
            lo_row = block_id * HASH_BLOCK_SIZE
            hi_row = (block_id + 1) * HASH_BLOCK_SIZE

            def keyset(rows, cols):
                rows = np.asarray(rows, dtype=np.uint64)
                cols = np.asarray(cols, dtype=np.uint64)
                keep = (rows >= lo_row) & (rows < hi_row)
                return set(zip(rows[keep].tolist(), cols[keep].tolist()))

            local_rows, local_cols = self._block_pairs(block_id)
            participants = [keyset(local_rows, local_cols)]
            participants += [keyset(r, c) for r, c in pair_sets]
            majority = (len(participants) + 1) // 2

            all_pairs = set().union(*participants)
            consensus = {
                p for p in all_pairs
                if sum(p in s for s in participants) >= majority
            }

            diffs = []
            for s in participants:
                sets = sorted(consensus - s)
                clears = sorted(s - consensus)
                diffs.append((sets, clears))

            for row_id, col in diffs[0][0]:
                self.set_bit(int(row_id), self.slice * SLICE_WIDTH + int(col))
            for row_id, col in diffs[0][1]:
                self.clear_bit(int(row_id), self.slice * SLICE_WIDTH + int(col))
            return diffs[1:]

    # ----------------------------------------------------------------- BSI

    def _planes(self, depth):
        """jnp uint32[depth+1, W]: planes 0..depth-1 + exists plane
        (full slice width)."""
        return self.planes_win(depth, 0, WORDS_PER_SLICE)

    def planes_win(self, depth, base32, width32):
        """jnp uint32[depth+1, width32] plane matrix rebased into the
        column window [base32, base32+width32) of uint32 device words
        (base32 must be even — windows are 64-bit-word aligned).

        On an EVICTED fragment the planes assemble from lazy container
        decodes (BSI plane rows 0..depth) — Sum/Min/Max/Range over a
        cold index never faults matrices in; the memo blocks are
        governor-charged like every lazy read."""
        lazy = self._lazy_serve(
            lambda r: self._lazy_planes(r, depth, base32, width32))
        if lazy is not _NOT_LAZY:
            return lazy
        with self.mu:
            key = (depth, base32, width32)
            cached = self._planes_cache.get(key)
            if cached and cached[0] == self._version:
                return cached[1]
            version = self._version
            b64, w64 = base32 // 2, width32 // 2
            mat = np.zeros((depth + 1, w64), dtype=np.uint64)
            lo = max(self._w64_base, b64)
            hi = min(self._w64_base + self._w64, b64 + w64)
            if lo < hi:
                for i in range(depth + 1):
                    phys = self._row_index.get(i)
                    if phys is not None:
                        mat[i, lo - b64 : hi - b64] = self._matrix[
                            phys,
                            lo - self._w64_base : hi - self._w64_base]
            planes = jnp.asarray(mat.view(np.uint32))
            self._planes_cache = {key: (version, planes)}
            return planes

    def set_field_value(self, column_id, bit_depth, value):
        """Write value bits into rows 0..depth-1 + not-null row
        (ref: fragment.go:517-546)."""
        with self.mu:
            changed = False
            for i in range(bit_depth):
                if (value >> i) & 1:
                    changed |= self.set_bit(i, column_id)
                else:
                    changed |= self.clear_bit(i, column_id)
            changed |= self.set_bit(bit_depth, column_id)
            return changed

    def field_value(self, column_id, bit_depth):
        """(value, exists) for one column (ref: fragment.go:493-515)."""
        with self.mu:
            col = column_id % SLICE_WIDTH
            word, mask = col >> 6, np.uint64(1 << (col & 63))

            def bit(row_id):
                phys = self._row_index.get(row_id)
                base = self._w64_base
                if phys is None or not (base <= word < base + self._w64):
                    return False
                return bool(self._matrix[phys, word - base] & mask)

            if not bit(bit_depth):
                return 0, False
            value = 0
            for i in range(bit_depth):
                if bit(i):
                    value |= 1 << i
            return value, True

    def field_sum(self, filter_words, bit_depth):
        """(sum, count) over columns with a value, optionally ∩ filter
        (ref: FieldSum fragment.go:590-618)."""
        planes = self._planes(bit_depth)
        if filter_words is None:
            filt = planes[bit_depth]
        else:
            filt = bitops.bitmap_and(
                planes[bit_depth],
                jnp.asarray(np.ascontiguousarray(filter_words).view(np.uint32)))
        counts = np.asarray(bsi_ops.plane_counts(planes[:bit_depth], filt))
        total = sum((1 << i) * int(c) for i, c in enumerate(counts))
        return total, int(bitops.count(filt))

    def field_range(self, op, bit_depth, predicate):
        """uint64[WORDS64] bitmap of matching columns
        (ref: FieldRange fragment.go:621-798)."""
        planes = self._planes(bit_depth)
        exists = planes[bit_depth]
        bits = bsi_ops.value_to_bits(predicate, bit_depth)
        fn = {
            "==": bsi_ops.bsi_eq, "!=": bsi_ops.bsi_neq,
            "<": bsi_ops.bsi_lt, "<=": bsi_ops.bsi_lte,
            ">": bsi_ops.bsi_gt, ">=": bsi_ops.bsi_gte,
        }[op]
        out = np.asarray(fn(planes[:bit_depth], exists, bits))
        return np.ascontiguousarray(out).view(np.uint64)

    def field_range_between(self, bit_depth, lo, hi):
        planes = self._planes(bit_depth)
        out = np.asarray(bsi_ops.bsi_between(
            planes[:bit_depth], planes[bit_depth],
            bsi_ops.value_to_bits(lo, bit_depth),
            bsi_ops.value_to_bits(hi, bit_depth)))
        return np.ascontiguousarray(out).view(np.uint64)

    def field_not_null(self, bit_depth):
        """(ref: FieldNotNull fragment.go:755)."""
        return np.array(self.row_words(bit_depth))

    def field_min_max(self, filter_words, bit_depth, find_max):
        """(value, count). Bit-descent Min/Max over the planes."""
        planes = self._planes(bit_depth)
        filt = planes[bit_depth]
        if filter_words is not None:
            filt = bitops.bitmap_and(
                filt, jnp.asarray(np.ascontiguousarray(filter_words).view(np.uint32)))
        if int(bitops.count(filt)) == 0:
            return 0, 0
        ind, remaining = bsi_ops.bsi_extrema_indicators(
            planes[:bit_depth], filt, find_max)
        value = sum((1 << i) * int(b) for i, b in enumerate(np.asarray(ind)))
        return value, int(bitops.count(remaining))

    # ---------------------------------------------------------------- TopN

    def top(self, opt=None):
        """TopN over this fragment (ref: fragment.go:831-963).

        TPU path: one fused popcount over the whole row matrix (optionally
        ∩ src) replaces the reference's ranked-cache walk — counts are
        exact, not cache-approximate. The cache's *candidate* semantics
        are preserved: with no explicit row_ids, only rows present in the
        cache are eligible (ref: topBitmapPairs fragment.go:965), and a
        ``none``-cache frame yields no TopN results, as in the reference.
        """
        from pilosa_tpu.ops import topn as topn_ops
        from pilosa_tpu.storage.cache import NopCache

        opt = opt or TopOptions()
        if opt.src is None:
            # Src-less TopN is a cache walk + exact counts — both
            # available on an EVICTED fragment (cache sidecar + header
            # cardinalities), so don't fault the matrix in for it.
            out = self._lazy_serve(lambda r: self._lazy_top(r, opt))
            if out is not _NOT_LAZY:
                return out
        with self.mu:
            n_phys = len(self._phys_rows)
            if n_phys == 0:
                return []
            if opt.row_ids is None and isinstance(self.cache, NopCache):
                return []
            if opt.src is not None:
                # Only the src-intersection path reads the device
                # matrix; building (and slicing) it for the src-less
                # cache walk cost a device upload + dispatch per
                # fragment per query for data the counts never touch.
                matrix = self.device_matrix()[:n_phys]
                # The matrix may be narrower than the full slice; bits
                # beyond its width are zero, so trimming src to the
                # matrix width preserves every intersection count. The
                # Tanimoto denominator's |src| must still come from the
                # FULL src bitmap.
                src_words = np.ascontiguousarray(opt.src)
                base = self._w64_base
                src32 = jnp.asarray(np.ascontiguousarray(
                    src_words[base : base + self._w64]).view(np.uint32))
                if opt.tanimoto_threshold:
                    counts = np.asarray(topn_ops.tanimoto_masked_counts(
                        matrix, src32, self._row_counts_device(n_phys),
                        int(np.bitwise_count(src_words).sum()),
                        opt.tanimoto_threshold))
                else:
                    counts = np.asarray(bitops.count_and_rows(matrix, src32))
            else:
                counts = self._row_counts[:n_phys].copy()

            row_ids = np.asarray(self._phys_rows, dtype=np.uint64)
            counts_np = np.asarray(counts, dtype=np.int64)
            # Vectorized eligibility + selection: at the chem-showcase
            # shape (500k cached rows in one fragment) the per-row
            # Python loop + full sort this replaces was ~300 ms/query —
            # most of the measured TopN latency on an accelerator.
            mask = counts_np > 0
            if opt.min_threshold:
                mask &= counts_np >= opt.min_threshold
            if opt.row_ids is not None:
                mask &= np.isin(row_ids, np.fromiter(
                    opt.row_ids, dtype=np.uint64))
            elif not isinstance(self.cache, NopCache):
                mask &= np.isin(row_ids, self.cache.ids_arr())
            if opt.filter_row_ids is not None:
                mask &= np.isin(row_ids, np.fromiter(
                    opt.filter_row_ids, dtype=np.uint64))
            idx = np.nonzero(mask)[0]
            # Explicit row ids (the TopN phase-2 exact re-query) are
            # never truncated per slice — trimming happens only after
            # the cross-slice merge (ref: fragment.go:835-838
            # "If row ids are provided, we don't want to truncate").
            truncate = bool(opt.n) and opt.row_ids is None
            if truncate and idx.size > opt.n:
                # Exact top-n: nth-largest count bounds the candidate
                # set (count ties straddling the cut stay in and are
                # broken by row id in the final sort).
                c = counts_np[idx]
                nth = c[np.argpartition(-c, opt.n - 1)[opt.n - 1]]
                idx = idx[c >= nth]
            order = np.lexsort((row_ids[idx], -counts_np[idx]))
            sel = idx[order[: opt.n]] if truncate else idx[order]
            return [(int(r), int(c))
                    for r, c in zip(row_ids[sel], counts_np[sel])]

    # -------------------------------------------------------------- backup

    def write_to(self, fileobj):
        """Tar archive of data + cache (ref: fragment.go:1476-1560).

        An EVICTED fragment's roaring file (snapshot + op-log tail)
        already IS its current state — readers replay the tail — so
        backup streams the raw file bytes instead of faulting the
        matrix in to re-serialize it: backing up a cold index is file
        copying, not an index-wide decode."""
        import io

        if not self._resident and self._opened:
            done = fresh = False
            self.mu.acquire_raw()
            try:
                if not self._resident and self._opened:
                    fresh = (self._lazy_cache_ids is None
                             and not self._cache_loaded)
                    cache = json.dumps(sorted(
                        self._lazy_cache_ids_locked())).encode()
                    with open(self.path, "rb") as f:
                        # Streamed, not f.read(): a multi-GB cold
                        # fragment must not double-buffer through host
                        # memory — the resource eviction protects.
                        self._write_backup_tar(
                            fileobj, f, os.fstat(f.fileno()).st_size,
                            cache)
                    done = True
            finally:
                self.mu.release_raw()
            if done:
                if fresh and self.governor is not None:
                    self.governor.touch(self)
                    self.governor.update(self, self.host_bytes())
                return

        with self.mu:
            data = codec.serialize_arrays(*self._to_arrays())
            cache = json.dumps(self.cache.ids()).encode()
        self._write_backup_tar(fileobj, io.BytesIO(data), len(data),
                               cache)

    @staticmethod
    def _write_backup_tar(fileobj, data_stream, data_size, cache):
        """The ONE backup-archive layout (data + cache members),
        shared by the cold (raw-file stream) and resident
        (re-serialized) paths so the two formats cannot diverge."""
        import io
        import tarfile

        with tarfile.open(fileobj=fileobj, mode="w") as tar:
            info = tarfile.TarInfo("data")
            info.size = data_size
            tar.addfile(info, data_stream)
            cinfo = tarfile.TarInfo("cache")
            cinfo.size = len(cache)
            tar.addfile(cinfo, io.BytesIO(cache))

    def read_from(self, fileobj):
        """Restore from a backup tar (ref: fragment.go:1562-1648)."""
        import tarfile

        with tarfile.open(fileobj=fileobj, mode="r") as tar:
            for member in tar.getmembers():
                payload = tar.extractfile(member).read()
                if member.name == "data":
                    # Raw lock: restoring over an evicted/untouched
                    # fragment must not fault the soon-discarded old
                    # state in first.
                    self.mu.acquire_raw()
                    try:
                        self._drop_lazy_locked()  # file being replaced
                        blocks, _, _ = codec.deserialize(payload)
                        self._reset_storage()
                        self._load_blocks(blocks)
                        with open(self.path, "wb") as f:
                            f.write(codec.serialize(blocks))
                        if self._op_file:
                            self._op_file.close()
                            self._op_file = None
                        self.op_n = 0
                        # The rewritten file IS the new snapshot.
                        self._snap_card = int(self._row_counts.sum())
                        # A restore fully replaces both memory and the
                        # on-disk file — exactly the reload the
                        # fail-stop latch waits for — so it clears the
                        # latch: restoring over a fail-stopped
                        # fragment is the operator's repair path, and
                        # leaving writes 503ing after a verified
                        # restore would demand a pointless restart.
                        self._failed = None
                        self._resident = True  # restored state IS current
                        self._mem_changed()
                    finally:
                        self.mu.release_raw()
                elif member.name == "cache":
                    with open(self.cache_path, "wb") as f:
                        f.write(payload)
                    self.cache.clear()
                    self._open_cache()
                    self._cache_loaded = True

    def merge_from(self, fileobj):
        """Union-install a backup tar: every set bit in the snapshot
        is OR-ed into the CURRENT fragment (one vectorized
        import_bits), clearing nothing. The elastic-rebalance install
        path (cluster/rebalancer.py) for bit views: a replacing
        restore would wipe dual writes applied to this replica while
        the snapshot was in flight — the acked-write-loss race — while
        a union can only add bits the source held. The rank cache
        member is ignored (it rebuilds from the merged counts)."""
        import tarfile

        rows_out, cols_out = [], []
        with tarfile.open(fileobj=fileobj, mode="r") as tar:
            for member in tar.getmembers():
                if member.name != "data":
                    continue
                payload = tar.extractfile(member).read()
                blocks, _, _ = codec.deserialize(payload)
                cbits = _WORDS64_PER_CONTAINER * 64
                for key, words in blocks.items():
                    w = np.ascontiguousarray(words, dtype=np.uint64)
                    bits = np.flatnonzero(np.unpackbits(
                        w.view(np.uint8), bitorder="little"))
                    if len(bits) == 0:
                        continue
                    rows_out.append(np.full(len(bits), key
                                            // _CONTAINERS_PER_ROW,
                                            dtype=np.uint64))
                    cols_out.append(
                        bits.astype(np.uint64)
                        + np.uint64((key % _CONTAINERS_PER_ROW) * cbits
                                    + self.slice * SLICE_WIDTH))
        if rows_out:
            self.import_bits(np.concatenate(rows_out),
                             np.concatenate(cols_out))

    def _reset_storage(self):
        self._cap = 0
        self._w64 = _MIN_W64
        self._w64_base = 0
        self._matrix = np.zeros((0, _MIN_W64), dtype=np.uint64)
        self._row_counts = np.zeros(0, dtype=np.int64)
        self._row_index = {}
        self._phys_rows = []
        self.max_row_id = 0
        self._dev = None
        self._dirty.clear()
        self._planes_cache = {}
        self._row_dev = {}
        self._rc_dev = None
        self._cont_dev = {}
        self._cont_fmt = {}
        self._version += 1
        _bump_epoch(self.index)
