"""Input definitions — stored ETL mappings from JSON records to bits
(ref: input_definition.go)."""
from pilosa_tpu import errors as perr

INPUT_MAPPING = "mapping"
INPUT_VALUE_TO_ROW = "value-to-row"
INPUT_SINGLE_ROW_BOOL = "single-row-boolean"
INPUT_SET_TIMESTAMP = "set-timestamp"

VALID_DESTINATIONS = (INPUT_MAPPING, INPUT_VALUE_TO_ROW,
                      INPUT_SINGLE_ROW_BOOL, INPUT_SET_TIMESTAMP)


class Action:
    """(ref: input_definition.go:204-229)."""

    def __init__(self, frame, value_destination, value_map=None, row_id=None):
        self.frame = frame
        self.value_destination = value_destination
        self.value_map = value_map or {}
        self.row_id = row_id

    def validate(self):
        if not self.frame:
            raise perr.ErrFrameRequired()
        if self.value_destination not in VALID_DESTINATIONS:
            raise ValueError(
                f"invalid ValueDestination: {self.value_destination}")
        if self.value_destination == INPUT_MAPPING and not self.value_map:
            raise perr.ErrInputDefinitionValueMap()
        return self

    def to_dict(self):
        d = {"frame": self.frame, "valueDestination": self.value_destination}
        if self.value_map:
            d["valueMap"] = self.value_map
        if self.row_id is not None:
            d["rowID"] = self.row_id
        return d

    @classmethod
    def from_dict(cls, d):
        return cls(d.get("frame", ""), d.get("valueDestination", ""),
                   d.get("valueMap"), d.get("rowID"))


def handle_action(action, value, col_id, timestamp):
    """JSON field value -> (row_id, col_id, timestamp) bit, or None
    (ref: HandleAction input_definition.go:353-390)."""
    dest = action.value_destination
    if dest == INPUT_MAPPING:
        if not isinstance(value, str):
            raise ValueError(f"Mapping value must be a string {value}")
        if value not in action.value_map:
            raise ValueError(f"Value {value} does not exist in definition map")
        return (action.value_map[value], col_id, timestamp)
    if dest == INPUT_SINGLE_ROW_BOOL:
        if not isinstance(value, bool):
            raise ValueError(
                f"single-row-boolean value {value} must equate to a Bool")
        if not value:
            return None
        return (action.row_id, col_id, timestamp)
    if dest == INPUT_VALUE_TO_ROW:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise ValueError(f"value-to-row value must equate to an integer {value}")
        return (int(value), col_id, timestamp)
    if dest == INPUT_SET_TIMESTAMP:
        return None
    raise ValueError(f"Unrecognized Value Destination: {dest} in Action")


class InputField:
    def __init__(self, name, primary_key=False, actions=None):
        self.name = name
        self.primary_key = primary_key
        self.actions = actions or []

    def to_dict(self):
        return {"name": self.name, "primaryKey": self.primary_key,
                "actions": [a.to_dict() for a in self.actions]}

    @classmethod
    def from_dict(cls, d):
        return cls(d.get("name", ""), d.get("primaryKey", False),
                   [Action.from_dict(a) for a in d.get("actions", [])])


class InputDefinition:
    """(ref: input_definition.go:38-182)."""

    def __init__(self, name, frames, fields):
        self.name = name
        # frames: [{"name": ..., "options": {...}}]
        self.frames = frames
        self.fields = [f if isinstance(f, InputField) else InputField.from_dict(f)
                       for f in fields]

    def validate(self, column_label):
        if not self.frames or not self.fields:
            raise perr.ErrInputDefinitionAttrsRequired()
        n_primary = sum(1 for f in self.fields if f.primary_key)
        if n_primary == 0:
            raise perr.ErrInputDefinitionHasPrimaryKey()
        if n_primary > 1:
            raise perr.ErrInputDefinitionDupePrimaryKey()
        primary = next(f for f in self.fields if f.primary_key)
        if primary.name != column_label:
            raise perr.ErrInputDefinitionColumnLabel()
        for f in self.fields:
            for a in f.actions:
                a.validate()
        return self

    def to_dict(self):
        return {"frames": self.frames,
                "fields": [f.to_dict() for f in self.fields]}

    @classmethod
    def from_dict(cls, name, d):
        return cls(name, d.get("frames", []), d.get("fields", []))

    def parse_records(self, records):
        """JSON records -> {frame: [(row, col, t)]} (ref: handler.go:1948
        InputJSONDataParser + Index.InputBits)."""
        out = {}
        primary = next(f for f in self.fields if f.primary_key)
        for rec in records:
            if primary.name not in rec:
                raise ValueError(
                    f"primary key {primary.name} does not exist in record")
            col_id = rec[primary.name]
            if not isinstance(col_id, (int, float)) or isinstance(col_id, bool):
                raise ValueError("primary key must be an integer")
            col_id = int(col_id)
            timestamp = None
            for f in self.fields:
                for a in f.actions:
                    if (a.value_destination == INPUT_SET_TIMESTAMP
                            and f.name in rec):
                        timestamp = rec[f.name]
            for f in self.fields:
                if f.primary_key or f.name not in rec:
                    continue
                for a in f.actions:
                    bit = handle_action(a, rec[f.name], col_id, timestamp)
                    if bit is not None:
                        out.setdefault(a.frame, []).append(bit)
        return out
