"""View — a named container of fragments keyed by slice (ref: view.go).

View names: ``standard``, ``inverse``, time-derived (``standard_2017``),
and ``field_<name>`` for BSI fields (view.go:32-38).
"""
import os
import threading

import numpy as np

from pilosa_tpu import SLICE_WIDTH
from pilosa_tpu import stats as stats_mod
from pilosa_tpu.storage.fragment import Fragment
from pilosa_tpu import lockcheck

VIEW_STANDARD = "standard"
VIEW_INVERSE = "inverse"
VIEW_FIELD_PREFIX = "field_"


def view_field_name(field):
    return VIEW_FIELD_PREFIX + field


def is_view_allowed(name):
    return bool(name)


class View:
    def __init__(self, path, index, frame, name,
                 cache_type="ranked", cache_size=50000):
        self.path = path
        self.index = index
        self.frame = frame
        self.name = name
        self.cache_type = cache_type
        self.cache_size = cache_size
        self.mu = lockcheck.register("storage.View.mu",
                                     threading.RLock(),
                                     allow_device_sync=True)
        self.stats = stats_mod.NOP
        self.events = None  # flight recorder, frame-propagated
        self.fragments = {}  # slice -> Fragment
        # Set by Frame: called with (view_name, slice) when a NEW slice's
        # fragment is created, so peers can learn the max slice
        # (ref: view.go:240-255 CreateSliceMessage; :59 dedup guard).
        self.on_new_slice = None
        self._slice_notified = set()
        # Set by Frame: host-memory governor passed to fragments.
        self.governor = None

    def open(self):
        """Scan the fragments directory and open each (ref: view.go:100-158)."""
        with self.mu:
            frag_dir = os.path.join(self.path, "fragments")
            os.makedirs(frag_dir, exist_ok=True)
            for entry in sorted(os.listdir(frag_dir)):
                if entry.endswith(".cache") or entry.endswith(".snapshotting"):
                    continue
                try:
                    slice_num = int(entry)
                except ValueError:
                    continue
                self._open_fragment(slice_num)
        return self

    def close(self):
        with self.mu:
            for frag in self.fragments.values():
                frag.close()
            self.fragments = {}

    def fragment_path(self, slice_num):
        return os.path.join(self.path, "fragments", str(slice_num))

    def _open_fragment(self, slice_num):
        """Caller holds self.mu."""
        frag = Fragment(self.fragment_path(slice_num), self.index, self.frame,
                        self.name, slice_num,
                        cache_type=self.cache_type, cache_size=self.cache_size)
        frag.stats = self.stats.with_tags(f"slice:{slice_num}")
        frag.governor = self.governor
        frag.events = self.events
        frag.open()
        self.fragments[slice_num] = frag
        return frag

    def fragment(self, slice_num):
        with self.mu:
            return self.fragments.get(slice_num)

    def create_fragment_if_not_exists(self, slice_num):
        """(ref: view.go:224)."""
        notify = False
        with self.mu:
            frag = self.fragments.get(slice_num)
            if frag is None:
                frag = self._open_fragment(slice_num)
                if (self.on_new_slice is not None
                        and slice_num not in self._slice_notified):
                    self._slice_notified.add(slice_num)
                    notify = True
        # Notify outside the view lock: the broadcast does network IO and
        # must not serialize other readers/writers of this view.
        if notify:
            self.on_new_slice(self.name, slice_num)
        return frag

    def max_slice(self):
        with self.mu:
            return max(self.fragments, default=0)

    def drop_fragment(self, slice_num):
        """Remove one fragment entirely: close it and delete its
        on-disk files (data, rank cache, stray snapshot temp). The
        post-rebalance prune path (cluster/rebalancer.py) — a slice
        this node no longer owns stops being served AND stops costing
        disk. Returns True when a fragment was dropped. Close rides
        under ``mu`` exactly as ``refresh_replica``'s drop path does."""
        with self.mu:
            frag = self.fragments.pop(slice_num, None)
            self._slice_notified.discard(slice_num)
            if frag is None:
                return False
            frag.close()
        for suffix in ("", ".cache", ".snapshotting"):
            try:
                os.remove(self.fragment_path(slice_num) + suffix)
            except OSError:
                pass  # already gone / never existed
        return True

    def refresh_replica(self):
        """Replica worker resync (see server/workers.py): open
        fragments that appeared on disk since our scan, drop the ones
        whose files vanished, and unload the rest so the next touch
        re-faults the master's current bytes + op tail."""
        with self.mu:
            frag_dir = os.path.join(self.path, "fragments")
            on_disk = set()
            try:
                for entry in os.listdir(frag_dir):
                    if entry.endswith(".cache") or \
                            entry.endswith(".snapshotting") or \
                            entry.endswith(".lock"):
                        continue
                    try:
                        on_disk.add(int(entry))
                    except ValueError:
                        continue
            except FileNotFoundError:
                on_disk = set()
            for slice_num in on_disk - self.fragments.keys():
                self._open_fragment(slice_num)
            for slice_num in list(self.fragments.keys() - on_disk):
                self.fragments.pop(slice_num).close()
        # Resync OUTSIDE the view lock: it takes each fragment's own
        # lock, and a concurrent read holding a fragment lock may be
        # about to take the view lock (fragment getter).
        for frag in list(self.fragments.values()):
            frag.replica_resync()

    # Delegation to the owning fragment (ref: view.go:274-352).

    def set_bit(self, row_id, column_id):
        return self.create_fragment_if_not_exists(
            column_id // SLICE_WIDTH).set_bit(row_id, column_id)

    def bulk_set_bits(self, row_ids, column_ids):
        """Vectorized SetBit burst grouped by slice; returns per-bit
        changed flags in input order."""
        return self._bulk_bits(row_ids, column_ids, set_value=True)

    def bulk_clear_bits(self, row_ids, column_ids):
        """Vectorized ClearBit burst; absent fragments clear nothing."""
        return self._bulk_bits(row_ids, column_ids, set_value=False)

    def _bulk_bits(self, row_ids, column_ids, set_value):
        row_ids = np.asarray(row_ids, dtype=np.uint64)
        column_ids = np.asarray(column_ids, dtype=np.uint64)
        changed = np.zeros(len(row_ids), dtype=bool)
        slices = column_ids // SLICE_WIDTH
        for s in np.unique(slices).tolist():
            sel = slices == s
            if set_value:
                frag = self.create_fragment_if_not_exists(int(s))
                changed[sel] = frag.bulk_set_bits(row_ids[sel],
                                                  column_ids[sel])
            else:
                frag = self.fragment(int(s))
                if frag is not None:
                    changed[sel] = frag.bulk_clear_bits(row_ids[sel],
                                                        column_ids[sel])
        return changed

    def clear_bit(self, row_id, column_id):
        frag = self.fragment(column_id // SLICE_WIDTH)
        return frag.clear_bit(row_id, column_id) if frag else False

    def set_field_value(self, column_id, bit_depth, value):
        return self.create_fragment_if_not_exists(
            column_id // SLICE_WIDTH).set_field_value(column_id, bit_depth, value)

    def field_value(self, column_id, bit_depth):
        frag = self.fragment(column_id // SLICE_WIDTH)
        return frag.field_value(column_id, bit_depth) if frag else (0, False)
