"""Host-memory governor: bounded fragment residency with LRU eviction.

The reference opens a fragment by mmap and lets the OS evict cold pages
(fragment.go:190-247, roaring.go:698-716 zero-copy attach) — host RSS
is naturally bounded by page reclaim. Our fragments materialize dense
row matrices in host RAM, so the equivalent economics need an explicit
governor: every resident fragment registers its host byte usage, access
stamps an LRU clock, and when the configured budget is exceeded the
least-recently-used fragments are unloaded (their matrices and device
mirrors dropped; the roaring file + op log remain the durable source,
so unloading never loses data — the next touch faults the state back
in, exactly like a page fault).

Budget comes from the ``PILOSA_TPU_HOST_BYTES`` env var or the Holder
constructor; None means unlimited (tracking only).
"""
import itertools
import threading
from pilosa_tpu import lockcheck


class HostMemGovernor:
    def __init__(self, budget_bytes=None):
        self.budget = budget_bytes
        self._mu = lockcheck.register("memgov.HostMemGovernor._mu",
                                      threading.Lock())
        self._resident = {}          # fragment -> registered host bytes
        self._clock = itertools.count(1)
        self.evictions = 0           # fragments unloaded by budget
        self.faults = 0              # fragment fault-ins (reloads)
        # Flight recorder (observe.events), server-installed; None
        # when off. One event per eviction sweep, not per victim.
        self.events = None

    def touch(self, frag):
        """Stamp access recency. Lock-free: a torn read of the int
        stamp only perturbs LRU order, never correctness."""
        frag._last_used = next(self._clock)

    def update(self, frag, nbytes):
        """Re-register a fragment's resident byte count (0 = gone) and
        evict LRU fragments while over budget. Victims are unloaded
        OUTSIDE the governor lock and WITHOUT blocking on their
        fragment locks: the caller typically holds its own fragment
        lock, and two threads faulting in concurrently while each
        evicts the other's fragment would otherwise ABBA-deadlock. A
        contended victim is simply skipped (it is busy, hence not LRU
        in spirit) and stays registered for the next update to retry.

        Eviction runs to a LOW-WATER mark (90% of budget), not to the
        budget edge: a working set sitting just over budget would
        otherwise evict exactly one peer per update, whose next read
        re-creates its reader and evicts someone else — perpetual
        one-for-one churn paying an O(N log N) LRU sort per read
        (profiled as the dominant cost of a 9.5k-fragment evicted
        TopN walk at a 64 MB cap). Hysteresis batches that into one
        occasional sweep.
        """
        victims = []
        with self._mu:
            if nbytes:
                self._resident[frag] = nbytes
            else:
                self._resident.pop(frag, None)
            if self.budget is not None:
                total = sum(self._resident.values())
                if total > self.budget:
                    low_water = int(self.budget * 0.9)
                    # Never evict the fragment being registered: it is
                    # mid-operation under its own lock.
                    order = sorted(
                        (f for f in self._resident if f is not frag),
                        key=lambda f: f._last_used)
                    for f in order:
                        if total <= low_water:
                            break
                        b = self._resident.pop(f)
                        total -= b
                        victims.append((f, b))
        evicted = freed = 0
        for f, b in victims:
            out = f.unload(blocking=False)
            if out:  # True: resident state actually dropped
                with self._mu:
                    self.evictions += 1
                evicted += 1
                freed += b
            elif out is None and f._resident:
                # Lock-contended but still resident: re-register so a
                # later pass retries. (out is False — the fragment
                # closed/unloaded itself in the gap — don't resurrect.)
                with self._mu:
                    self._resident.setdefault(f, b)
        if evicted:
            ev = self.events
            if ev is not None:
                ev.emit("governor.evict", fragments=evicted,
                        bytes=freed)

    def resident_bytes(self):
        with self._mu:
            return sum(self._resident.values())

    def pressure(self):
        """Resident/budget fraction, the autopilot tiering loop's
        sensor; None when unbounded (tracking-only governor)."""
        with self._mu:
            if not self.budget:
                return None
            return sum(self._resident.values()) / self.budget

    def coldest(self, limit, hot=()):
        """The ``limit`` least-recently-used resident fragments,
        skipping any whose (index, slice) is in ``hot`` — the
        autopilot's demotion candidates. Read-only: callers unload
        OUTSIDE the governor lock, exactly like the eviction sweep."""
        hot = set(hot)
        with self._mu:
            order = sorted(self._resident, key=lambda f: f._last_used)
        return [f for f in order
                if (f.index, f.slice) not in hot][:limit]

    def resident_fragments(self):
        """Snapshot of every registered-resident fragment (the
        autopilot pre-stage walk)."""
        with self._mu:
            return list(self._resident)

    def note_fault(self):
        with self._mu:
            self.faults += 1

    def resident_count(self):
        with self._mu:
            return len(self._resident)

    def snapshot(self):
        """Gauges for /debug/vars."""
        with self._mu:
            return {
                "budgetBytes": self.budget or 0,
                "residentBytes": sum(self._resident.values()),
                "residentFragments": len(self._resident),
                "evictions": self.evictions,
                "faults": self.faults,
            }
