"""Hand-written proto3 wire codec for the reference's public messages.

Field numbers and types follow internal/public.proto exactly (Bitmap:1-3,
Pair, SumCount, Attr:1-6, QueryRequest:1-7, QueryResponse:1-3,
QueryResult:1-6, ImportRequest:1-8, ImportValueRequest:1-7) so existing
pilosa protobuf clients interoperate. Implemented from the proto3 wire
spec (varint / 64-bit / length-delimited); no generated code.
"""
import struct

# Attr.Type values (ref: attr.go:38-41)
ATTR_STRING, ATTR_INT, ATTR_BOOL, ATTR_FLOAT = 1, 2, 3, 4

# QueryResult.Type values (ref: handler.go:1652-1658)
RESULT_NIL, RESULT_BITMAP, RESULT_PAIRS = 0, 1, 2
RESULT_SUMCOUNT, RESULT_UINT64, RESULT_BOOL = 3, 4, 5

_WIRE_VARINT, _WIRE_64, _WIRE_LEN, _WIRE_32 = 0, 1, 2, 5


# --------------------------------------------------------------- primitives

def _varint(n):
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf, i):
    shift, val = 0, 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


def _key(field, wire):
    return _varint((field << 3) | wire)


def _tag_varint(field, value):
    if value is None:
        return b""
    return _key(field, _WIRE_VARINT) + _varint(int(value))


def _tag_bytes(field, data):
    return _key(field, _WIRE_LEN) + _varint(len(data)) + data


def _tag_string(field, s):
    return _tag_bytes(field, s.encode()) if s else b""


def _pack_varints_np(values):
    """Packed-varint payload built with NumPy: 7-bit chunks of every
    value computed as one [n, 10] matrix, then masked flat in order.
    ~40× the scalar loop on bulk-import payloads."""
    import numpy as np

    # Two's-complement mask like the scalar _varint (BSI values may be
    # negative; np.asarray(dtype=uint64) would raise on those).
    a = np.asarray(values)
    if a.dtype.kind == "i":
        v = a.astype(np.int64, copy=False).view(np.uint64)
    elif a.dtype.kind == "u":
        v = a.astype(np.uint64, copy=False)
    else:  # object dtype: ints outside [0, 2^64) — mask elementwise
        v = np.asarray([int(x) & ((1 << 64) - 1) for x in values],
                       dtype=np.uint64)
    if v.size == 0:
        return b""
    # Width = bytes the largest value needs (≤10); the chunk matrix is
    # the dominant cost and most payloads are small ids.
    width = max(1, (int(v.max()).bit_length() + 6) // 7)
    shifts = np.uint64(7) * np.arange(width, dtype=np.uint64)
    chunks = (v[:, None] >> shifts[None, :]) & np.uint64(0x7F)
    nonzero = chunks != 0
    nbytes = width - np.argmax(nonzero[:, ::-1], axis=1)
    nbytes = np.where(nonzero.any(axis=1), nbytes, 1)
    pos = np.arange(width)[None, :]
    keep = pos < nbytes[:, None]
    cont = pos < (nbytes - 1)[:, None]
    out = chunks.astype(np.uint8) | (cont.astype(np.uint8) << 7)
    return out[keep].tobytes()


def _unpack_varints_np(buf):
    """Decode a packed-varint payload with NumPy (inverse of
    _pack_varints_np). Returns a uint64 array, or None to request the
    scalar fallback (10-byte varints, i.e. values ≥ 2^63)."""
    import numpy as np

    b = np.frombuffer(buf, dtype=np.uint8)
    if b.size == 0:
        return np.zeros(0, dtype=np.uint64)
    ends = (b & 0x80) == 0
    if not ends[-1]:
        raise ValueError("truncated varint")
    idx = np.nonzero(ends)[0]
    starts = np.empty_like(idx)
    starts[0] = 0
    starts[1:] = idx[:-1] + 1
    if int((idx - starts).max()) > 8:
        return None  # ≥10-byte varint: 7*9=63-bit shifts would overflow
    group_start = np.repeat(starts, idx - starts + 1)
    k = (np.arange(b.size) - group_start).astype(np.uint64)
    contrib = (b.astype(np.uint64) & np.uint64(0x7F)) << (np.uint64(7) * k)
    return np.add.reduceat(contrib, starts)


def _tag_packed_varints(field, values):
    if values is None or (hasattr(values, "__len__") and len(values) == 0):
        return b""
    if len(values) >= 64:
        payload = _pack_varints_np(values)
    else:
        payload = b"".join(_varint(int(v)) for v in values)
    return _tag_bytes(field, payload)


def _tag_double(field, value):
    return _key(field, _WIRE_64) + struct.pack("<d", value)


def _signed(v):
    """proto3 int64 decode: values > 2^63 are negative."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _walk(data):
    """Yield (field, wire, value) triples; value is int or bytes."""
    i = 0
    while i < len(data):
        key, i = _read_varint(data, i)
        field, wire = key >> 3, key & 7
        if wire == _WIRE_VARINT:
            val, i = _read_varint(data, i)
        elif wire == _WIRE_64:
            val = data[i : i + 8]
            i += 8
        elif wire == _WIRE_LEN:
            ln, i = _read_varint(data, i)
            val = data[i : i + ln]
            i += ln
        elif wire == _WIRE_32:
            val = data[i : i + 4]
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def _repeated_uint64(fields, field_no):
    """Handle both packed and unpacked repeated uint64."""
    out = []
    for field, wire, val in fields:
        if field != field_no:
            continue
        if wire == _WIRE_VARINT:
            out.append(val)
        else:
            vals = _unpack_varints_np(val) if len(val) >= 64 else None
            if vals is not None:
                out.extend(vals.tolist())
            else:
                i = 0
                while i < len(val):
                    v, i = _read_varint(val, i)
                    out.append(v)
    return out


# -------------------------------------------------------------------- Attr

def encode_attr(key, value):
    # Zero/false/empty payloads are ELIDED (proto3 canonical form — the
    # Type field still identifies the kind, and decoders default the
    # missing value field to zero).
    out = _tag_string(1, key)
    if isinstance(value, bool):
        out += _tag_varint(2, ATTR_BOOL) + (_tag_varint(5, 1) if value
                                            else b"")
    elif isinstance(value, int):
        out += _tag_varint(2, ATTR_INT) + _tag_varint(4, value or None)
    elif isinstance(value, float):
        # Only POSITIVE zero is the proto3 default; -0.0 has a distinct
        # bit pattern and the official runtime serializes it.
        is_default = struct.pack("<d", value) == b"\x00" * 8
        out += _tag_varint(2, ATTR_FLOAT) + (b"" if is_default
                                             else _tag_double(6, value))
    else:
        out += _tag_varint(2, ATTR_STRING) + _tag_string(3, str(value))
    return out


def decode_attr(data):
    key, typ, sval, ival, bval, fval = "", 0, "", 0, False, 0.0
    for field, wire, val in _walk(data):
        if field == 1:
            key = val.decode()
        elif field == 2:
            typ = val
        elif field == 3:
            sval = val.decode()
        elif field == 4:
            ival = _signed(val)
        elif field == 5:
            bval = bool(val)
        elif field == 6:
            fval = struct.unpack("<d", val)[0]
    if typ == ATTR_BOOL:
        return key, bval
    if typ == ATTR_INT:
        return key, ival
    if typ == ATTR_FLOAT:
        return key, fval
    return key, sval


def _encode_attrs(attrs):
    return b"".join(_tag_bytes(2, encode_attr(k, v))
                    for k, v in sorted(attrs.items()))


def _decode_attrs(fields, field_no=2):
    out = {}
    for field, _, val in fields:
        if field == field_no:
            k, v = decode_attr(val)
            out[k] = v
    return out


# ---------------------------------------------------------------- messages

def encode_bitmap(columns, attrs=None):
    return _tag_packed_varints(1, columns) + _encode_attrs(attrs or {})


def decode_bitmap(data):
    fields = list(_walk(data))
    return {"bits": _repeated_uint64(fields, 1),
            "attrs": _decode_attrs(fields)}


def encode_pair(row_id, count):
    return _tag_varint(1, row_id or None) + _tag_varint(2, count or None)


def decode_pair(data):
    rid = cnt = 0
    for field, _, val in _walk(data):
        if field == 1:
            rid = val
        elif field == 2:
            cnt = val
    return rid, cnt


def encode_sum_count(s, c):
    return _tag_varint(1, s or None) + _tag_varint(2, c or None)


def decode_sum_count(data):
    s = c = 0
    for field, _, val in _walk(data):
        if field == 1:
            s = _signed(val)
        elif field == 2:
            c = _signed(val)
    return s, c


def encode_query_request(query, slices=None, column_attrs=False, remote=False,
                         exclude_attrs=False, exclude_bits=False):
    out = _tag_string(1, query)
    out += _tag_packed_varints(2, slices or [])
    if column_attrs:
        out += _tag_varint(3, 1)
    if remote:
        out += _tag_varint(5, 1)
    if exclude_attrs:
        out += _tag_varint(6, 1)
    if exclude_bits:
        out += _tag_varint(7, 1)
    return out


def decode_query_request(data):
    fields = list(_walk(data))
    req = {"query": "", "slices": [], "column_attrs": False, "remote": False,
           "exclude_attrs": False, "exclude_bits": False}
    for field, wire, val in fields:
        if field == 1:
            req["query"] = val.decode()
        elif field == 3:
            req["column_attrs"] = bool(val)
        elif field == 5:
            req["remote"] = bool(val)
        elif field == 6:
            req["exclude_attrs"] = bool(val)
        elif field == 7:
            req["exclude_bits"] = bool(val)
    req["slices"] = _repeated_uint64(fields, 2)
    return req


def encode_query_result(result):
    # Canonical proto3 byte layout (matches the official runtime, which
    # serializes in FIELD-NUMBER order): the payload field — Bitmap:1,
    # N:2, Pairs:3, Changed:4, SumCount:5 — precedes Type:6, and
    # default values (Type 0 for nil, false, 0) are elided entirely.
    from pilosa_tpu.bitmap import Bitmap
    from pilosa_tpu.executor import SumCount

    if isinstance(result, Bitmap):
        return (_tag_bytes(1, encode_bitmap(result.columns().tolist(),
                                            result.attrs))
                + _tag_varint(6, RESULT_BITMAP))
    if isinstance(result, SumCount):
        return (_tag_bytes(5, encode_sum_count(result.sum, result.count))
                + _tag_varint(6, RESULT_SUMCOUNT))
    if isinstance(result, bool):
        return ((_tag_varint(4, 1) if result else b"")
                + _tag_varint(6, RESULT_BOOL))
    if isinstance(result, int):
        return _tag_varint(2, result or None) + _tag_varint(6, RESULT_UINT64)
    if isinstance(result, list):
        return (b"".join(_tag_bytes(3, encode_pair(r, c)) for r, c in result)
                + _tag_varint(6, RESULT_PAIRS))
    return b""  # nil: Type 0 elided → empty message


def decode_query_result(data):
    from pilosa_tpu.executor import SumCount

    typ = RESULT_NIL
    bitmap = None
    n = 0
    pairs = []
    sumcount = (0, 0)
    changed = False
    for field, wire, val in _walk(data):
        if field == 6:
            typ = val
        elif field == 1:
            bitmap = decode_bitmap(val)
        elif field == 2:
            n = val
        elif field == 3:
            pairs.append(decode_pair(val))
        elif field == 5:
            sumcount = decode_sum_count(val)
        elif field == 4:
            changed = bool(val)
    if typ == RESULT_BITMAP:
        return bitmap or {"bits": [], "attrs": {}}
    if typ == RESULT_PAIRS:
        return pairs
    if typ == RESULT_SUMCOUNT:
        return SumCount(*sumcount)
    if typ == RESULT_UINT64:
        return n
    if typ == RESULT_BOOL:
        return changed
    return None


def encode_query_response(results, error=None):
    out = _tag_string(1, error or "")
    for r in results:
        out += _tag_bytes(2, encode_query_result(r))
    return out


def decode_query_response(data):
    err = ""
    results = []
    for field, wire, val in _walk(data):
        if field == 1:
            err = val.decode()
        elif field == 2:
            results.append(decode_query_result(val))
    return {"error": err or None, "results": results}


def encode_import_request(index, frame, slice_num, row_ids, column_ids,
                          timestamps=None, row_keys=None, column_keys=None):
    """ImportRequest (public.proto:70-80). RowKeys/ColumnKeys (fields
    7/8) are the keyed-import variant's payload — carried for wire
    parity; the reference server at this version ignores them
    (handler.go handlePostImport reads only the ID fields)."""
    out = _tag_string(1, index) + _tag_string(2, frame)
    out += _tag_varint(3, slice_num or None)
    out += _tag_packed_varints(4, row_ids)
    out += _tag_packed_varints(5, column_ids)
    out += _tag_packed_varints(6, timestamps or [])
    # NB: _tag_string drops empty strings (proto3 default-value
    # elision), but row/column keys pair positionally — an elided empty
    # key would misalign every pair after it, so emit explicitly.
    for key in row_keys or []:
        out += _tag_bytes(7, key.encode())
    for key in column_keys or []:
        out += _tag_bytes(8, key.encode())
    return out


def decode_import_request(data):
    fields = list(_walk(data))
    req = {"index": "", "frame": "", "slice": 0,
           "rowKeys": [], "columnKeys": []}
    for field, wire, val in fields:
        if field == 1:
            req["index"] = val.decode()
        elif field == 2:
            req["frame"] = val.decode()
        elif field == 3:
            req["slice"] = val
        elif field == 7:
            req["rowKeys"].append(val.decode())
        elif field == 8:
            req["columnKeys"].append(val.decode())
    req["rowIDs"] = _repeated_uint64(fields, 4)
    req["columnIDs"] = _repeated_uint64(fields, 5)
    req["timestamps"] = [_signed(t) for t in _repeated_uint64(fields, 6)]
    return req


def encode_import_value_request(index, frame, slice_num, field_name,
                                column_ids, values):
    out = _tag_string(1, index) + _tag_string(2, frame)
    out += _tag_varint(3, slice_num or None) + _tag_string(4, field_name)
    out += _tag_packed_varints(5, column_ids)
    out += _tag_packed_varints(6, values)
    return out


def decode_import_value_request(data):
    fields = list(_walk(data))
    req = {"index": "", "frame": "", "slice": 0, "field": ""}
    for field, wire, val in fields:
        if field == 1:
            req["index"] = val.decode()
        elif field == 2:
            req["frame"] = val.decode()
        elif field == 3:
            req["slice"] = val
        elif field == 4:
            req["field"] = val.decode()
    req["columnIDs"] = _repeated_uint64(fields, 5)
    req["values"] = [_signed(v) for v in _repeated_uint64(fields, 6)]
    return req


# ----------------------------------------------- private.proto messages
# (internal/private.proto:5-153; field numbers kept exactly so reference
# nodes/tooling interoperate with the cluster sync plane.)

# Broadcast envelope message types (ref: broadcast.go:126-137).
MSG_CREATE_SLICE = 1
MSG_CREATE_INDEX = 2
MSG_DELETE_INDEX = 3
MSG_CREATE_FRAME = 4
MSG_DELETE_FRAME = 5
MSG_CREATE_INPUT_DEFINITION = 6
MSG_DELETE_INPUT_DEFINITION = 7
MSG_DELETE_VIEW = 8
MSG_CREATE_FIELD = 9
MSG_DELETE_FIELD = 10
# In-house extension (no reference analog): full placement state for
# the elastic-topology resize protocol (cluster/placement.py). The
# payload is the state dict as one JSON string field — placement
# messages are rare (a handful per resize), so wire compactness is
# irrelevant next to forward-compatibility of the state shape.
MSG_PLACEMENT_STATE = 64


def _encode_index_meta(opts):
    """IndexMeta{ColumnLabel:1, TimeQuantum:2}."""
    return (_tag_string(1, opts.get("columnLabel", ""))
            + _tag_string(2, opts.get("timeQuantum", "")))


def _decode_index_meta(data):
    out = {"columnLabel": "", "timeQuantum": ""}
    for field, _, val in _walk(data):
        if field == 1:
            out["columnLabel"] = val.decode()
        elif field == 2:
            out["timeQuantum"] = val.decode()
    return out


def _encode_schema_field(fd):
    """Field{Name:1, Type:2, Min:3, Max:4} (private.proto:142-147)."""
    return (_tag_string(1, fd.get("name", ""))
            + _tag_string(2, fd.get("type", ""))
            + _tag_varint(3, fd.get("min", 0) or None)
            + _tag_varint(4, fd.get("max", 0) or None))


def _decode_schema_field(data):
    out = {"name": "", "type": "", "min": 0, "max": 0}
    for field, _, val in _walk(data):
        if field == 1:
            out["name"] = val.decode()
        elif field == 2:
            out["type"] = val.decode()
        elif field == 3:
            out["min"] = _signed(val)
        elif field == 4:
            out["max"] = _signed(val)
    return out


def _encode_frame_meta(opts):
    """FrameMeta{RowLabel:1, InverseEnabled:2, CacheType:3,
    CacheSize:4, TimeQuantum:5, RangeEnabled:6, Fields:7}."""
    out = _tag_string(1, opts.get("rowLabel", ""))
    if opts.get("inverseEnabled"):
        out += _tag_varint(2, 1)
    out += _tag_string(3, opts.get("cacheType", ""))
    out += _tag_varint(4, opts.get("cacheSize", 0) or None)
    out += _tag_string(5, opts.get("timeQuantum", ""))
    if opts.get("rangeEnabled"):
        out += _tag_varint(6, 1)
    for fd in opts.get("fields", []) or []:
        out += _tag_bytes(7, _encode_schema_field(fd))
    return out


def _decode_frame_meta(data):
    out = {"rowLabel": "", "inverseEnabled": False, "cacheType": "",
           "cacheSize": 0, "timeQuantum": "", "rangeEnabled": False,
           "fields": []}
    for field, _, val in _walk(data):
        if field == 1:
            out["rowLabel"] = val.decode()
        elif field == 2:
            out["inverseEnabled"] = bool(val)
        elif field == 3:
            out["cacheType"] = val.decode()
        elif field == 4:
            out["cacheSize"] = val
        elif field == 5:
            out["timeQuantum"] = val.decode()
        elif field == 6:
            out["rangeEnabled"] = bool(val)
        elif field == 7:
            out["fields"].append(_decode_schema_field(val))
    return out


def _encode_str_u64_map(field_no, mapping):
    """map<string, uint64> — one length-delimited entry per key, keys
    sorted for deterministic bytes (Go map order is random; sorting is
    wire-compatible and testable)."""
    out = b""
    for k in sorted(mapping):
        out += _tag_bytes(field_no,
                          _tag_string(1, k) + _tag_varint(2, mapping[k]))
    return out


def _decode_str_u64_map(fields, field_no):
    out = {}
    for field, _, val in fields:
        if field != field_no:
            continue
        k, v = "", 0
        for f2, _, v2 in _walk(val):
            if f2 == 1:
                k = v2.decode()
            elif f2 == 2:
                v = v2
        out[k] = v
    return out


def _encode_input_action(a):
    """InputDefinitionAction{Frame:1, ValueDestination:2, ValueMap:3,
    RowID:4}."""
    out = _tag_string(1, a.get("frame", ""))
    out += _tag_string(2, a.get("valueDestination", ""))
    out += _encode_str_u64_map(3, a.get("valueMap", {}) or {})
    if a.get("rowID") is not None:
        out += _tag_varint(4, a["rowID"])
    return out


def _decode_input_action(data):
    fields = list(_walk(data))
    out = {"frame": "", "valueDestination": ""}
    for field, _, val in fields:
        if field == 1:
            out["frame"] = val.decode()
        elif field == 2:
            out["valueDestination"] = val.decode()
        elif field == 4:
            out["rowID"] = val
    vm = _decode_str_u64_map(fields, 3)
    if vm:
        out["valueMap"] = vm
    return out


def _encode_input_field(f):
    """InputDefinitionField{Name:1, PrimaryKey:2, Actions:3}."""
    out = _tag_string(1, f.get("name", ""))
    if f.get("primaryKey"):
        out += _tag_varint(2, 1)
    for a in f.get("actions", []) or []:
        out += _tag_bytes(3, _encode_input_action(a))
    return out


def _decode_input_field(data):
    out = {"name": "", "primaryKey": False, "actions": []}
    for field, _, val in _walk(data):
        if field == 1:
            out["name"] = val.decode()
        elif field == 2:
            out["primaryKey"] = bool(val)
        elif field == 3:
            out["actions"].append(_decode_input_action(val))
    return out


def _encode_schema_frame(fr):
    """Frame{Name:1, Meta:2}."""
    out = _tag_string(1, fr.get("name", ""))
    meta = fr.get("options") or fr.get("meta")
    if meta:
        out += _tag_bytes(2, _encode_frame_meta(meta))
    return out


def _decode_schema_frame(data):
    out = {"name": ""}
    for field, _, val in _walk(data):
        if field == 1:
            out["name"] = val.decode()
        elif field == 2:
            out["options"] = _decode_frame_meta(val)
    return out


def _encode_input_definition(name, d):
    """InputDefinition{Name:1, Frames:2, Fields:3}."""
    out = _tag_string(1, name)
    for fr in d.get("frames", []) or []:
        out += _tag_bytes(2, _encode_schema_frame(fr))
    for f in d.get("fields", []) or []:
        out += _tag_bytes(3, _encode_input_field(f))
    return out


def _decode_input_definition(data):
    name = ""
    d = {"frames": [], "fields": []}
    for field, _, val in _walk(data):
        if field == 1:
            name = val.decode()
        elif field == 2:
            d["frames"].append(_decode_schema_frame(val))
        elif field == 3:
            d["fields"].append(_decode_input_field(val))
    return name, d


def encode_cluster_message(msg):
    """JSON-shaped broadcast dict → reference envelope (1 type byte +
    protobuf; ref: MarshalMessage broadcast.go:139-173)."""
    t = msg.get("type")
    if t == "create-slice":
        body = (_tag_string(1, msg["index"]) + _tag_varint(2, msg["slice"])
                + (_tag_varint(3, 1) if msg.get("inverse") else b""))
        typ = MSG_CREATE_SLICE
    elif t == "create-index":
        body = _tag_string(1, msg["index"])
        meta = _encode_index_meta(msg.get("options", {}) or {})
        if meta:
            body += _tag_bytes(2, meta)
        typ = MSG_CREATE_INDEX
    elif t == "delete-index":
        body = _tag_string(1, msg["index"])
        typ = MSG_DELETE_INDEX
    elif t == "create-frame":
        body = _tag_string(1, msg["index"]) + _tag_string(2, msg["frame"])
        meta = _encode_frame_meta(msg.get("options", {}) or {})
        if meta:
            body += _tag_bytes(3, meta)
        typ = MSG_CREATE_FRAME
    elif t == "delete-frame":
        body = _tag_string(1, msg["index"]) + _tag_string(2, msg["frame"])
        typ = MSG_DELETE_FRAME
    elif t == "create-field":
        body = (_tag_string(1, msg["index"]) + _tag_string(2, msg["frame"])
                + _tag_bytes(3, _encode_schema_field(msg["field"])))
        typ = MSG_CREATE_FIELD
    elif t == "delete-field":
        body = (_tag_string(1, msg["index"]) + _tag_string(2, msg["frame"])
                + _tag_string(3, msg["field"]))
        typ = MSG_DELETE_FIELD
    elif t == "delete-view":
        body = (_tag_string(1, msg["index"]) + _tag_string(2, msg["frame"])
                + _tag_string(3, msg["view"]))
        typ = MSG_DELETE_VIEW
    elif t == "create-input-definition":
        body = _tag_string(1, msg["index"]) + _tag_bytes(
            3, _encode_input_definition(msg["name"],
                                        msg.get("definition", {})))
        typ = MSG_CREATE_INPUT_DEFINITION
    elif t == "delete-input-definition":
        body = _tag_string(1, msg["index"]) + _tag_string(2, msg["name"])
        typ = MSG_DELETE_INPUT_DEFINITION
    elif t == "placement-state":
        import json as _json

        body = _tag_string(1, _json.dumps(msg.get("state") or {}))
        typ = MSG_PLACEMENT_STATE
    else:
        raise ValueError(f"message type not implemented: {t}")
    return bytes([typ]) + body


def decode_cluster_message(data):
    """Reference envelope → the JSON-shaped dict receive_message eats
    (ref: UnmarshalMessage broadcast.go:175-196)."""
    if not data:
        raise ValueError("empty cluster message")
    typ, body = data[0], data[1:]
    fields = list(_walk(body))

    def s(field_no):
        for f, _, v in fields:
            if f == field_no:
                return v.decode()
        return ""

    def u(field_no):
        for f, _, v in fields:
            if f == field_no:
                return v
        return 0

    def sub(field_no):
        for f, _, v in fields:
            if f == field_no:
                return v
        return b""

    if typ == MSG_CREATE_SLICE:
        return {"type": "create-slice", "index": s(1), "slice": u(2),
                "inverse": bool(u(3))}
    if typ == MSG_CREATE_INDEX:
        return {"type": "create-index", "index": s(1),
                "options": _decode_index_meta(sub(2))}
    if typ == MSG_DELETE_INDEX:
        return {"type": "delete-index", "index": s(1)}
    if typ == MSG_CREATE_FRAME:
        return {"type": "create-frame", "index": s(1), "frame": s(2),
                "options": _decode_frame_meta(sub(3))}
    if typ == MSG_DELETE_FRAME:
        return {"type": "delete-frame", "index": s(1), "frame": s(2)}
    if typ == MSG_CREATE_FIELD:
        return {"type": "create-field", "index": s(1), "frame": s(2),
                "field": _decode_schema_field(sub(3))}
    if typ == MSG_DELETE_FIELD:
        return {"type": "delete-field", "index": s(1), "frame": s(2),
                "field": s(3)}
    if typ == MSG_DELETE_VIEW:
        return {"type": "delete-view", "index": s(1), "frame": s(2),
                "view": s(3)}
    if typ == MSG_CREATE_INPUT_DEFINITION:
        name, d = _decode_input_definition(sub(3))
        return {"type": "create-input-definition", "index": s(1),
                "name": name, "definition": d}
    if typ == MSG_DELETE_INPUT_DEFINITION:
        return {"type": "delete-input-definition", "index": s(1),
                "name": s(2)}
    if typ == MSG_PLACEMENT_STATE:
        import json as _json

        try:
            state = _json.loads(s(1) or "{}")
        except ValueError:
            raise ValueError("malformed placement-state payload")
        return {"type": "placement-state", "state": state}
    raise ValueError(f"unknown cluster message type {typ}")


# BlockData sync endpoints (private.proto:24-35; client.go:923-1011).

def encode_block_data_request(index, frame, view, slice_num, block):
    """BlockDataRequest{Index:1, Frame:2, Block:3, Slice:4, View:5}."""
    return (_tag_string(1, index) + _tag_string(2, frame)
            + _tag_varint(3, block or None) + _tag_varint(4, slice_num or None)
            + _tag_string(5, view))


def decode_block_data_request(data):
    out = {"index": "", "frame": "", "view": "", "slice": 0, "block": 0}
    for field, _, val in _walk(data):
        if field == 1:
            out["index"] = val.decode()
        elif field == 2:
            out["frame"] = val.decode()
        elif field == 3:
            out["block"] = val
        elif field == 4:
            out["slice"] = val
        elif field == 5:
            out["view"] = val.decode()
    return out


def encode_block_data_response(row_ids, column_ids):
    """BlockDataResponse{RowIDs:1, ColumnIDs:2} (packed)."""
    return (_tag_packed_varints(1, row_ids)
            + _tag_packed_varints(2, column_ids))


def decode_block_data_response(data):
    fields = list(_walk(data))
    return (_repeated_uint64(fields, 1), _repeated_uint64(fields, 2))


def encode_max_slices_response(max_slices):
    """MaxSlicesResponse{MaxSlices:1 map<string,uint64>}."""
    return _encode_str_u64_map(1, max_slices)


def decode_max_slices_response(data):
    return _decode_str_u64_map(list(_walk(data)), 1)


# NodeStatus / ClusterStatus (private.proto:127-136) — the gossip
# state-exchange payload; ours rides the same bytes over HTTP.

def encode_schema_index(idx):
    """Index{Name:1, Meta:2, MaxSlice:3, Frames:4, Slices:5,
    InputDefinitions:6}."""
    out = _tag_string(1, idx.get("name", ""))
    meta = idx.get("options") or idx.get("meta")
    if meta:
        out += _tag_bytes(2, _encode_index_meta(meta))
    out += _tag_varint(3, idx.get("maxSlice", 0) or None)
    for fr in idx.get("frames", []) or []:
        out += _tag_bytes(4, _encode_schema_frame(fr))
    out += _tag_packed_varints(5, idx.get("slices", []) or [])
    for name, d in sorted((idx.get("inputDefinitions") or {}).items()):
        out += _tag_bytes(6, _encode_input_definition(name, d))
    return out


def decode_schema_index(data):
    fields = list(_walk(data))
    out = {"name": "", "frames": [], "inputDefinitions": {}}
    for field, _, val in fields:
        if field == 1:
            out["name"] = val.decode()
        elif field == 2:
            out["options"] = _decode_index_meta(val)
        elif field == 3:
            out["maxSlice"] = val
        elif field == 4:
            out["frames"].append(_decode_schema_frame(val))
        elif field == 6:
            name, d = _decode_input_definition(val)
            out["inputDefinitions"][name] = d
    slices = _repeated_uint64(fields, 5)
    if slices:
        out["slices"] = slices
    return out


def encode_node_status(status):
    """NodeStatus{Host:1, State:2, Indexes:3, Scheme:4}."""
    out = _tag_string(1, status.get("host", ""))
    out += _tag_string(2, status.get("state", ""))
    for idx in status.get("indexes", []) or []:
        out += _tag_bytes(3, encode_schema_index(idx))
    out += _tag_string(4, status.get("scheme", ""))
    return out


def decode_node_status(data):
    out = {"host": "", "state": "", "scheme": "", "indexes": []}
    for field, _, val in _walk(data):
        if field == 1:
            out["host"] = val.decode()
        elif field == 2:
            out["state"] = val.decode()
        elif field == 3:
            out["indexes"].append(decode_schema_index(val))
        elif field == 4:
            out["scheme"] = val.decode()
    return out


def encode_cluster_status(nodes):
    """ClusterStatus{Nodes:1}."""
    return b"".join(_tag_bytes(1, encode_node_status(n)) for n in nodes)


def decode_cluster_status(data):
    return [decode_node_status(val) for field, _, val in _walk(data)
            if field == 1]
