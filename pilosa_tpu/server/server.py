"""Server assembly: holder + executor + handler + HTTP + background
monitors (ref: server.go:55-234, server/server.go:52-249).
"""
import logging
import threading
import time

from pilosa_tpu import __version__, tracing
from pilosa_tpu import faults as faults_mod
from pilosa_tpu import qos as qos_mod
from pilosa_tpu import stats as stats_mod
from pilosa_tpu.config import DEFAULT_MAX_BODY_SIZE
from pilosa_tpu.cluster.broadcast import HTTPBroadcaster, NopBroadcaster, StaticNodeSet
from pilosa_tpu.cluster.client import InternalClient
from pilosa_tpu.cluster.cluster import Cluster, Node
from pilosa_tpu.cluster.syncer import HolderSyncer
from pilosa_tpu.executor import Executor
from pilosa_tpu.server.handler import Handler, make_http_server
from pilosa_tpu.stats import new_stats_client
from pilosa_tpu.storage.holder import Holder

DEFAULT_ANTI_ENTROPY_INTERVAL = 600   # 10 min (ref: server.go:44)
DEFAULT_POLLING_INTERVAL = 60         # max-slice poll (ref: server.go:321)
DEFAULT_CACHE_FLUSH_INTERVAL = 600    # (ref: holder.go:340)
DEFAULT_DRAIN_TIMEOUT = 5.0           # close()/SIGTERM in-flight wait
# How long a LEAVING node's close() waits for the in-flight resize to
# finish handing its slices off before shutting down anyway.
DEFAULT_REBALANCE_DRAIN_TIMEOUT = 30.0

_LOG = logging.getLogger("pilosa_tpu.server")


class Server:
    def __init__(self, data_dir, bind="localhost:10101", cluster_hosts=None,
                 replica_n=1, max_writes_per_request=5000,
                 anti_entropy_interval=DEFAULT_ANTI_ENTROPY_INTERVAL,
                 polling_interval=DEFAULT_POLLING_INTERVAL,
                 metric_service="expvar", metric_host="127.0.0.1:8125",
                 long_query_time=None, tls_cert=None, tls_key=None,
                 tls_skip_verify=False, host_bytes=None, workers=None,
                 trace_enabled=None, trace_slow_threshold=None,
                 trace_ring_size=None, trace_slow_ring_size=None,
                 qos=None, max_body_size=None, faults=None,
                 drain_timeout=None, metrics=None, epoch_probe_ttl=None,
                 executor=None, storage=None, ingest=None, planner=None,
                 rebalance_stream_concurrency=None,
                 rebalance_bandwidth=None,
                 rebalance_drain_timeout=None,
                 observe=None, profile=None, slo=None, mesh=None,
                 autopilot=None, hedge=None):
        self.data_dir = data_dir
        self.bind = bind
        self.host = bind
        # TLS (ref: server.go:128-134 tls.NewListener; config.go TLS
        # {certificate, key, skip-verify}).
        self.tls_cert = tls_cert
        self.tls_key = tls_key
        self.tls_skip_verify = tls_skip_verify
        self.scheme = "https" if tls_cert else "http"
        self.holder = Holder(data_dir, host_bytes=host_bytes or None)
        self.stats = new_stats_client(metric_service, metric_host)
        self.holder.stats = self.stats

        # Distributed query tracing (tracing.py): off by default — the
        # nop tracer keeps the serving path allocation-free, the same
        # pattern as NopStatsClient. PILOSA_TRACE_ENABLED=1 or the
        # [trace] config section turns it on.
        import os as _os

        if trace_enabled is None:
            trace_enabled = _os.environ.get(
                "PILOSA_TRACE_ENABLED", "").lower() in ("1", "true", "yes")
        if trace_slow_threshold is None:
            # Mirror config.py's documented env override for direct
            # Server() construction (tests, embedding) — the CLI path
            # already resolved it through Config._apply_env.
            env_thr = _os.environ.get("PILOSA_TRACE_SLOW_THRESHOLD")
            if env_thr:
                try:
                    trace_slow_threshold = float(env_thr)
                except ValueError:
                    pass
        if trace_enabled:
            self.tracer = tracing.Tracer(
                ring_size=trace_ring_size or tracing.DEFAULT_RING_SIZE,
                slow_threshold=(trace_slow_threshold
                                if trace_slow_threshold is not None
                                else tracing.DEFAULT_SLOW_THRESHOLD),
                slow_ring_size=(trace_slow_ring_size
                                or tracing.DEFAULT_SLOW_RING_SIZE),
                stats=self.stats)
        else:
            self.tracer = tracing.NOP

        # QoS & admission control (qos.py): off by default — the nop
        # tier keeps the serving path lock- and allocation-free, the
        # same pattern as the nop tracer. ``qos`` is the [qos] config
        # table (a plain dict; Python-underscore keys accepted too for
        # direct Server() construction); PILOSA_QOS_ENABLED=1 flips it
        # on with defaults.
        qcfg = {k.replace("_", "-"): v for k, v in (qos or {}).items()}
        qos_enabled = qcfg.get("enabled")
        if qos_enabled is None:
            qos_enabled = _os.environ.get(
                "PILOSA_QOS_ENABLED", "").lower() in ("1", "true", "yes")
        if qos_enabled:
            # Only keys actually present are forwarded — defaults live
            # in ONE place (qos.QoS.__init__), so a default change
            # can't drift between the config path and direct Server()
            # construction.
            key_map = {"max-concurrent": "max_concurrent",
                       "queue-length": "queue_length",
                       "queue-timeout": "queue_timeout",
                       "default-deadline": "default_deadline",
                       "client-qps": "client_qps",
                       "client-burst": "client_burst",
                       "quotas": "client_overrides",
                       "breaker-threshold": "breaker_threshold",
                       "breaker-cooldown": "breaker_cooldown"}
            self.qos = qos_mod.QoS(**{
                py: qcfg[k] for k, py in key_map.items() if k in qcfg})
        else:
            self.qos = qos_mod.NOP
        self.max_body_size = (max_body_size if max_body_size is not None
                              else int(_os.environ.get(
                                  "PILOSA_MAX_BODY_SIZE",
                                  DEFAULT_MAX_BODY_SIZE)))

        # Runtime telemetry ([metrics] config table): tagged histogram
        # families on /metrics, the process-telemetry collector, and
        # /cluster/metrics aggregation. Histograms default ON (an
        # observation is a bisect + three integer adds); disabling
        # restores the single-nop-attribute-read hot path — same
        # discipline as qos.NOP/faults, verified by test.
        mcfg = {k.replace("_", "-"): v for k, v in (metrics or {}).items()}
        hist_on = mcfg.get("histograms")
        if hist_on is None:
            env_h = _os.environ.get("PILOSA_METRICS_HISTOGRAMS")
            hist_on = (env_h.lower() in ("1", "true", "yes")
                       if env_h else True)
        if hist_on:
            self.histograms = stats_mod.HistogramSet(
                mcfg.get("histogram-buckets") or None)
        else:
            self.histograms = stats_mod.NOP_HISTOGRAMS
        collector = mcfg.get("collector-interval")
        if collector is None:
            collector = int(_os.environ.get(
                "PILOSA_METRICS_COLLECTOR_INTERVAL", "10"))
        self.collector_interval = int(collector)
        self.cluster_metrics_enabled = bool(
            mcfg.get("cluster-aggregation", True))
        # Monotonic: feeds uptime_seconds (a duration) via
        # stats.process_telemetry — never wall clock.
        self._started_at = time.monotonic()

        # Workload observatory ([observe] config table): kernel-cost
        # attribution + slice/row heatmaps, always-on by default.
        # kerneltime/heatmap are PROCESS-GLOBAL like the kernels they
        # instrument (see observe/__init__.py): installed only FOR a
        # real enable, so a later observe-disabled server in the same
        # process never downgrades an enabled one (the
        # set_dispatch_histogram discipline).
        from pilosa_tpu.observe import heatmap as heatmap_mod
        from pilosa_tpu.observe import kerneltime as kerneltime_mod
        from pilosa_tpu.observe import slo as slo_mod

        ocfg = {k.replace("_", "-"): v for k, v in (observe or {}).items()}
        observe_enabled = ocfg.get("enabled")
        if observe_enabled is None:
            env_o = _os.environ.get("PILOSA_OBSERVE_ENABLED")
            observe_enabled = (env_o.lower() in ("1", "true", "yes")
                               if env_o else True)
        self.observe_enabled = bool(observe_enabled)
        if self.observe_enabled:
            rate = ocfg.get("kernel-sample-rate")
            if rate is None:
                try:
                    rate = int(_os.environ.get(
                        "PILOSA_OBSERVE_KERNEL_SAMPLE_RATE", "0"))
                except ValueError:
                    rate = 0
            kerneltime_mod.enable(sample_rate=max(0, int(rate)))
            heatmap_mod.enable(
                half_life=float(ocfg.get("heatmap-half-life",
                                         heatmap_mod.DEFAULT_HALF_LIFE)),
                top_k=int(ocfg.get("heatmap-top-k",
                                   heatmap_mod.DEFAULT_TOP_K)))
            # Measured cost model (PR 15 query inspector): enabled
            # with the observatory — the kerneltime cells ARE its
            # measurement source. Predicted-vs-measured error ratios
            # ride the cost_model_error histogram family when
            # histograms are on.
            from pilosa_tpu.observe import costmodel as costmodel_mod

            cm = costmodel_mod.enable()
            if self.histograms.enabled:
                cm.set_histogram(self.histograms.histogram(
                    "cost_model_error",
                    buckets=(0.125, 0.25, 0.5, 0.8, 1.0, 1.25,
                             2.0, 4.0, 8.0)))
            # Analytic device-kernel attribution (observe/devprof.py):
            # enabled with the observatory — its captures fold into
            # the kerneltime cells and the cost model's fallbacks.
            from pilosa_tpu.observe import devprof as devprof_mod

            devprof_mod.enable()

        # Continuous profiler ([profile] config table): always-on
        # stack sampler, process-global like kerneltime (one sampler
        # thread serves every in-process server; sys._current_frames
        # is process-wide anyway). sample-hz 0 = off; a later
        # profile-disabled server never downgrades an enabled one.
        from pilosa_tpu.observe import profiler as profiler_mod

        pcfg = {k.replace("_", "-"): v for k, v in (profile or {}).items()}
        hz = pcfg.get("sample-hz")
        if hz is None:
            try:
                hz = float(_os.environ.get(
                    "PILOSA_PROFILE_SAMPLE_HZ",
                    profiler_mod.DEFAULT_HZ))
            except ValueError:
                hz = profiler_mod.DEFAULT_HZ
        if float(hz) > 0:
            profiler_mod.enable(sample_hz=float(hz))
        self.profile_trace_dir = str(
            pcfg.get("device-trace-dir")
            or _os.environ.get("PILOSA_PROFILE_DEVICE_TRACE_DIR", "")
            or "")

        # SLO tracker ([slo] config table): per-server (it is fed
        # only by this server's handler), advisory-only.
        slo_cfg = {k.replace("_", "-"): v for k, v in (slo or {}).items()}
        slo_enabled = slo_cfg.get("enabled")
        if slo_enabled is None:
            env_se = _os.environ.get("PILOSA_SLO_ENABLED")
            if env_se:
                slo_enabled = env_se.lower() in ("1", "true", "yes")
            else:
                # Declared objectives imply enabling — the same rule
                # as Config._apply_env, so the CLI and embedded
                # construction paths agree under identical env.
                slo_enabled = bool(
                    _os.environ.get("PILOSA_SLO_OBJECTIVES"))
        if slo_enabled:
            objectives = None
            if slo_cfg.get("objectives"):
                objectives = slo_mod.normalize_objectives(
                    slo_cfg["objectives"])
            elif _os.environ.get("PILOSA_SLO_OBJECTIVES"):
                objectives = slo_mod.parse_objectives(
                    _os.environ["PILOSA_SLO_OBJECTIVES"])
            self.slo = slo_mod.SLOTracker(objectives)
        else:
            self.slo = slo_mod.NOP

        # Fault injection ([faults] config table): the PILOSA_FAULTS
        # env is read once at faults-module import; the config path
        # installs/extends the same process-global registry (an
        # in-process ServerCluster shares it by design — see
        # faults.py). Off by default: injection sites cost one
        # attribute read on the shared nop object.
        fcfg = {k.replace("_", "-"): v for k, v in (faults or {}).items()}
        if fcfg.get("enabled"):
            faults_mod.enable(fcfg.get("spec") or None)
        # Graceful drain budget for close()/SIGTERM: how long in-flight
        # queries get to finish after the node flips to LEAVING.
        if drain_timeout is None:
            env_dt = _os.environ.get("PILOSA_DRAIN_TIMEOUT")
            drain_timeout = float(env_dt) if env_dt \
                else DEFAULT_DRAIN_TIMEOUT
        self.drain_timeout = float(drain_timeout)

        hosts = cluster_hosts or [bind]
        self.cluster = Cluster(
            nodes=[Node(h, scheme=self.scheme) for h in hosts],
            replica_n=replica_n,
            max_writes_per_request=max_writes_per_request,
            long_query_time=long_query_time)
        # Distributed mutation epochs (cluster/epochs.py): assigned
        # below for multi-node; None keeps the single-node hot paths
        # and wire format byte-identical to before.
        self.epochs = None
        if len(hosts) > 1:
            # Heartbeat membership with failure detection; a recovered
            # peer gets a schema push (the gossip state-exchange analog).
            from pilosa_tpu.cluster.membership import HTTPNodeSet

            self.cluster.node_set = HTTPNodeSet(
                self.cluster, bind,
                InternalClient(timeout=5, skip_verify=tls_skip_verify),
                on_rejoin=self._on_peer_rejoin,
                # Heartbeat piggyback: schema/max-slice/epoch state
                # rides every probe both directions, making the 60 s
                # max-slice poll a backstop rather than the mechanism.
                status_fn=self._heartbeat_status,
                merge_fn=self._merge_peer_status)
        else:
            self.cluster.node_set = StaticNodeSet(self.cluster.nodes)

        self.client = InternalClient(skip_verify=tls_skip_verify,
                                     breakers=self.qos.breakers)
        if len(hosts) > 1:
            from pilosa_tpu.cluster.epochs import (
                ClusterEpochs, DEFAULT_PROBE_TTL)

            if epoch_probe_ttl is None:
                env_ttl = _os.environ.get("PILOSA_EPOCH_PROBE_TTL")
                if env_ttl:
                    try:
                        epoch_probe_ttl = float(env_ttl)
                    except ValueError:
                        pass
            # 0/None = one heartbeat interval (the registry stays
            # fresh for free off the membership probes).
            ttl = float(epoch_probe_ttl or 0) or DEFAULT_PROBE_TTL
            self.epochs = ClusterEpochs(
                self.host, self.holder, cluster=self.cluster,
                client=self.client, ttl=ttl)
            # The internal client feeds every RPC response's piggyback
            # header into the registry — a relayed write's ack carries
            # the replica's bumped counter back inline.
            self.client.epochs = self.epochs
        # Shared breaker registry: the client records transport
        # outcomes, the executor/cluster consult state up front when
        # mapping slices, /status surfaces it.
        self.cluster.breakers = self.qos.breakers
        # Elastic topology (cluster/placement.py + rebalancer.py):
        # versioned slice placement with an online background migrator,
        # multi-node only — a single-node server has nothing to
        # stream and no broadcast plane to commit over.
        self.rebalancer = None
        if len(hosts) > 1:
            from pilosa_tpu.cluster.rebalancer import Rebalancer

            if rebalance_stream_concurrency is None:
                rebalance_stream_concurrency = int(_os.environ.get(
                    "PILOSA_REBALANCE_STREAM_CONCURRENCY", "2"))
            if rebalance_bandwidth is None:
                rebalance_bandwidth = int(_os.environ.get(
                    "PILOSA_REBALANCE_BANDWIDTH", "0"))
            self.rebalancer = Rebalancer(
                self.holder, self.cluster, self.host, self.client,
                stream_concurrency=rebalance_stream_concurrency,
                bandwidth=rebalance_bandwidth,
                tracer=self.tracer, stats=self.stats,
                pending_hints_fn=lambda: (
                    self.executor.pending_hint_hosts()))
        if rebalance_drain_timeout is None:
            env_rdt = _os.environ.get("PILOSA_REBALANCE_DRAIN_TIMEOUT")
            rebalance_drain_timeout = float(env_rdt) if env_rdt \
                else DEFAULT_REBALANCE_DRAIN_TIMEOUT
        self.rebalance_drain_timeout = float(rebalance_drain_timeout)

        # Control-plane flight recorder + per-replica vitals ([observe]
        # events/vitals keys, observe/events.py + observe/replica.py):
        # per-server like the SLO tracker — an in-process test cluster
        # must attribute each transition to the node that observed it.
        # Both default to the observatory switch; emitting subsystems
        # hold ``events = None`` when off (one attribute read).
        from pilosa_tpu.observe import events as events_mod
        from pilosa_tpu.observe import replica as replica_mod

        ev_on = ocfg.get("events")
        if ev_on is None:
            env_ev = _os.environ.get("PILOSA_OBSERVE_EVENTS")
            ev_on = (env_ev.lower() in ("1", "true", "yes")
                     if env_ev else self.observe_enabled)
        vt_on = ocfg.get("vitals")
        if vt_on is None:
            env_vt = _os.environ.get("PILOSA_OBSERVE_VITALS")
            vt_on = (env_vt.lower() in ("1", "true", "yes")
                     if env_vt else self.observe_enabled)
        if ev_on:
            pl = self.cluster.placement
            self.events = events_mod.EventRecorder(
                host=self.host,
                ring_size=int(ocfg.get("events-ring",
                                       events_mod.DEFAULT_RING)),
                gen_fn=lambda: pl.generation,
                sink_path=ocfg.get("events-sink") or None)
        else:
            self.events = events_mod.NOP
        self.vitals = replica_mod.NOP
        if vt_on:
            self.vitals = replica_mod.ReplicaVitals(
                window=float(ocfg.get("vitals-window", 30.0)),
                watchdog_factor=float(ocfg.get("watchdog-factor", 3.0)),
                watchdog_min=float(
                    ocfg.get("watchdog-min-ms", 50.0)) / 1e3)
            self.vitals.epochs = self.epochs
            self.client.vitals = self.vitals
        if self.events.enabled:
            rec = self.events
            self.cluster.placement.events = rec
            if self.qos.enabled:
                self.qos.events = rec
                self.qos.breakers.events = rec
            ns = self.cluster.node_set
            if hasattr(ns, "events"):   # HTTPNodeSet (multi-node only)
                ns.events = rec
            if self.epochs is not None:
                self.epochs.events = rec
            if self.rebalancer is not None:
                self.rebalancer.events = rec
            if self.slo.enabled:
                self.slo.events = rec
            if faults_mod.ACTIVE.enabled:
                # Process-global registry: in-process clusters journal
                # arm/clear on whichever server wired last — same
                # last-enable-wins contract as kerneltime/heatmap.
                faults_mod.ACTIVE.events = rec
            self.holder.events = rec
            self.holder.governor.events = rec
            if self.vitals.enabled:
                self.vitals.events = rec

        self.executor = Executor(
            self.holder, cluster=self.cluster, host=self.host,
            client=self.client,
            max_writes_per_request=max_writes_per_request)
        # Result-memo validity on clusters: the executor keys its
        # whole-result memos on the epoch vector of the owning nodes.
        self.executor.epochs = self.epochs
        # [executor] config table: the slice-plan cache entry budget
        # (plancache.py). The PlanCache constructor already honored
        # PILOSA_PLAN_CACHE_ENTRIES for bare construction; an explicit
        # config value wins (0 = off).
        ecfg = {k.replace("_", "-"): v for k, v in (executor or {}).items()}
        if ecfg.get("plan-cache-entries") is not None:
            self.executor.plans.set_capacity(
                int(ecfg["plan-cache-entries"]))
        # Cross-query micro-batching tick knobs. The executor resolves
        # PILOSA_COALESCE_* env itself for bare construction; explicit
        # config values win here (config.py already folded env into
        # them with env-over-file precedence).
        if any(ecfg.get(k) is not None for k in (
                "coalesce-max-wait-us", "coalesce-max-group",
                "coalesce-compressed", "coalesce-densify-bytes")):
            self.executor.set_coalesce_config(
                max_wait_us=ecfg.get("coalesce-max-wait-us"),
                max_group=ecfg.get("coalesce-max-group"),
                compressed=ecfg.get("coalesce-compressed"),
                densify_bytes=ecfg.get("coalesce-densify-bytes"))
        # [planner] config table: the adaptive cost-based planner
        # (planner.py). The Planner resolves PILOSA_PLANNER_* env
        # itself at construction for bare Executors; explicit config
        # values win here (config.py already folded env into them with
        # env-over-file precedence).
        pcfg = {k.replace("_", "-"): v for k, v in (planner or {}).items()}
        if pcfg:
            self.executor.planner.set_config(
                enabled=pcfg.get("enabled"),
                reorder=pcfg.get("reorder"),
                short_circuit=pcfg.get("short-circuit"),
                tier_select=pcfg.get("tier-select"),
                explore_stride=pcfg.get("explore-stride"))
        # [storage] config table: the compressed container tier
        # (ops/containers.py). The module read PILOSA_CONTAINER_FORMATS
        # at import for bare construction; an explicit config value
        # wins. Process-global like the kernels themselves — in-process
        # test clusters share one tier.
        scfg = {k.replace("_", "-"): v for k, v in (storage or {}).items()}
        if scfg.get("container-formats") is not None:
            from pilosa_tpu.ops import containers as containers_mod

            containers_mod.set_enabled(bool(scfg["container-formats"]))

        # Streaming bulk-ingest pipeline (ingest/pipeline.py): the
        # [ingest] config table. Default ON — disabling answers 501 on
        # the route and removes the pilosa_ingest_* metrics group.
        icfg = {k.replace("_", "-"): v for k, v in (ingest or {}).items()}
        ingest_enabled = icfg.get("enabled")
        if ingest_enabled is None:
            env_ie = _os.environ.get("PILOSA_INGEST_ENABLED")
            ingest_enabled = (env_ie.lower() in ("1", "true", "yes")
                              if env_ie else True)
        self.ingest = None
        if ingest_enabled:
            from pilosa_tpu.ingest import IngestPipeline
            from pilosa_tpu.ingest.pipeline import DEFAULT_MAX_BATCH_BITS

            max_batch_bits = icfg.get("max-batch-bits")
            if max_batch_bits is None:
                env_mb = _os.environ.get("PILOSA_INGEST_MAX_BATCH_BITS")
                if env_mb:
                    try:
                        max_batch_bits = int(env_mb)
                    except ValueError:
                        pass
            self.ingest = IngestPipeline(
                self.holder, cluster=self.cluster, client=self.client,
                max_batch_bits=max_batch_bits or DEFAULT_MAX_BATCH_BITS,
                stats=self.stats, tracer=self.tracer)

        # Collective data plane ([mesh] config table,
        # cluster/meshplane.py): within a mesh peer group — one JAX
        # process group sharing one device set — multi-node queries
        # compile to one shard_map + psum program instead of HTTP
        # fan-out. Off by default: it is a topology claim, not a
        # tuning knob. Constructed even single-node so the
        # pilosa_mesh_* metrics group and /debug/mesh are live
        # wherever the config says the plane is on.
        mshcfg = {k.replace("_", "-"): v for k, v in (mesh or {}).items()}
        mesh_enabled = mshcfg.get("enabled")
        if mesh_enabled is None:
            mesh_enabled = _os.environ.get(
                "PILOSA_MESH_ENABLED", "").lower() in ("1", "true",
                                                       "yes")
        self.meshplane = None
        if mesh_enabled:
            from pilosa_tpu.cluster.meshplane import (
                DEFAULT_STACK_BYTES, MeshPlane)

            group = mshcfg.get("group")
            if not group:
                group = _os.environ.get("PILOSA_MESH_GROUP") or None
            stack_bytes = mshcfg.get("stack-bytes")
            if stack_bytes is None:
                env_sb = _os.environ.get("PILOSA_MESH_STACK_BYTES")
                if env_sb:
                    try:
                        stack_bytes = int(env_sb)
                    except ValueError:
                        pass
            self.meshplane = MeshPlane(
                self.holder, self.cluster, self.host,
                group=group or None,
                stack_bytes=stack_bytes or DEFAULT_STACK_BYTES)
            self.meshplane.register()
            self.executor.meshplane = self.meshplane

        # Histogram wiring: executor latency + fan-out rounds, internal
        # client round trips, admission queue-wait, and per-kernel
        # dispatch time. The kernel hook is module-level (bitops) —
        # installed only for a REAL set, so a later nop-configured
        # server in the same process never downgrades an enabled one.
        self.executor.set_histograms(self.histograms)
        if self.ingest is not None and self.histograms.enabled:
            self.ingest.set_histograms(self.histograms)
        if self.histograms.enabled:
            self.client.set_histogram(
                self.histograms.histogram("client_request_seconds"))
            self.qos.set_histograms(self.histograms)
            from pilosa_tpu.ops import bitops

            bitops.set_dispatch_histogram(
                self.histograms.histogram("kernel_dispatch_seconds"))

        if len(self.cluster.nodes) > 1:
            self.broadcaster = HTTPBroadcaster(self.client, self.cluster,
                                               self.host)
        else:
            self.broadcaster = NopBroadcaster()

        # Heat-driven autopilot ([autopilot] config table,
        # autopilot/controller.py): the closed-loop controller that
        # operates the cluster itself. OFF by default — it is an
        # authority claim, not a tuning knob. Constructed after every
        # sensor/actuator it reads so the wiring below is one
        # straight-line install; NOP when disabled (the qos/tracer
        # pattern: one attribute read on every surface).
        from pilosa_tpu import autopilot as autopilot_mod

        apcfg = {k.replace("_", "-"): v
                 for k, v in (autopilot or {}).items()}
        ap_enabled = apcfg.get("enabled")
        if ap_enabled is None:
            ap_enabled = _os.environ.get(
                "PILOSA_AUTOPILOT_ENABLED", "").lower() in (
                    "1", "true", "yes")
        if ap_enabled:
            ap_key_map = {"interval": "interval",
                          "dry-run": "dry_run",
                          "placement": "placement_loop",
                          "memory": "memory_loop",
                          "slo": "slo_loop",
                          "min-dwell": "min_dwell",
                          "max-actions-per-window":
                              "max_actions_per_window",
                          "window": "window",
                          "heat-imbalance": "heat_imbalance",
                          "memory-headroom": "memory_headroom"}
            self.autopilot = autopilot_mod.Autopilot(
                local_host=self.host, **{
                    py: apcfg[k] for k, py in ap_key_map.items()
                    if k in apcfg})
            # Sensors + actuators: every one an EXISTING surface — the
            # autopilot adds no new mutation paths, it drives the same
            # levers an operator does.
            ap = self.autopilot
            ap.cluster = self.cluster
            ap.rebalancer = self.rebalancer
            ap.client = self.client
            ap.governor = self.holder.governor
            if self.qos.enabled:
                ap.qos = self.qos
            if self.vitals.enabled:
                ap.vitals = self.vitals
            if self.slo.enabled:
                ap.slo = self.slo
            if heatmap_mod.ACTIVE.enabled:
                ap.heat_fn = heatmap_mod.ACTIVE.snapshot
            if self.events.enabled:
                ap.events = self.events
        else:
            self.autopilot = autopilot_mod.NOP

        # Tail-tolerant reads ([cluster] hedge-* / replica-routing
        # keys, cluster/hedge.py): replica-aware routing + hedged
        # fan-out. OFF by default — the executor holds ``hedger =
        # None`` and the preferred-owner fan-out path is
        # byte-identical to pre-hedging behavior. Constructed after
        # vitals/qos/epochs/events so the wiring below is one
        # straight-line install (the autopilot pattern).
        from pilosa_tpu.cluster import hedge as hedge_mod

        hcfg = {k.replace("_", "-"): v for k, v in (hedge or {}).items()}
        if not hcfg:
            # Direct Server() construction (tests, embedding): mirror
            # config.py's documented PILOSA_HEDGE_* env overrides.
            hcfg = hedge_mod.env_config(_os.environ)
        if hcfg.get("hedge-reads") or hcfg.get("replica-routing"):
            self.hedger = hedge_mod.Hedger(hcfg)
            hg = self.hedger
            hg.local_host = self.host
            hg.epochs = self.epochs
            if self.vitals.enabled:
                hg.vitals = self.vitals
            if self.qos.enabled:
                hg.qos = self.qos
                hg.breakers = self.qos.breakers
            if self.events.enabled:
                hg.events = self.events
            self.executor.hedger = hg
        else:
            self.hedger = hedge_mod.NOP

        self.holder.broadcaster = self.broadcaster
        self.handler = Handler(self.holder, self.executor,
                               cluster=self.cluster,
                               broadcaster=self.broadcaster,
                               local_host=self.host, version=__version__,
                               tracer=self.tracer, qos=self.qos,
                               histograms=self.histograms,
                               epochs=self.epochs,
                               rebalancer=self.rebalancer,
                               ingest=self.ingest,
                               slo=self.slo,
                               events=self.events,
                               vitals=self.vitals,
                               autopilot=self.autopilot,
                               hedger=self.hedger,
                               device_trace_dir=self.profile_trace_dir)
        if self.rebalancer is not None and self.histograms.enabled:
            # pilosa_rebalance_stream_seconds{peer=...} — per-peer
            # migration stream durations.
            self.rebalancer.set_histogram(
                self.histograms.histogram("rebalance_stream_seconds"))
        self.handler.cluster_metrics_enabled = self.cluster_metrics_enabled
        self.syncer = HolderSyncer(self.holder, self.cluster, self.host,
                                   self.client)
        self.anti_entropy_interval = anti_entropy_interval
        self.polling_interval = polling_interval

        # Worker frontend processes (ref: goroutine-per-conn serving,
        # server.go:205-217; see server/workers.py for the design).
        import os as _os

        if workers is None:
            workers = int(_os.environ.get("PILOSA_TPU_WORKERS", "0"))
        self.workers = workers
        self.worker_pool = None
        self.plan_server = None

        self._httpd = None
        self._threads = []
        self._closing = threading.Event()

    # ------------------------------------------------------------ lifecycle

    def open(self):
        """(ref: Server.Open server.go:123-234)."""
        self.holder.open()
        self._load_path_model()
        # Master response replay on EVERY topology: single-node
        # validates on the in-process epoch, multi-node on the
        # distributed epoch vector (cluster/epochs.py) — unknown or
        # stale peers mean cold, never stale.
        self.handler.enable_response_cache()
        self._httpd = make_http_server(self.handler, self.bind,
                                       reuse_port=self.workers > 0,
                                       max_body_size=self.max_body_size)
        if self.tls_cert:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(self.tls_cert, self.tls_key or None)
            self._httpd.socket = ctx.wrap_socket(
                self._httpd.socket, server_side=True)
        port = self._httpd.server_address[1]
        host = self.bind.rsplit(":", 1)[0]
        self.host = f"{host}:{port}"
        self.handler.local_host = self.host
        self.executor.host = self.host
        if self.epochs is not None:
            self.epochs.local_host = self.host  # ":0" bind resolved
        # Re-point our own node entry at the real bound port (":0" case).
        node = self.cluster.node_by_host(self.bind)
        if node is not None:
            node.host = self.host
            self.cluster.topology_version += 1  # ownership cache epoch
            # Placement host lists must track the reachable name too.
            self.cluster.placement.rename_host(self.bind, self.host)
        if self.rebalancer is not None:
            self.rebalancer.local_host = self.host
        if self.autopilot.enabled:
            self.autopilot.local_host = self.host
        if self.meshplane is not None:
            self.meshplane.set_local_host(self.host)
        # The journal's host stamp must be the reachable name (":0"
        # binds resolve only here), so re-point it before the first
        # event a peer could ever merge.
        if self.events.enabled:
            self.events.host = self.host
            self.events.emit("server.start", bind=self.bind,
                             version=__version__)

        # Named for the profiler's serving seam (request threads get
        # Python's own "(process_request_thread)" suffix).
        t = threading.Thread(target=self._httpd.serve_forever,
                             daemon=True, name="http-serve")
        t.start()
        self._threads.append(t)

        if self.workers > 0:
            import os as _os

            from pilosa_tpu.server.workers import PlanServer, WorkerPool
            from pilosa_tpu.storage import fragment as fragment_mod

            # Unix socket paths cap at ~108 bytes; keep it short and
            # unique rather than inside a (possibly deep) data dir.
            # A freshly-created 0700 directory (not a predictable
            # world-writable /tmp name) means no other local user can
            # pre-plant an entry at the socket path or connect during
            # the bind window — the plan socket's dispatch surface is
            # reachable only by this uid.
            import tempfile

            self._plan_dir = tempfile.mkdtemp(prefix="pilosa_plan_")
            sock = _os.path.join(self._plan_dir, "plan.sock")
            if len(sock) > 100:  # deep $TMPDIR would overflow sun_path
                import shutil

                shutil.rmtree(self._plan_dir, ignore_errors=True)
                self._plan_dir = tempfile.mkdtemp(prefix="pilosa_plan_",
                                                  dir="/tmp")
                sock = _os.path.join(self._plan_dir, "plan.sock")
            self.plan_server = PlanServer(self.handler.dispatch,
                                          sock).open()
            # Worker-local read execution: default ON for the CPU
            # backend (each worker's replica executes on its own GIL —
            # the goroutine-across-cores analog) and OFF on an
            # accelerator, where the master's device does the math and
            # workers only shed the HTTP transport.
            exec_env = _os.environ.get("PILOSA_TPU_WORKER_EXEC")
            if exec_env is not None:
                exec_reads = exec_env == "1"
            else:
                import jax

                exec_reads = jax.default_backend() == "cpu"
            # SINGLE-NODE GATE for worker-local execution only: the
            # worker replica's executor has no cluster — on a
            # multi-node cluster local execution would return partial
            # (local-slice-only) results. The worker RESPONSE CACHE
            # runs on every topology: single-node it validates on the
            # published local epoch (word 0); multi-node it also
            # requires the published cluster epoch version (word 1,
            # fed by the epoch registry — 0 means cold, so a peer
            # visibility lapse degrades workers to relay, never to
            # stale replay).
            single_node = len(self.cluster.nodes) <= 1
            exec_reads = exec_reads and single_node
            fragment_mod.publish_epochs(
                _os.path.join(self.data_dir, ".mutation_epoch"))
            if self.epochs is not None:
                # Synchronous word-1 publication on every observed
                # change + a staleness monitor that flips it to 0
                # (cold) when a peer stops answering.
                self.epochs.attach_worker_publisher(
                    fragment_mod.publish_cluster_version)
                self._spawn(self._monitor_worker_epochs,
                            max(0.5, self.epochs.ttl / 2))
            self.worker_pool = WorkerPool(
                self.workers, self.host, sock,
                tls_cert=self.tls_cert, tls_key=self.tls_key,
                data_dir=self.data_dir,
                exec_reads=exec_reads,
                cluster_epochs=not single_node,
                trace_enabled=self.tracer.enabled,
                max_body_size=self.max_body_size,
                qos_active=self.qos.enabled,
                plan_cache_entries=self.executor.plans.capacity).open()

        from pilosa_tpu.cluster.membership import HTTPNodeSet

        if isinstance(self.cluster.node_set, HTTPNodeSet):
            self.cluster.node_set.local_host = self.host
            self.cluster.node_set.open()

        # Background monitors (ref: server.go:227-232).
        if self.anti_entropy_interval and len(self.cluster.nodes) > 1:
            self._spawn(self._monitor_anti_entropy,
                        self.anti_entropy_interval)
        if self.polling_interval and len(self.cluster.nodes) > 1:
            self._spawn(self._monitor_max_slices, self.polling_interval)
        self._spawn(self._monitor_cache_flush, DEFAULT_CACHE_FLUSH_INTERVAL)
        if self.collector_interval > 0:
            self._spawn(self._monitor_runtime, self.collector_interval)
        if self.autopilot.enabled and self.autopilot.interval > 0:
            # The control loop rides the monitor harness: crashes log
            # + count but never kill the thread, and the kill switch
            # (autopilot.disable()) makes every subsequent tick a
            # no-op even before close() stops the loop.
            self._spawn(self.autopilot.tick, self.autopilot.interval)
        return self

    def _heartbeat_status(self):
        """Compact NodeStatus for the membership probe piggyback:
        schema/max-slices from the holder plus (multi-node) this
        node's mutation-epoch counters."""
        st = self.holder.node_status_compact(self.host)
        if self.epochs is not None:
            from pilosa_tpu.cluster import epochs as epochs_mod

            st["epochs"] = epochs_mod.local_epochs(self.holder)
        if self.cluster.placement.active:
            # Placement convergence backstop: a peer that missed a
            # resize broadcast (rebalance.commit.partial, a transient
            # partition) learns the newest placement state within one
            # probe interval; the seq guard makes re-application a
            # no-op.
            st["placement"] = self.cluster.placement.wire_state()
        return st

    def _merge_peer_status(self, st):
        """Apply a heartbeat reply: epoch observation first (it must
        never be lost to a schema-merge hiccup), then placement
        convergence, then the holder's create-only schema/max-slice
        merge."""
        if self.epochs is not None and isinstance(
                st.get("epochs"), dict) and st.get("host"):
            self.epochs.observe(st["host"], st["epochs"])
        if self.rebalancer is not None:
            self.rebalancer.merge_placement(st)
        self.holder.merge_remote_status(st)

    def _on_peer_rejoin(self, node):
        """Reconcile a recovered peer: push full schema (options+fields)
        and replay writes hinted while it was down (the reference's
        gossip MergeRemoteState analog + hinted handoff)."""
        self.client.post_schema(node, self.holder.schema(include_meta=True))
        self.executor.replay_hints(node, self.client)

    def close(self):
        """Graceful drain, then teardown: announce LEAVING (new
        serving work sheds 503 + Retry-After, /status flips so peers
        stop routing here), wait up to ``drain_timeout`` for in-flight
        queries — whose op-log writes flush synchronously inside them
        — then close for real (the existing hard teardown, which also
        severs any straggler the deadline abandoned)."""
        first = not self._closing.is_set()
        self._closing.set()
        # Autopilot stands down FIRST: the kill switch makes any
        # mid-flight tick abort before its actuator call, so shutdown
        # never races a controller-initiated resize.
        self.autopilot.close()
        if first and self.meshplane is not None:
            # Leave the mesh peer group BEFORE draining: peers must
            # stop staging collective reads against this holder while
            # it can still serve their HTTP fallbacks.
            self.meshplane.close()
        if (first and self.rebalancer is not None
                and self.cluster.placement.is_leaving(self.host)):
            # A LEAVING node exits only after the resize that removes
            # it finishes handing its slices off (commit + cleanup —
            # every fragment has a verified copy on its new owner), up
            # to the rebalance drain budget. The handler keeps serving
            # migration reads meanwhile; the regular drain below then
            # sheds what remains.
            done = self.rebalancer.wait_handoff(
                self.rebalance_drain_timeout)
            if not done:
                self.stats.count("rebalance_handoff_timeout_total", 1)
                _LOG.warning(
                    "leaving node shutting down before handoff "
                    "completed (waited %.1fs); anti-entropy on the "
                    "surviving replicas is the backstop",
                    self.rebalance_drain_timeout)
        if first and self._httpd is not None:
            self.events.emit("drain.begin",
                             timeoutSeconds=self.drain_timeout)
            waited, drained, left = self.handler.drain(self.drain_timeout)
            self.events.emit("drain.end", waitedSeconds=round(waited, 3),
                             drained=drained, inflight=left)
            self.stats.timing("drain_duration_seconds", waited)
            if not drained:
                self.stats.count("drain_timeout_total", 1)
                _LOG.warning(
                    "drain timeout after %.3fs: %d request(s) still in "
                    "flight, closing anyway", waited, left)
        if first:
            self.events.emit("server.stop")
        self._save_path_model()  # learned minima survive the restart
        if self.worker_pool is not None:
            self.worker_pool.close()
        if self.plan_server is not None:
            self.plan_server.close()
            import shutil

            shutil.rmtree(getattr(self, "_plan_dir", ""),
                          ignore_errors=True)
        if self.cluster.node_set is not None:
            self.cluster.node_set.close()
        if hasattr(self.broadcaster, "close"):
            self.broadcaster.close()
        self.syncer.close()
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        # Fan-out thread pools: the executor's map/reduce pool and the
        # epoch registry's probe pool park daemon threads — release
        # them so long-lived processes churning servers (tests) don't
        # accumulate parked workers.
        self.executor.close()
        if self.ingest is not None:
            self.ingest.close()
        if self.epochs is not None:
            self.epochs.close()
        if self.rebalancer is not None:
            self.rebalancer.close()
        # Drop pooled keep-alive sockets (self.client is shared by the
        # executor, syncer, and broadcaster; the node set holds its
        # own probing client) — a closed server must not keep idle
        # connections parked against peers.
        self.client.close()
        ns_client = getattr(self.cluster.node_set, "client", None)
        if ns_client is not None and hasattr(ns_client, "close"):
            ns_client.close()
        self.holder.close()

    def _spawn(self, fn, interval):
        name = fn.__name__.lstrip("_").replace("monitor_", "")
        stats = self.stats.with_tags(f"monitor:{name}")

        def loop():
            while not self._closing.wait(interval):
                try:
                    fn()
                except Exception:  # noqa: BLE001 — monitors must not die
                    # ...but they must not die SILENTLY either: a
                    # permanently-crashing monitor (anti-entropy that
                    # can never finish, say) used to be invisible.
                    _LOG.warning("monitor %s crashed (will run again "
                                 "next interval)", name, exc_info=True)
                    stats.count("monitor_errors_total", 1)

        # bg- prefix: the continuous profiler's thread-name seam for
        # the background subsystem (observe/profiler.py).
        t = threading.Thread(target=loop, daemon=True,
                             name=f"bg-{name}")
        t.start()
        self._threads.append(t)

    # ------------------------------------------------------------- monitors

    def _monitor_worker_epochs(self):
        """Keep the worker-published cluster epoch honest: probe stale
        peers off the serving path; publish 0 (= cold) when any peer
        stays unreachable so worker caches degrade to relay."""
        self.epochs.publish_for_workers(probe=True)

    def _monitor_anti_entropy(self):
        """(ref: monitorAntiEntropy server.go:281-319)."""
        import time
        t0 = time.perf_counter()
        self.stats.count("AntiEntropy", 1)
        self.syncer.sync_holder()
        self.stats.timing("AntiEntropyDuration", time.perf_counter() - t0)

    def _monitor_max_slices(self):
        """Poll peers' max slices (ref: monitorMaxSlices server.go:321-357)."""
        for node in self.cluster.nodes:
            if node.host == self.host:
                continue
            try:
                for index, max_slice in self.client.max_slices(node).items():
                    idx = self.holder.index(index)
                    if idx is not None:
                        idx.set_remote_max_slice(max_slice)
                for index, max_slice in self.client.max_slices(
                        node, inverse=True).items():
                    idx = self.holder.index(index)
                    if idx is not None:
                        idx.set_remote_max_inverse_slice(max_slice)
            except Exception:  # noqa: BLE001 — peer may be down; pilint: disable=swallow
                continue

    PATH_MODEL_FILE = ".path_model.json"

    def _path_model_path(self):
        import os as _os

        return _os.path.join(self.data_dir, self.PATH_MODEL_FILE)

    def _load_path_model(self):
        """Warm-start the executor's batched-vs-serial model from the
        previous process's learned minima (best-effort)."""
        import json as _json

        try:
            with open(self._path_model_path()) as f:
                self.executor.load_path_model(_json.load(f))
        except (OSError, ValueError):
            pass

    def _save_path_model(self):
        import json as _json
        import os as _os

        try:
            path = self._path_model_path()
            # Unique tmp per call: the flush monitor and close() can
            # save concurrently; a shared tmp name would interleave
            # their writes and install garbled JSON.
            tmp = f"{path}.{_os.getpid()}.{threading.get_ident()}.tmp"
            with open(tmp, "w") as f:
                _json.dump(self.executor.save_path_model(), f)
            _os.replace(tmp, path)
        except OSError:
            pass

    def _monitor_cache_flush(self):
        """(ref: monitorCacheFlush holder.go:340-376). Also persists
        the executor's learned path model — same sidecar-class,
        best-effort discipline as the rank caches."""
        self.holder.flush_caches()
        self._save_path_model()

    def _monitor_runtime(self):
        """Process-telemetry collector (ref: monitorRuntime
        server.go:632-675, open FDs via CountOpenFiles :701-723):
        gauges RSS, CPU seconds, per-generation GC counters, threads,
        open fds, and uptime into the stats client — rendered as
        ``pilosa_process_*`` on /metrics and folded into the hourly
        diagnostics JSONL. Interval (and the 0 = off switch) comes
        from ``[metrics] collector-interval``. The legacy RSS/Threads/
        Goroutines/OpenFiles gauge names are kept for older
        dashboards."""
        t = stats_mod.process_telemetry(self._started_at)
        for key, val in t.items():
            self.stats.gauge(f"process_{key}", val)
        if "rss_bytes" in t:
            self.stats.gauge("RSS", t["rss_bytes"] // 1024)
        self.stats.gauge("Threads", t["threads"])
        self.stats.gauge("Goroutines", t["threads"])
        if "open_fds" in t:
            self.stats.gauge("OpenFiles", t["open_fds"])
