"""Multi-process serving: worker HTTP frontends + master plan service.

The reference serves every connection on its own goroutine across all
cores (ref: server.go:205-217 http.Serve). A single CPython process
cannot do that — HTTP parsing, routing, and response encoding all hold
the GIL, which capped round-3 serving at ~700 q/s no matter the client
count (BASELINE.md "GIL analysis"). The TPU-native shape of the fix
splits serving across processes around the one resource that must stay
singly-owned — the accelerator:

- N WORKER processes bind the SAME public port via ``SO_REUSEPORT``
  (the kernel load-balances accepted connections, the moral equivalent
  of Go's shared listener + goroutine-per-conn). Workers do the
  GIL-heavy transport half: HTTP parse, header handling, response
  write. Phase 2 (`PILOSA_TPU_WORKER_EXEC`, see worker.py) moves
  read-only query execution into the workers too, against their own
  holder replica refreshed by a shared mutation epoch.
- The MASTER keeps exclusive ownership of the device, the holder, and
  every write path. Workers relay requests over persistent unix-domain
  sockets as length-prefixed binary frames; the master answers with
  ``Handler.dispatch`` directly — no HTTP parsing ever touches its
  GIL. Cross-query count coalescing happens in the master exactly as
  before, now fed by genuinely concurrent worker streams.

Trust boundary: the unix socket lives in a freshly-created 0700
directory with 0600 socket permissions — an INTERNAL transport between
processes of the same installation, never exposed on the network. The
frames themselves are nevertheless a closed, data-only codec (below):
no pickle, so a reachable socket is at worst a request-forgery surface,
never code execution.

Frame codec: a deliberately tiny self-describing binary format for the
relay tuples (method, path, query-params, body, headers) and responses
(status, content-type, payload[, extra headers]). Tags: N one=None,
T/F=bool, I=int64, S=utf-8 string, B=bytes, L=list, U=tuple, D=dict —
each length-prefixed. Unlike pickle it can only ever produce these
eight shapes; truncated/oversized/garbage input raises ``FrameError``
(fuzzed in tests/test_workers.py). The discipline mirrors the schema'd
internal/private.proto data plane (ref: internal/private.proto).
"""
import os
import socket
import struct
import subprocess
import sys
import threading

_LEN = struct.Struct("<I")
_I64 = struct.Struct("<q")
MAX_FRAME = 1 << 30
_MAX_DEPTH = 16


class FrameError(ValueError):
    """Malformed relay frame (truncated, oversized, or garbage)."""


# Integer tag constants: the codec sits on the per-request relay hot
# path, so both directions dispatch on small-int compares over a
# bytes/bytearray buffer (no per-token slicing or struct round trips
# beyond the length words).
_T_NONE, _T_TRUE, _T_FALSE = ord("N"), ord("T"), ord("F")
_T_INT, _T_STR, _T_BYTES = ord("I"), ord("S"), ord("B")
_T_LIST, _T_TUPLE, _T_DICT = ord("L"), ord("U"), ord("D")


def _pack_into(obj, out, depth=0):
    if depth > _MAX_DEPTH:
        raise FrameError("frame nesting too deep")
    t = type(obj)
    if t is str:
        raw = obj.encode()
        out.append(_T_STR)
        out += _LEN.pack(len(raw))
        out += raw
    elif t is bytes:
        out.append(_T_BYTES)
        out += _LEN.pack(len(obj))
        out += obj
    elif t is bool:  # before int: bool is an int subclass
        out.append(_T_TRUE if obj else _T_FALSE)
    elif t is int:
        out.append(_T_INT)
        out += _I64.pack(obj)
    elif obj is None:
        out.append(_T_NONE)
    elif t is list or t is tuple:
        out.append(_T_LIST if t is list else _T_TUPLE)
        out += _LEN.pack(len(obj))
        for item in obj:
            _pack_into(item, out, depth + 1)
    elif t is dict:
        out.append(_T_DICT)
        out += _LEN.pack(len(obj))
        for k, v in obj.items():
            _pack_into(k, out, depth + 1)
            _pack_into(v, out, depth + 1)
    # Subclass fallbacks (slow path; bool needs none — it is final).
    # Coerce through the BASE type's methods, never subclass hooks, so
    # an adversarial override can't recurse or change the bytes.
    elif isinstance(obj, str):
        raw = str.encode(obj)
        out.append(_T_STR)
        out += _LEN.pack(len(raw))
        out += raw
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        raw = bytes(obj)
        out.append(_T_BYTES)
        out += _LEN.pack(len(raw))
        out += raw
    elif isinstance(obj, int):
        out.append(_T_INT)
        out += _I64.pack(obj)
    elif isinstance(obj, (list, tuple)):
        out.append(_T_LIST if isinstance(obj, list) else _T_TUPLE)
        out += _LEN.pack(len(obj))
        for item in obj:
            _pack_into(item, out, depth + 1)
    elif isinstance(obj, dict):
        out.append(_T_DICT)
        out += _LEN.pack(len(obj))
        for k, v in obj.items():
            _pack_into(k, out, depth + 1)
            _pack_into(v, out, depth + 1)
    else:
        raise TypeError(f"frame cannot carry {type(obj).__name__}")


def pack(obj):
    out = bytearray()
    _pack_into(obj, out)
    return bytes(out)


def _unpack_from(data, pos, end, depth=0):
    """data: bytes; returns (obj, new_pos). Bounds-checked against
    ``end`` before every read; any violation raises FrameError."""
    if depth > _MAX_DEPTH:
        raise FrameError("frame nesting too deep")
    if pos >= end:
        raise FrameError("truncated frame")
    tag = data[pos]
    pos += 1
    if tag == _T_STR or tag == _T_BYTES:
        if pos + 4 > end:
            raise FrameError("truncated frame")
        (n,) = _LEN.unpack_from(data, pos)
        pos += 4
        if pos + n > end:
            raise FrameError("truncated frame")
        raw = data[pos:pos + n]
        pos += n
        if tag == _T_BYTES:
            return raw, pos
        try:
            return raw.decode(), pos
        except UnicodeDecodeError as exc:
            raise FrameError(f"bad utf-8 in frame: {exc}") from None
    if tag == _T_NONE:
        return None, pos
    if tag == _T_TRUE:
        return True, pos
    if tag == _T_FALSE:
        return False, pos
    if tag == _T_INT:
        if pos + 8 > end:
            raise FrameError("truncated frame")
        val = _I64.unpack_from(data, pos)[0]
        return val, pos + 8
    if tag == _T_LIST or tag == _T_TUPLE:
        if pos + 4 > end:
            raise FrameError("truncated frame")
        (n,) = _LEN.unpack_from(data, pos)
        pos += 4
        if n > end - pos:  # every element costs ≥ 1 byte
            raise FrameError("collection count exceeds frame")
        items = []
        for _ in range(n):
            item, pos = _unpack_from(data, pos, end, depth + 1)
            items.append(item)
        return (items if tag == _T_LIST else tuple(items)), pos
    if tag == _T_DICT:
        if pos + 4 > end:
            raise FrameError("truncated frame")
        (n,) = _LEN.unpack_from(data, pos)
        pos += 4
        if n > (end - pos) // 2:  # a pair costs ≥ 2 bytes
            raise FrameError("dict count exceeds frame")
        d = {}
        for _ in range(n):
            k, pos = _unpack_from(data, pos, end, depth + 1)
            v, pos = _unpack_from(data, pos, end, depth + 1)
            try:
                d[k] = v
            except TypeError:  # e.g. a tuple key wrapping a list
                raise FrameError("unhashable dict key in frame") from None
        return d, pos
    raise FrameError(f"unknown frame tag {chr(tag)!r}")


def unpack(data):
    data = bytes(data)
    try:
        obj, pos = _unpack_from(data, 0, len(data))
    except struct.error as exc:
        raise FrameError(str(exc)) from None
    if pos != len(data):
        raise FrameError(f"{len(data) - pos} trailing bytes in frame")
    return obj


def write_frame(sock, obj):
    data = pack(obj)
    sock.sendall(_LEN.pack(len(data)) + data)


def read_frame(sock):
    hdr = _read_exact(sock, _LEN.size)
    if hdr is None:
        return None
    (n,) = _LEN.unpack(hdr)
    if n > MAX_FRAME:
        raise FrameError(f"frame too large: {n}")
    data = _read_exact(sock, n)
    if data is None:
        return None
    return unpack(data)


def _read_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


class PlanServer:
    """Master-side unix-socket service answering worker frames with
    Handler.dispatch. One daemon thread per worker connection — worker
    connections are per-HTTP-client and long-lived, so the thread
    count tracks concurrent clients the same way ThreadingHTTPServer's
    does, minus the HTTP parsing those threads used to do."""

    def __init__(self, dispatch, sock_path):
        self.dispatch = dispatch
        self.sock_path = sock_path
        self._sock = None
        self._closing = threading.Event()

    def open(self):
        # The pre-bind unlink can fail with more than FileNotFoundError
        # (e.g. EPERM on a sticky-dir entry someone else planted):
        # surface anything but "already absent" as a clear startup
        # error instead of crashing later in bind().
        try:
            os.unlink(self.sock_path)
        except FileNotFoundError:
            pass
        except OSError as exc:
            raise RuntimeError(
                f"plan socket path {self.sock_path} is obstructed "
                f"({exc}); refusing to serve") from exc
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        # The bind→chmod window (socket briefly carrying umask-default
        # perms) is closed by PLACEMENT, not umask: callers bind inside
        # a freshly-created 0700 directory (Server.open does), which no
        # other uid can traverse. A process-wide umask flip here would
        # race concurrent threads writing data files.
        s.bind(self.sock_path)
        os.chmod(self.sock_path, 0o600)
        s.listen(128)
        self._sock = s
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        return self

    def _accept_loop(self):
        while not self._closing.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn):
        try:
            while not self._closing.is_set():
                req = read_frame(conn)
                if req is None:
                    return
                try:
                    method, path, qp, body, headers = req
                except (TypeError, ValueError):
                    raise FrameError(
                        f"request frame is not a 5-tuple: {type(req)}"
                    ) from None
                try:
                    resp = self.dispatch(method, path, qp, body, headers)
                except Exception as e:  # noqa: BLE001 — mirror handler 500s
                    import json as _json

                    resp = (500, "application/json",
                            _json.dumps({"error": str(e)}).encode())
                write_frame(conn, resp)
        except (OSError, EOFError, FrameError):
            pass
        finally:
            conn.close()

    def close(self):
        self._closing.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        try:
            os.unlink(self.sock_path)
        except FileNotFoundError:
            pass


class WorkerPool:
    """Spawns and supervises the worker frontend processes."""

    def __init__(self, n, bind, sock_path, tls_cert=None, tls_key=None,
                 data_dir=None, exec_reads=False, trace_enabled=False,
                 max_body_size=None, qos_active=False,
                 cluster_epochs=False, plan_cache_entries=None):
        self.n = n
        self.bind = bind
        self.sock_path = sock_path
        self.tls_cert = tls_cert
        self.tls_key = tls_key
        self.data_dir = data_dir
        self.exec_reads = exec_reads
        self.trace_enabled = trace_enabled
        self.max_body_size = max_body_size
        self.qos_active = qos_active
        # Multi-node master: worker response caches must also validate
        # the published CLUSTER epoch version (word 1; 0 = cold).
        self.cluster_epochs = cluster_epochs
        # Master's resolved slice-plan cache capacity (plancache.py):
        # forwarded via env so worker exec processes honor a
        # TOML-configured value (incl. the 0 = off switch), not just
        # an operator-set PILOSA_PLAN_CACHE_ENTRIES.
        self.plan_cache_entries = plan_cache_entries
        self._procs = []

    def open(self):
        args = [sys.executable, "-m", "pilosa_tpu.server.worker",
                "--bind", self.bind, "--socket", self.sock_path,
                "--parent-pid", str(os.getpid())]
        if self.max_body_size is not None:
            # The 413 early-reject happens at the HTTP tier, which in
            # worker mode is the WORKER's listener — the master's limit
            # must ride along or oversized bodies would be buffered and
            # relayed before the master could refuse them.
            args += ["--max-body-size", str(self.max_body_size)]
        if self.tls_cert:
            args += ["--tls-cert", self.tls_cert]
        if self.tls_key:
            args += ["--tls-key", self.tls_key]
        if self.data_dir:
            # Always passed: the epoch-validated response cache needs
            # the published counter even in relay-only mode.
            args += ["--data-dir", self.data_dir]
        if self.exec_reads and self.data_dir:
            args += ["--exec-reads"]
        if self.cluster_epochs:
            args += ["--cluster-epochs"]
        env = dict(os.environ)
        if self.plan_cache_entries is not None:
            env["PILOSA_PLAN_CACHE_ENTRIES"] = str(
                self.plan_cache_entries)
        # Workers never touch the accelerator; pin them to the host
        # backend so a hung TPU relay can't freeze a transport process.
        # Unconditional: a master launched with PILOSA_TPU_PLATFORM=tpu
        # must NOT hand that value down — worker executors would then
        # contend for the singly-owned chip.
        env["PILOSA_TPU_PLATFORM"] = "cpu"
        if self.exec_reads:
            # Read-only replica mode for the worker's storage layer
            # (storage/fragment.py REPLICA): no flock, no repair
            # snapshots, no sidecar writes against the master's files.
            env["PILOSA_TPU_READ_ONLY"] = "1"
        if self.trace_enabled:
            # The MASTER owns the tracer: workers must relay every
            # query (no local exec, no response-cache replay) or the
            # worker-served fraction of traffic would silently vanish
            # from /debug/traces and the slow-query metrics.
            env["PILOSA_TPU_MASTER_TRACING"] = "1"
        if self.qos_active:
            # The MASTER owns the QoS tier (admission gate, deadlines,
            # client-quota buckets): worker-local read execution would
            # run ungated and deadline-free, and a worker cache replay
            # would be quota-free — so with QoS enabled workers relay
            # every request, the same discipline as master tracing.
            env["PILOSA_TPU_MASTER_QOS"] = "1"
        for _ in range(self.n):
            self._procs.append(subprocess.Popen(
                args, env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL))
        return self

    def alive(self):
        return sum(1 for p in self._procs if p.poll() is None)

    def close(self):
        for p in self._procs:
            if p.poll() is None:
                p.terminate()
        for p in self._procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
        self._procs = []
