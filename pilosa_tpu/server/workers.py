"""Multi-process serving: worker HTTP frontends + master plan service.

The reference serves every connection on its own goroutine across all
cores (ref: server.go:205-217 http.Serve). A single CPython process
cannot do that — HTTP parsing, routing, and response encoding all hold
the GIL, which capped round-3 serving at ~700 q/s no matter the client
count (BASELINE.md "GIL analysis"). The TPU-native shape of the fix
splits serving across processes around the one resource that must stay
singly-owned — the accelerator:

- N WORKER processes bind the SAME public port via ``SO_REUSEPORT``
  (the kernel load-balances accepted connections, the moral equivalent
  of Go's shared listener + goroutine-per-conn). Workers do the
  GIL-heavy transport half: HTTP parse, header handling, response
  write. Phase 2 (`PILOSA_TPU_WORKER_EXEC`, see worker.py) moves
  read-only query execution into the workers too, against their own
  holder replica refreshed by a shared mutation epoch.
- The MASTER keeps exclusive ownership of the device, the holder, and
  every write path. Workers relay requests over persistent unix-domain
  sockets as length-prefixed binary frames; the master answers with
  ``Handler.dispatch`` directly — no HTTP parsing ever touches its
  GIL. Cross-query count coalescing happens in the master exactly as
  before, now fed by genuinely concurrent worker streams.

Trust boundary: the unix socket lives in a freshly-created 0700
directory with 0600 socket permissions — an INTERNAL transport between
processes of the same installation, never exposed on the network. The
frames themselves are nevertheless a closed, data-only codec (below):
no pickle, so a reachable socket is at worst a request-forgery surface,
never code execution.

Frame codec: a deliberately tiny self-describing binary format for the
relay tuples (method, path, query-params, body, headers) and responses
(status, content-type, payload[, extra headers]). Tags: N one=None,
T/F=bool, I=int64, S=utf-8 string, B=bytes, L=list, U=tuple, D=dict —
each length-prefixed. Unlike pickle it can only ever produce these
eight shapes; truncated/oversized/garbage input raises ``FrameError``
(fuzzed in tests/test_workers.py). The discipline mirrors the schema'd
internal/private.proto data plane (ref: internal/private.proto).
"""
import os
import socket
import struct
import subprocess
import sys
import threading

_LEN = struct.Struct("<I")
_I64 = struct.Struct("<q")
MAX_FRAME = 1 << 30
_MAX_DEPTH = 16


class FrameError(ValueError):
    """Malformed relay frame (truncated, oversized, or garbage)."""


def _pack_into(obj, out, depth=0):
    if depth > _MAX_DEPTH:
        raise FrameError("frame nesting too deep")
    if obj is None:
        out.append(b"N")
    elif obj is True:
        out.append(b"T")
    elif obj is False:
        out.append(b"F")
    elif isinstance(obj, int):
        out.append(b"I")
        out.append(_I64.pack(obj))
    elif isinstance(obj, str):
        raw = obj.encode()
        out.append(b"S")
        out.append(_LEN.pack(len(raw)))
        out.append(raw)
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        raw = bytes(obj)
        out.append(b"B")
        out.append(_LEN.pack(len(raw)))
        out.append(raw)
    elif isinstance(obj, (list, tuple)):
        out.append(b"L" if isinstance(obj, list) else b"U")
        out.append(_LEN.pack(len(obj)))
        for item in obj:
            _pack_into(item, out, depth + 1)
    elif isinstance(obj, dict):
        out.append(b"D")
        out.append(_LEN.pack(len(obj)))
        for k, v in obj.items():
            _pack_into(k, out, depth + 1)
            _pack_into(v, out, depth + 1)
    else:
        raise TypeError(f"frame cannot carry {type(obj).__name__}")


def pack(obj):
    out = []
    _pack_into(obj, out)
    return b"".join(out)


def _need(view, pos, n):
    if pos + n > len(view):
        raise FrameError("truncated frame")
    return pos + n


def _unpack_count(view, pos):
    end = _need(view, pos, _LEN.size)
    (n,) = _LEN.unpack_from(view, pos)
    return n, end


def _unpack_from(view, pos, depth=0):
    if depth > _MAX_DEPTH:
        raise FrameError("frame nesting too deep")
    end = _need(view, pos, 1)
    tag = view[pos:end].tobytes()
    if tag == b"N":
        return None, end
    if tag == b"T":
        return True, end
    if tag == b"F":
        return False, end
    if tag == b"I":
        pos = end
        end = _need(view, pos, _I64.size)
        return _I64.unpack_from(view, pos)[0], end
    if tag in (b"S", b"B"):
        n, pos = _unpack_count(view, end)
        end = _need(view, pos, n)
        raw = view[pos:end].tobytes()
        if tag == b"B":
            return raw, end
        try:
            return raw.decode(), end
        except UnicodeDecodeError as exc:
            raise FrameError(f"bad utf-8 in frame: {exc}") from None
    if tag in (b"L", b"U"):
        n, pos = _unpack_count(view, end)
        if n > len(view) - pos:  # every element costs ≥ 1 byte
            raise FrameError("collection count exceeds frame")
        items = []
        for _ in range(n):
            item, pos = _unpack_from(view, pos, depth + 1)
            items.append(item)
        return (items if tag == b"L" else tuple(items)), pos
    if tag == b"D":
        n, pos = _unpack_count(view, end)
        if n > (len(view) - pos) // 2:  # a pair costs ≥ 2 bytes
            raise FrameError("dict count exceeds frame")
        d = {}
        for _ in range(n):
            k, pos = _unpack_from(view, pos, depth + 1)
            v, pos = _unpack_from(view, pos, depth + 1)
            try:
                d[k] = v
            except TypeError:  # e.g. a tuple key wrapping a list
                raise FrameError("unhashable dict key in frame") from None
        return d, pos
    raise FrameError(f"unknown frame tag {tag!r}")


def unpack(data):
    try:
        obj, pos = _unpack_from(memoryview(data), 0)
    except struct.error as exc:
        raise FrameError(str(exc)) from None
    if pos != len(data):
        raise FrameError(f"{len(data) - pos} trailing bytes in frame")
    return obj


def write_frame(sock, obj):
    data = pack(obj)
    sock.sendall(_LEN.pack(len(data)) + data)


def read_frame(sock):
    hdr = _read_exact(sock, _LEN.size)
    if hdr is None:
        return None
    (n,) = _LEN.unpack(hdr)
    if n > MAX_FRAME:
        raise FrameError(f"frame too large: {n}")
    data = _read_exact(sock, n)
    if data is None:
        return None
    return unpack(data)


def _read_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


class PlanServer:
    """Master-side unix-socket service answering worker frames with
    Handler.dispatch. One daemon thread per worker connection — worker
    connections are per-HTTP-client and long-lived, so the thread
    count tracks concurrent clients the same way ThreadingHTTPServer's
    does, minus the HTTP parsing those threads used to do."""

    def __init__(self, dispatch, sock_path):
        self.dispatch = dispatch
        self.sock_path = sock_path
        self._sock = None
        self._closing = threading.Event()

    def open(self):
        # The pre-bind unlink can fail with more than FileNotFoundError
        # (e.g. EPERM on a sticky-dir entry someone else planted):
        # surface anything but "already absent" as a clear startup
        # error instead of crashing later in bind().
        try:
            os.unlink(self.sock_path)
        except FileNotFoundError:
            pass
        except OSError as exc:
            raise RuntimeError(
                f"plan socket path {self.sock_path} is obstructed "
                f"({exc}); refusing to serve") from exc
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        # The bind→chmod window (socket briefly carrying umask-default
        # perms) is closed by PLACEMENT, not umask: callers bind inside
        # a freshly-created 0700 directory (Server.open does), which no
        # other uid can traverse. A process-wide umask flip here would
        # race concurrent threads writing data files.
        s.bind(self.sock_path)
        os.chmod(self.sock_path, 0o600)
        s.listen(128)
        self._sock = s
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        return self

    def _accept_loop(self):
        while not self._closing.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn):
        try:
            while not self._closing.is_set():
                req = read_frame(conn)
                if req is None:
                    return
                try:
                    method, path, qp, body, headers = req
                except (TypeError, ValueError):
                    raise FrameError(
                        f"request frame is not a 5-tuple: {type(req)}"
                    ) from None
                try:
                    resp = self.dispatch(method, path, qp, body, headers)
                except Exception as e:  # noqa: BLE001 — mirror handler 500s
                    import json as _json

                    resp = (500, "application/json",
                            _json.dumps({"error": str(e)}).encode())
                write_frame(conn, resp)
        except (OSError, EOFError, FrameError):
            pass
        finally:
            conn.close()

    def close(self):
        self._closing.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        try:
            os.unlink(self.sock_path)
        except FileNotFoundError:
            pass


class WorkerPool:
    """Spawns and supervises the worker frontend processes."""

    def __init__(self, n, bind, sock_path, tls_cert=None, tls_key=None,
                 data_dir=None, exec_reads=False):
        self.n = n
        self.bind = bind
        self.sock_path = sock_path
        self.tls_cert = tls_cert
        self.tls_key = tls_key
        self.data_dir = data_dir
        self.exec_reads = exec_reads
        self._procs = []

    def open(self):
        args = [sys.executable, "-m", "pilosa_tpu.server.worker",
                "--bind", self.bind, "--socket", self.sock_path,
                "--parent-pid", str(os.getpid())]
        if self.tls_cert:
            args += ["--tls-cert", self.tls_cert]
        if self.tls_key:
            args += ["--tls-key", self.tls_key]
        if self.data_dir:
            # Always passed: the epoch-validated response cache needs
            # the published counter even in relay-only mode.
            args += ["--data-dir", self.data_dir]
        if self.exec_reads and self.data_dir:
            args += ["--exec-reads"]
        env = dict(os.environ)
        # Workers never touch the accelerator; pin them to the host
        # backend so a hung TPU relay can't freeze a transport process.
        # Unconditional: a master launched with PILOSA_TPU_PLATFORM=tpu
        # must NOT hand that value down — worker executors would then
        # contend for the singly-owned chip.
        env["PILOSA_TPU_PLATFORM"] = "cpu"
        if self.exec_reads:
            # Read-only replica mode for the worker's storage layer
            # (storage/fragment.py REPLICA): no flock, no repair
            # snapshots, no sidecar writes against the master's files.
            env["PILOSA_TPU_READ_ONLY"] = "1"
        for _ in range(self.n):
            self._procs.append(subprocess.Popen(
                args, env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL))
        return self

    def alive(self):
        return sum(1 for p in self._procs if p.poll() is None)

    def close(self):
        for p in self._procs:
            if p.poll() is None:
                p.terminate()
        for p in self._procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
        self._procs = []
