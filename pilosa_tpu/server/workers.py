"""Multi-process serving: worker HTTP frontends + master plan service.

The reference serves every connection on its own goroutine across all
cores (ref: server.go:205-217 http.Serve). A single CPython process
cannot do that — HTTP parsing, routing, and response encoding all hold
the GIL, which capped round-3 serving at ~700 q/s no matter the client
count (BASELINE.md "GIL analysis"). The TPU-native shape of the fix
splits serving across processes around the one resource that must stay
singly-owned — the accelerator:

- N WORKER processes bind the SAME public port via ``SO_REUSEPORT``
  (the kernel load-balances accepted connections, the moral equivalent
  of Go's shared listener + goroutine-per-conn). Workers do the
  GIL-heavy transport half: HTTP parse, header handling, response
  write. Phase 2 (`PILOSA_TPU_WORKER_EXEC`, see worker.py) moves
  read-only query execution into the workers too, against their own
  holder replica refreshed by a shared mutation epoch.
- The MASTER keeps exclusive ownership of the device, the holder, and
  every write path. Workers relay requests over persistent unix-domain
  sockets as length-prefixed pickled frames; the master answers with
  ``Handler.dispatch`` directly — no HTTP parsing ever touches its
  GIL. Cross-query count coalescing happens in the master exactly as
  before, now fed by genuinely concurrent worker streams.

Trust boundary: the unix socket lives next to the data directory with
0600 permissions and carries pickled tuples — it is an INTERNAL
transport between processes of the same installation (same trust as
the data files themselves), never exposed on the network.
"""
import os
import pickle
import socket
import struct
import subprocess
import sys
import threading

_LEN = struct.Struct("<I")
MAX_FRAME = 1 << 30


def write_frame(sock, obj):
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(data)) + data)


def read_frame(sock):
    hdr = _read_exact(sock, _LEN.size)
    if hdr is None:
        return None
    (n,) = _LEN.unpack(hdr)
    if n > MAX_FRAME:
        raise ValueError(f"frame too large: {n}")
    data = _read_exact(sock, n)
    if data is None:
        return None
    return pickle.loads(data)


def _read_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


class PlanServer:
    """Master-side unix-socket service answering worker frames with
    Handler.dispatch. One daemon thread per worker connection — worker
    connections are per-HTTP-client and long-lived, so the thread
    count tracks concurrent clients the same way ThreadingHTTPServer's
    does, minus the HTTP parsing those threads used to do."""

    def __init__(self, dispatch, sock_path):
        self.dispatch = dispatch
        self.sock_path = sock_path
        self._sock = None
        self._closing = threading.Event()

    def open(self):
        try:
            os.unlink(self.sock_path)
        except FileNotFoundError:
            pass
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.bind(self.sock_path)
        os.chmod(self.sock_path, 0o600)
        s.listen(128)
        self._sock = s
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        return self

    def _accept_loop(self):
        while not self._closing.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn):
        try:
            while not self._closing.is_set():
                req = read_frame(conn)
                if req is None:
                    return
                method, path, qp, body, headers = req
                try:
                    resp = self.dispatch(method, path, qp, body, headers)
                except Exception as e:  # noqa: BLE001 — mirror handler 500s
                    import json as _json

                    resp = (500, "application/json",
                            _json.dumps({"error": str(e)}).encode())
                write_frame(conn, resp)
        except (OSError, EOFError, pickle.PickleError):
            pass
        finally:
            conn.close()

    def close(self):
        self._closing.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        try:
            os.unlink(self.sock_path)
        except FileNotFoundError:
            pass


class WorkerPool:
    """Spawns and supervises the worker frontend processes."""

    def __init__(self, n, bind, sock_path, tls_cert=None, tls_key=None,
                 data_dir=None, exec_reads=False):
        self.n = n
        self.bind = bind
        self.sock_path = sock_path
        self.tls_cert = tls_cert
        self.tls_key = tls_key
        self.data_dir = data_dir
        self.exec_reads = exec_reads
        self._procs = []

    def open(self):
        args = [sys.executable, "-m", "pilosa_tpu.server.worker",
                "--bind", self.bind, "--socket", self.sock_path,
                "--parent-pid", str(os.getpid())]
        if self.tls_cert:
            args += ["--tls-cert", self.tls_cert]
        if self.tls_key:
            args += ["--tls-key", self.tls_key]
        if self.data_dir:
            # Always passed: the epoch-validated response cache needs
            # the published counter even in relay-only mode.
            args += ["--data-dir", self.data_dir]
        if self.exec_reads and self.data_dir:
            args += ["--exec-reads"]
        env = dict(os.environ)
        # Workers never touch the accelerator; pin them to the host
        # backend so a hung TPU relay can't freeze a transport process.
        env.setdefault("PILOSA_TPU_PLATFORM", "cpu")
        if self.exec_reads:
            # Read-only replica mode for the worker's storage layer
            # (storage/fragment.py REPLICA): no flock, no repair
            # snapshots, no sidecar writes against the master's files.
            env["PILOSA_TPU_READ_ONLY"] = "1"
        for _ in range(self.n):
            self._procs.append(subprocess.Popen(
                args, env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL))
        return self

    def alive(self):
        return sum(1 for p in self._procs if p.poll() is None)

    def close(self):
        for p in self._procs:
            if p.poll() is None:
                p.terminate()
        for p in self._procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
        self._procs = []
