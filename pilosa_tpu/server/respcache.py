"""Epoch-validated response replay cache, shared by worker frontends
(server/worker.py) and the master handler (server/handler.py): the
deepest memo tier — exact response BYTES for identical read queries,
valid while the mutation-epoch token stands.
"""
import re
import threading

from pilosa_tpu.pql.ast import WRITE_CALLS
from pilosa_tpu import lockcheck

# EXACTLY the PQL query route: endswith("/query") would also match
# /index/<i>/input/query and /index/<i>/input-definition/query —
# mutating endpoints whose 200s must never be replayed (an input
# definition can legitimately be NAMED "query").
_QUERY_ROUTE = re.compile(r"/index/[^/]+/query\Z")


class ResponseCache:
    """Epoch-validated replay of identical READ-query responses.

    Correctness argument: the handler is deterministic, and the
    epoch token moves (before the write's HTTP response) on every
    data or schema change visible to this node — so replaying the
    exact bytes previously produced for (path, body, accept headers)
    is indistinguishable from re-executing, as long as the token read
    BEFORE the original request still equals the current one. On a
    single node the token is the process-local mutation epoch; on a
    cluster it is the epoch VECTOR over the owning nodes
    (cluster/epochs.py), and a ``None`` token — unknown or stale peer
    — means cold: nothing is stored, nothing replays. Writes are
    never cached (conservative substring gate derived from
    pql.ast.WRITE_CALLS: any body containing a write-call name is
    passed through, so a new write call added to WRITE_CALLS is
    automatically never cached), and a cached entry can never
    acknowledge a write it didn't perform. This is the warm-dashboard
    fast path for EVERY backend: on TPU it answers repeats without
    touching the master or the chip.
    """

    MAX = 512
    MAX_BYTES = 64 << 20  # payload budget, as the master's result memo
    _WRITE_MARKERS = tuple(name.encode() for name in WRITE_CALLS)

    def __init__(self, epoch_reader):
        # epoch_reader(path) -> hashable validity token, or None for
        # "cold right now" (multi-node registry with a stale peer).
        self._epoch = epoch_reader
        self._mu = lockcheck.register("respcache.ResponseCache._mu",
                                      threading.Lock())
        self._entries = {}
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    def cacheable(self, method, path, body):
        return (method == "POST"
                and _QUERY_ROUTE.fullmatch(path) is not None
                and not any(m in body for m in self._WRITE_MARKERS))

    @staticmethod
    def make_key(path, qp, body, headers):
        """THE cache key, shared by both tiers (worker frontends and
        the master handler) — a key-shape drift between them would
        make the tiers replay/miss differently for one request.
        Encoding negotiation is part of the response bytes; parse_qs
        values are LISTS and must be tupled to stay hashable."""
        return (path,
                tuple((k, tuple(v)) for k, v in sorted(qp.items()))
                if qp else None,
                body, headers.get("Content-Type"),
                headers.get("Accept"))

    def pre_epoch(self, path):
        """Read BEFORE issuing the request: a write landing mid-flight
        makes the stored token stale and the entry a harmless miss —
        never the reverse. ``None`` (cold) makes ``put`` a no-op."""
        return self._epoch(path)

    def get(self, key):
        # The token read (which on a cluster may probe stale peers)
        # happens OUTSIDE the entry lock.
        cur = self._epoch(key[0])
        with self._mu:
            hit = self._entries.get(key)
            if hit is None:
                self.misses += 1
                return None
            if cur is None or hit[0] != cur:
                self.misses += 1
                if cur is not None:
                    # Monotone counters: an unequal token can never
                    # become equal again — evict on discovery. A None
                    # token is only a temporary visibility lapse; the
                    # entry may validate once peers answer again.
                    del self._entries[key]
                    self._bytes -= len(hit[1][2])
                return None
            self.hits += 1
        return hit[1]

    def stats(self):
        with self._mu:
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "hits": self.hits, "misses": self.misses}

    def put(self, key, epoch, resp):
        status, _, payload = resp[:3]
        if epoch is None or status != 200 \
                or len(payload) > self.MAX_BYTES // 8:
            return
        with self._mu:
            old = self._entries.get(key)
            if old is not None:
                self._bytes -= len(old[1][2])
            if (len(self._entries) >= self.MAX
                    or self._bytes + len(payload) > self.MAX_BYTES):
                self._entries.clear()
                self._bytes = 0
            self._entries[key] = (epoch, resp[:3])
            self._bytes += len(payload)
