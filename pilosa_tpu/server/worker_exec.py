"""Worker-local read execution (phase 2 of server/workers.py).

A relay-only worker still funnels every query through the master's
GIL; with N workers EXECUTING reads themselves, count-shaped serving
scales with worker count the way the reference scales with goroutines
across cores (ref: server.go:205-217). Each worker holds a READ-ONLY
replica of the holder over the master's own data files
(`PILOSA_TPU_READ_ONLY=1` — no flock, no repair snapshots, no sidecar
writes; storage/fragment.py REPLICA gates) and re-faults it when the
master's published mutation epoch moves.

Consistency: a write relays to the master, which bumps the mmap'd
epoch counter BEFORE its HTTP response; any later read finds the
counter moved and, until the replica's resync catches up, RELAYS to
the always-current master — so every read is correct, every time.
Resyncs are throttled (REFRESH_MIN_S): an every-write full-tree
resync per worker collapsed write-heavy serving.

What serves locally: query trees whose ROOT is scalar-shaped (Count /
Sum / Min / Max / Average) and whose every node is a pure bitmap-read
call. Everything else relays: TopN (rank caches are master-maintained
and only sidecar-flushed periodically), Bitmap-rooted trees (their
responses can carry row attrs from the master's attr store), writes,
protobuf bodies, and every non-query route.
"""
import os
import re
import threading
import time

_READ_CALLS = frozenset({
    "Count", "Bitmap", "Intersect", "Union", "Difference", "Xor",
    "Range", "Sum", "Min", "Max", "Average"})
_SCALAR_ROOTS = frozenset({"Count", "Sum", "Min", "Max", "Average"})
_QUERY_RE = re.compile(r"^/index/([^/]+)/query$")


def _all_read_calls(call):
    if call.name not in _READ_CALLS:
        return False
    return all(_all_read_calls(c) for c in call.children)


class WorkerExecutor:
    def __init__(self, data_dir):
        from pilosa_tpu.utils.platform import apply_platform_override

        apply_platform_override()
        from pilosa_tpu.executor import Executor
        from pilosa_tpu.server.handler import Handler
        from pilosa_tpu.storage import fragment as fragment_mod
        from pilosa_tpu.storage.holder import Holder

        assert fragment_mod.REPLICA, \
            "worker exec requires PILOSA_TPU_READ_ONLY=1 (WorkerPool sets it)"
        self._fragment_mod = fragment_mod
        self.holder = Holder(data_dir)
        self.holder.open()
        self.executor = Executor(self.holder)
        self.handler = Handler(self.holder, self.executor)
        self._epoch = fragment_mod.open_published_epochs(
            os.path.join(data_dir, ".mutation_epoch"))
        self._seen = self._epoch()
        self._refresh_mu = threading.Lock()
        self._last_refresh = 0.0

    # ------------------------------------------------------------ dispatch

    def dispatch(self, method, path, qp, body, headers):
        """Serve locally when safe; None = relay to master."""
        if method != "POST":
            return None
        m = _QUERY_RE.match(path)
        if m is None:
            return None
        if headers.get("Content-Type") == "application/x-protobuf" or \
                headers.get("Accept") == "application/x-protobuf":
            return None  # internal/cluster traffic stays on the master
        try:
            # The executor's bounded parse memo — the same tree this
            # worker's handler.dispatch will use moments later.
            calls = self.executor._parse_memo(body.decode()).calls
        except Exception:  # noqa: BLE001 — let the master shape the error
            return None
        if not calls or not all(
                c.name in _SCALAR_ROOTS and _all_read_calls(c)
                for c in calls):
            return None
        if not self._fresh():
            # Stale replica: RELAY instead of refreshing inline. The
            # master is always current, so correctness never depends
            # on the refresh — and under a write-heavy load an
            # every-write refresh (full tree resync + executor cache
            # loss per worker per write) collapsed mixed serving
            # (measured 1,878 -> 95 q/s from 8 to 32 clients on one
            # core). Refreshes run at most every REFRESH_MIN_S.
            return None
        # Schema presence check AFTER the refresh: DDL bumps the
        # published epoch, but a replica scan can still trail a
        # concurrent create by one request — relay rather than answer
        # 404 for an index/frame the master already has.
        if self.holder.index(m.group(1)) is None:
            return None
        status, ctype, payload = self.handler.dispatch(
            method, path, qp, body, headers)
        if status in (400, 404):
            # Missing frame / stale-schema shapes: let the master (the
            # schema authority) produce the answer or the error.
            return None
        # Fourth element: extra response headers — lets tests and
        # operators see which process answered.
        return status, ctype, payload, {"X-Pilosa-Served-By": "worker"}

    REFRESH_MIN_S = 0.25

    def _fresh(self):
        """True when the replica may serve this read. On epoch
        movement, refresh at most every REFRESH_MIN_S (the caller
        relays meanwhile — reads stay correct through the master)."""
        cur = self._epoch()
        if cur == self._seen:
            return True
        if not self._refresh_mu.acquire(blocking=False):
            return False  # someone is refreshing; relay
        try:
            cur = self._epoch()
            if cur == self._seen:
                return True
            now = time.monotonic()
            if now - self._last_refresh < self.REFRESH_MIN_S:
                return False
            # Stamp BEFORE the resync so a failing refresh is also
            # throttled — and a failure means RELAY (return False),
            # never an error: correctness never depends on the
            # refresh (e.g. the master deleting an index mid-scan
            # can race the replica walk).
            self._last_refresh = now
            try:
                # Read the counter BEFORE refreshing: a bump landing
                # mid-refresh stays unseen and triggers the next one.
                self.holder.refresh_replica()
            except Exception:  # noqa: BLE001 — relay until next try
                return False
            self._seen = cur
            return True
        finally:
            self._refresh_mu.release()
