"""Worker-local read execution (phase 2 of server/workers.py).

A relay-only worker still funnels every query through the master's
GIL; with N workers EXECUTING reads themselves, count-shaped serving
scales with worker count the way the reference scales with goroutines
across cores (ref: server.go:205-217). Each worker holds a READ-ONLY
replica of the holder over the master's own data files
(`PILOSA_TPU_READ_ONLY=1` — no flock, no repair snapshots, no sidecar
writes; storage/fragment.py REPLICA gates) and re-faults it when the
master's published mutation epoch moves.

Consistency: a write relays to the master, which bumps the mmap'd
epoch counter BEFORE its HTTP response; any later read finds the
counter moved and, until the replica's resync catches up, RELAYS to
the always-current master — so every read is correct, every time.
Resyncs are throttled (REFRESH_MIN_S): an every-write full-tree
resync per worker collapsed write-heavy serving.

What MAY serve locally: query trees whose ROOT is scalar-shaped
(Count / Sum / Min / Max / Average) and whose every node is a pure
bitmap-read call. Everything else relays: TopN (rank caches are
master-maintained and only sidecar-flushed periodically),
Bitmap-rooted trees (their responses can carry row attrs from the
master's attr store), writes, protobuf bodies, and every non-query
route.

Whether an ELIGIBLE query actually serves locally is a learned
per-(call shape, slice-count bucket) COST decision (RelayCostModel):
the worker replica executes on the host CPU, while the master may own
an accelerator — a wide-window Count is 100×+ faster through the
master's device stacks than through a worker's CPU popcount, but a
narrow or host-cached read is faster served right here without the
extra hop. The model mirrors the executor's adaptive path model
(aged rolling minima per arm, exploration, periodic re-measure of the
loser — the mapperLocal-never-loses invariant, ref:
executor.go:1537): no shape is ever permanently parked on a losing
path. ``PILOSA_TPU_WORKER_PATH=local|relay`` pins the choice (tests,
operators).
"""
import os
import re
import threading
import time
from pilosa_tpu import lockcheck

_READ_CALLS = frozenset({
    "Count", "Bitmap", "Intersect", "Union", "Difference", "Xor",
    "Range", "Sum", "Min", "Max", "Average"})
_SCALAR_ROOTS = frozenset({"Count", "Sum", "Min", "Max", "Average"})
_QUERY_RE = re.compile(r"^/index/([^/]+)/query$")


def _all_read_calls(call):
    if call.name not in _READ_CALLS:
        return False
    return all(_all_read_calls(c) for c in call.children)


class RelayCostModel:
    """Learned local-CPU vs relay-to-master choice per (call shape,
    slice-count bucket).

    Samples are WALL TIMES of complete serves: the local arm times the
    replica handler dispatch; the relay arm times the full unix-socket
    round trip (master queue + device execution + transport). Each arm
    keeps an aged rolling MINIMUM (one-off costs — replica cache
    fills, master-side XLA compiles — must not bake into the
    steady-state estimate; 1%/query inflation lets a stale minimum
    decay). The loser is re-measured periodically so neither arm is
    ever permanently lost (executor.go:1537's mapperLocal invariant);
    a local probe that loses CATASTROPHICALLY (>5× the relay minimum —
    the CPU-walk-of-a-device-window case) backs its re-measure
    interval off geometrically, bounding probe overhead to a vanishing
    fraction of serving."""

    EXPLORE_N = 10
    REMEASURE_EVERY = 64
    REMEASURE_MAX = 4096
    AGE = 1.01
    HYSTERESIS = 0.98
    CATASTROPHIC = 5.0

    def __init__(self, force=None):
        self._mu = lockcheck.register("worker_exec.RelayCostModel._mu",
                                      threading.Lock())
        self._stats = {}
        if force is not None and force not in ("local", "relay"):
            # A typo'd pin ('Relay', 'remote') must not silently park
            # the worker on the possibly-100x-catastrophic local arm.
            import sys

            print(f"warning: PILOSA_TPU_WORKER_PATH={force!r} is not "
                  "'local'|'relay'; ignoring (adaptive)",
                  file=sys.stderr)
            force = None
        self.force = force  # "local" | "relay" | None
        self.choices = {"local": 0, "relay_cost": 0, "relay_forced": 0}

    def choose(self, key):
        """-> 'local' | 'relay' for one eligible query."""
        if self.force is not None:
            with self._mu:
                self.choices["local" if self.force == "local"
                             else "relay_cost"] += 1
            return self.force
        with self._mu:
            st = self._stats.setdefault(key, {"n": 0})
            n = st["n"]
            st["n"] = n + 1
            for p in ("l", "r"):
                if p in st:
                    st[p] *= self.AGE
            loc, rel = st.get("l"), st.get("r")
            if rel is None:
                # Relay first: always-correct, cheap to sample (the
                # master's own adaptive paths bound it); the possibly-
                # catastrophic local probe waits for a baseline.
                choice = "relay"
            elif loc is None:
                choice = "local"
            elif n < self.EXPLORE_N:
                # Alternate so both minima hold several samples before
                # the steady-state pick — one noisy sample must not
                # park the model on the wrong path.
                choice = "local" if n % 2 else "relay"
            elif n % st.get("every", self.REMEASURE_EVERY) == 0:
                choice = "local" if loc >= rel else "relay"  # loser
            else:
                choice = ("local" if loc < self.HYSTERESIS * rel
                          else "relay")
            self.choices["local" if choice == "local"
                         else "relay_cost"] += 1
            return choice

    REGIME_SAMPLES = 8

    def record(self, key, arm, elapsed):
        """Record a completed serve's wall time for one arm
        ('l' local / 'r' relay)."""
        with self._mu:
            st = self._stats.setdefault(key, {"n": 0})
            prev = st.get(arm)
            if (arm == "r" and prev is not None
                    and elapsed > 2.0 * prev):
                # A rolling minimum can only fall; REGIME_SAMPLES
                # consecutive relay serves at >2x the minimum mean the
                # master's cost regime changed (device lost, overload)
                # — resync the minimum to reality and re-arm local
                # probing, instead of waiting out the 1%/query aging.
                st["r_hi"] = st.get("r_hi", 0) + 1
                if st["r_hi"] >= self.REGIME_SAMPLES:
                    st["r"] = elapsed
                    st["r_hi"] = 0
                    st.pop("every", None)
                return
            if arm == "r":
                st["r_hi"] = 0
            st[arm] = elapsed if prev is None else min(prev, elapsed)
            if arm == "l":
                rel = st.get("r")
                if rel is not None and elapsed > self.CATASTROPHIC * rel:
                    st["every"] = min(
                        st.get("every", self.REMEASURE_EVERY) * 4,
                        self.REMEASURE_MAX)
                elif elapsed < (rel or float("inf")):
                    st.pop("every", None)  # local competitive again

    def snapshot(self):
        """Choice counters + per-key arm minima for /debug/worker."""
        with self._mu:
            keys = {}
            for (sig, bucket), st in self._stats.items():
                keys[f"{sig}/2^{bucket}slices"] = {
                    "queries": st.get("n", 0),
                    "localMs": (round(st["l"] * 1000, 3)
                                if "l" in st else None),
                    "relayMs": (round(st["r"] * 1000, 3)
                                if "r" in st else None),
                    "remeasureEvery": st.get("every",
                                             self.REMEASURE_EVERY),
                }
            return {"choices": dict(self.choices), "keys": keys,
                    "forced": self.force}


class WorkerExecutor:
    def __init__(self, data_dir):
        from pilosa_tpu.utils.platform import apply_platform_override

        apply_platform_override()
        from pilosa_tpu.executor import Executor
        from pilosa_tpu.server.handler import Handler
        from pilosa_tpu.storage import fragment as fragment_mod
        from pilosa_tpu.storage.holder import Holder

        assert fragment_mod.REPLICA, \
            "worker exec requires PILOSA_TPU_READ_ONLY=1 (WorkerPool sets it)"
        self._fragment_mod = fragment_mod
        self.holder = Holder(data_dir)
        self.holder.open()
        self.executor = Executor(self.holder)
        # Warm-start the replica executor's batched-vs-serial model
        # from the master's persisted minima (read-only — REPLICA mode
        # forbids sidecar writes, and the master owns the file):
        # workers respawn with every master boot and would otherwise
        # pay the exploration probes per shape per worker.
        try:
            import json as _json

            with open(os.path.join(data_dir, ".path_model.json")) as f:
                self.executor.load_path_model(_json.load(f))
        except (OSError, ValueError):
            pass
        self.handler = Handler(self.holder, self.executor)
        self._epoch = fragment_mod.open_published_epochs(
            os.path.join(data_dir, ".mutation_epoch"))
        self._seen = self._epoch()
        self._refresh_mu = lockcheck.register(
            "worker_exec.WorkerExecutor._refresh_mu", threading.Lock())
        self._last_refresh = 0.0
        self.cost = RelayCostModel(
            force=os.environ.get("PILOSA_TPU_WORKER_PATH") or None)
        self._tl = threading.local()

    # ------------------------------------------------------------ dispatch

    @staticmethod
    def _sig(call):
        if not call.children:
            return call.name
        return (f"{call.name}("
                f"{','.join(WorkerExecutor._sig(c) for c in call.children)})")

    def dispatch(self, method, path, qp, body, headers):
        """Serve locally when safe AND predicted cheaper; None = relay
        to master (the caller reports the relay's wall time back via
        relay_observed so the cost model sees both arms)."""
        self._tl.pending = None
        if method != "POST":
            return None
        m = _QUERY_RE.match(path)
        if m is None:
            return None
        if headers.get("Content-Type") == "application/x-protobuf" or \
                headers.get("Accept") == "application/x-protobuf":
            return None  # internal/cluster traffic stays on the master
        if ("profile" in qp or "explain" in qp
                or headers.get("X-Pilosa-Trace-Id")
                or headers.get("X-Pilosa-Collect-Stats")):
            # Traced/profiled/explained/stat-collected queries relay:
            # the MASTER owns the tracer, the querystats accumulator,
            # and the query inspector's tier/plan state — a worker
            # replica serving one locally would record nothing and
            # return no profile tree / explain block / stats footer.
            return None
        try:
            # The executor's bounded parse memo — the same tree this
            # worker's handler.dispatch will use moments later.
            calls = self.executor._parse_memo(body.decode()).calls
        except Exception:  # noqa: BLE001 — let the master shape the error
            return None
        if not calls or not all(
                c.name in _SCALAR_ROOTS and _all_read_calls(c)
                for c in calls):
            return None
        # Schema presence: a replica can trail a concurrent create by
        # one request — relay rather than answer 404 for an index the
        # master already has. (No cost sample: the key needs the
        # index's slice count.)
        idx = self.holder.index(m.group(1))
        if idx is None:
            return None
        key = ("\n".join(self._sig(c) for c in calls),
               max(idx.max_slice() + 1, 1).bit_length())
        if self.cost.choose(key) == "relay":
            # Model-driven relay (the master may own an accelerator
            # that beats this worker's CPU popcount 100×+ on wide
            # windows): time the full round trip as the relay arm.
            self._tl.pending = (key, time.perf_counter(), "r")
            return None
        if not self._fresh():
            # Stale replica: RELAY instead of refreshing inline. The
            # master is always current, so correctness never depends
            # on the refresh — and under a write-heavy load an
            # every-write refresh (full tree resync + executor cache
            # loss per worker per write) collapsed mixed serving
            # (measured 1,878 -> 95 q/s from 8 to 32 clients on one
            # core). Refreshes run at most every REFRESH_MIN_S. The
            # round trip still samples the relay arm — it measures the
            # same master path a cost relay would. The choose() above
            # counted this request as 'local'; re-book it as forced.
            with self.cost._mu:
                self.cost.choices["local"] -= 1
                self.cost.choices["relay_forced"] += 1
            self._tl.pending = (key, time.perf_counter(), "r")
            return None
        t0 = time.perf_counter()
        status, ctype, payload = self.handler.dispatch(
            method, path, qp, body, headers)
        if status in (400, 404):
            # Missing frame / stale-schema shapes: let the master (the
            # schema authority) produce the answer or the error. The
            # wasted local attempt PLUS the relay that follows is the
            # true cost of choosing local for this key — book the
            # whole round trip to the LOCAL arm so a persistently
            # erroring local path converges to relay instead of
            # parking on local unsampled.
            self._tl.pending = (key, t0, "l")
            return None
        self.cost.record(key, "l", time.perf_counter() - t0)
        # Fourth element: extra response headers — lets tests and
        # operators see which process answered.
        return status, ctype, payload, {"X-Pilosa-Served-By": "worker"}

    def relay_observed(self, resp):
        """Called by the worker loop after a relay completes: close the
        timing sample for the arm dispatch stashed ('r' for model/
        forced relays; 'l' for a failed local attempt whose true cost
        includes the relay that repaired it)."""
        pending = getattr(self._tl, "pending", None)
        self._tl.pending = None
        if pending is None:
            return
        key, t0, arm = pending
        if resp and resp[0] < 500:  # a 503 master outage is not a sample
            self.cost.record(key, arm, time.perf_counter() - t0)

    REFRESH_MIN_S = 0.25

    def _fresh(self):
        """True when the replica may serve this read. On epoch
        movement, refresh at most every REFRESH_MIN_S (the caller
        relays meanwhile — reads stay correct through the master)."""
        cur = self._epoch()
        if cur == self._seen:
            return True
        if not self._refresh_mu.acquire(blocking=False):
            return False  # someone is refreshing; relay
        try:
            cur = self._epoch()
            if cur == self._seen:
                return True
            now = time.monotonic()
            if now - self._last_refresh < self.REFRESH_MIN_S:
                return False
            # Stamp BEFORE the resync so a failing refresh is also
            # throttled — and a failure means RELAY (return False),
            # never an error: correctness never depends on the
            # refresh (e.g. the master deleting an index mid-scan
            # can race the replica walk).
            self._last_refresh = now
            try:
                # Read the counter BEFORE refreshing: a bump landing
                # mid-refresh stays unseen and triggers the next one.
                self.holder.refresh_replica()
            except Exception:  # noqa: BLE001 — relay until next try
                return False
            self._seen = cur
            return True
        finally:
            self._refresh_mu.release()
