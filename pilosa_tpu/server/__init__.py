"""HTTP API + server assembly (ref: handler.go, server.go, server/)."""
from pilosa_tpu.server.handler import Handler  # noqa: F401
from pilosa_tpu.server.server import Server  # noqa: F401
