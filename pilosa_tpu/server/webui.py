"""Embedded web console (ref: webui/ — single-page console with a query
textarea + PQL autocomplete, schema sidebar, and result rendering,
webui/assets/main.js; served at "/" by handleWebUI handler.go:196-210,
assets at /assets/{file} handler.go:101).
"""

INDEX_HTML = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>pilosa-tpu console</title>
<link rel="stylesheet" href="/assets/main.css">
</head>
<body>
<div id="main">
  <h1>pilosa-tpu console <span id="ver"></span></h1>
  <p>index: <input type="text" id="index" value="i" size="14"></p>
  <div style="position:relative">
    <textarea id="query" spellcheck="false"
     placeholder='Count(Bitmap(frame="f", rowID=1))'></textarea>
    <div id="autocomplete"></div>
  </div>
  <div id="hint">ctrl/cmd+enter to run &middot; click schema entries to
    insert &middot; calls autocomplete as you type</div>
  <button onclick="runQuery()">Query</button>
  <span id="timing"></span>
  <div id="result"></div>
  <h2>history</h2>
  <div id="history"></div>
</div>
<div id="side">
  <h2>schema</h2>
  <div id="schema">loading…</div>
  <div id="create">
    <input type="text" id="newname" placeholder="name" size="10">
    <button class="mini" onclick="createIndex()">+index</button>
    <button class="mini" onclick="createFrame()">+frame</button>
  </div>
  <h2>cluster</h2>
  <div id="nodes"></div>
</div>
<script src="/assets/main.js"></script>
</body>
</html>
"""

ASSETS = {
    "main.css": ("text/css", """ :root { --bg:#101014; --panel:#16161c; --line:#2a2a33; --fg:#d8d8e0;
         --dim:#8a8a96; --acc:#2fa374; --err:#c75050; }
 body { font-family: 'SF Mono', Menlo, Consolas, monospace; margin: 0;
        background: var(--bg); color: var(--fg); display: flex;
        height: 100vh; }
 #main { flex: 1; padding: 1.2em 1.6em; overflow-y: auto; }
 #side { width: 320px; border-left: 1px solid var(--line);
         padding: 1.2em; overflow-y: auto; background: var(--panel); }
 h1 { font-size: 1.05em; margin: 0 0 .8em; color: var(--acc); }
 h2 { font-size: .85em; color: var(--dim); text-transform: uppercase;
      letter-spacing: .08em; margin: 1.2em 0 .4em; }
 textarea { width: 100%; height: 7em; background: var(--panel);
            color: var(--fg); border: 1px solid var(--line);
            border-radius: 4px; padding: .6em; font: inherit;
            box-sizing: border-box; resize: vertical; }
 input[type=text] { background: var(--panel); color: var(--fg);
            border: 1px solid var(--line); border-radius: 4px;
            padding: .3em .5em; font: inherit; }
 button { background: var(--acc); color: #fff; border: 0;
          padding: .45em 1.2em; border-radius: 4px; cursor: pointer;
          font: inherit; }
 button:hover { filter: brightness(1.15); }
 pre { background: var(--panel); border: 1px solid var(--line);
       border-radius: 4px; padding: .8em; overflow-x: auto;
       font-size: .85em; }
 table { border-collapse: collapse; margin: .6em 0; font-size: .85em; }
 td, th { border: 1px solid var(--line); padding: .25em .7em;
          text-align: right; }
 th { color: var(--dim); }
 .err { color: var(--err); }
 .schema-item { cursor: pointer; padding: .1em 0; }
 .schema-item:hover { color: var(--acc); }
 .frame { padding-left: 1em; color: var(--fg); }
 .field { padding-left: 2em; color: var(--dim); }
 #hint { color: var(--dim); font-size: .8em; margin: .3em 0; }
 #autocomplete { position: absolute; background: var(--panel);
     border: 1px solid var(--line); border-radius: 4px; z-index: 10;
     max-height: 12em; overflow-y: auto; display: none; }
 #autocomplete div { padding: .2em .6em; cursor: pointer; }
 #autocomplete div.sel, #autocomplete div:hover { background: var(--line); }
 .hist { cursor: pointer; color: var(--dim); font-size: .8em;
         white-space: nowrap; overflow: hidden; text-overflow: ellipsis; }
 .hist:hover { color: var(--acc); }
 #ver { color: var(--dim); font-size: .75em; float: right; }
 #timing { color: var(--dim); font-size: .8em; margin-left: .8em; }
 button.mini { padding: .2em .5em; font-size: .75em; }
 #create input { width: 7em; font-size: .8em; }
 .node { font-size: .85em; padding: .1em 0; }
 .node .up { color: var(--acc); }
 .node .down { color: var(--err); }
"""),
    "main.js": ("application/javascript", """const CALLS = [
  'Bitmap(frame="", rowID=)', 'Union()', 'Intersect()', 'Difference()',
  'Xor()', 'Count()', 'TopN(frame="", n=)', 'Range(frame="", )',
  'Sum(frame="", field="")', 'Min(frame="", field="")',
  'Max(frame="", field="")', 'SetBit(frame="", rowID=, columnID=)',
  'ClearBit(frame="", rowID=, columnID=)',
  'SetRowAttrs(frame="", rowID=, )', 'SetColumnAttrs(columnID=, )',
  'SetFieldValue(frame="", columnID=, )'];
const qEl = () => document.getElementById('query');

async function refreshMeta() {
  try {
    const s = await (await fetch('/schema')).json();
    const el = document.getElementById('schema');
    el.innerHTML = '';
    for (const idx of s.indexes || []) {
      const d = document.createElement('div');
      d.className = 'schema-item';
      d.textContent = idx.name;
      d.onclick = () => { document.getElementById('index').value = idx.name; };
      el.appendChild(d);
      for (const fr of idx.frames || []) {
        const f = document.createElement('div');
        f.className = 'schema-item frame';
        f.textContent = fr.name;
        f.onclick = () => insert('Bitmap(frame="' + fr.name + '", rowID=)');
        el.appendChild(f);
        for (const fld of fr.fields || []) {
          const g = document.createElement('div');
          g.className = 'schema-item field';
          g.textContent = fld.name + ' [' + fld.min + ',' + fld.max + ']';
          g.onclick = () => insert(
              'Sum(frame="' + fr.name + '", field="' + fld.name + '")');
          el.appendChild(g);
        }
      }
    }
    if (!(s.indexes || []).length) el.textContent = '(no indexes)';
    // /hosts + /slices/max stay light; /status would re-ship the full
    // schema we already fetched above.
    const hosts = await (await fetch('/hosts')).json();
    let states = {};
    if (hosts.length > 1) {
      const st = (await (await fetch('/status')).json()).status || {};
      states = st.nodeStates || {};
    }
    const nodesEl = document.getElementById('nodes');
    nodesEl.innerHTML = '';
    if (hosts.length <= 1) {
      // Single node: no membership states exist, don't fabricate one.
      const host = hosts.length ? (hosts[0].host || hosts[0]) : 'localhost';
      nodesEl.textContent = host + ' (single node)';
    } else {
      for (const n of hosts) {
        const host = n.host || n;
        const state = states[host] || 'UP';
        const d = document.createElement('div');
        d.className = 'node';
        d.innerHTML = '<span class="' + state.toLowerCase() + '">●</span> ';
        d.appendChild(document.createTextNode(host + ' ' + state));
        nodesEl.appendChild(d);
      }
    }
    const v = await (await fetch('/version')).json();
    document.getElementById('ver').textContent = 'v' + v.version;
  } catch (e) { /* server restarting */ }
}

async function createErr(resp) {
  if (resp.ok) return false;
  let msg = resp.status;
  try { msg = (await resp.json()).error || msg; } catch (e) {}
  const el = document.getElementById('result');
  el.innerHTML = '<pre class="err"></pre>';
  el.firstChild.textContent = 'create failed: ' + msg;
  return true;
}

async function createIndex() {
  const name = document.getElementById('newname').value.trim();
  if (!name) return;
  try {
    const r = await fetch('/index/' + encodeURIComponent(name),
                          {method: 'POST', body: '{}'});
    if (await createErr(r)) return;
    document.getElementById('index').value = name;
  } catch (e) { return; }
  refreshMeta();
}

async function createFrame() {
  const name = document.getElementById('newname').value.trim();
  const idx = document.getElementById('index').value.trim();
  if (!name || !idx) return;
  try {
    const r = await fetch('/index/' + encodeURIComponent(idx) + '/frame/' +
                          encodeURIComponent(name),
                          {method: 'POST', body: '{}'});
    if (await createErr(r)) return;
  } catch (e) { return; }
  refreshMeta();
}

function insert(text) {
  const q = qEl();
  const pos = q.selectionStart;
  q.value = q.value.slice(0, pos) + text + q.value.slice(q.selectionEnd);
  q.focus();
  q.selectionStart = q.selectionEnd = pos + text.length;
}

function renderResult(data) {
  const el = document.getElementById('result');
  el.innerHTML = '';
  if (data.error) {
    el.innerHTML = '<pre class="err"></pre>';
    el.firstChild.textContent = data.error;
    return;
  }
  for (const r of data.results || []) {
    if (r && typeof r === 'object' && Array.isArray(r) && r.length &&
        r[0] && typeof r[0] === 'object' && 'id' in r[0]) {
      const t = document.createElement('table');  // TopN pairs
      t.innerHTML = '<tr><th>row</th><th>count</th></tr>';
      for (const p of r) t.innerHTML +=
          '<tr><td>' + p.id + '</td><td>' + p.count + '</td></tr>';
      el.appendChild(t);
    } else if (r && typeof r === 'object' && 'bits' in r) {
      const pre = document.createElement('pre');  // bitmap
      const bits = r.bits;
      pre.textContent = bits.length + ' bits: ' +
          JSON.stringify(bits.slice(0, 1000)) +
          (bits.length > 1000 ? ' …' : '') +
          (r.attrs && Object.keys(r.attrs).length
             ? '\\nattrs: ' + JSON.stringify(r.attrs) : '');
      el.appendChild(pre);
    } else {
      const pre = document.createElement('pre');
      pre.textContent = JSON.stringify(r, null, 1);
      el.appendChild(pre);
    }
  }
}

function pushHistory(q) {
  let h = JSON.parse(localStorage.getItem('pql_history') || '[]');
  h = [q].concat(h.filter(x => x !== q)).slice(0, 20);
  localStorage.setItem('pql_history', JSON.stringify(h));
  renderHistory();
}

function renderHistory() {
  const h = JSON.parse(localStorage.getItem('pql_history') || '[]');
  const el = document.getElementById('history');
  el.innerHTML = '';
  for (const q of h) {
    const d = document.createElement('div');
    d.className = 'hist';
    d.textContent = q;
    d.onclick = () => { qEl().value = q; };
    el.appendChild(d);
  }
}

async function runQuery() {
  const idx = document.getElementById('index').value;
  const q = qEl().value.trim();
  if (!q) return;
  const t0 = performance.now();
  const r = await fetch('/index/' + encodeURIComponent(idx) + '/query',
                        {method: 'POST', body: q});
  const body = await r.json();  // time includes the body download
  const ms = performance.now() - t0;
  document.getElementById('timing').textContent =
      ms >= 1 ? ms.toFixed(1) + ' ms' : (ms * 1000).toFixed(0) + ' µs';
  renderResult(body);
  pushHistory(q);
  refreshMeta();
}

// --- autocomplete -----------------------------------------------------
let acSel = 0;
function currentWord() {
  const q = qEl();
  const upto = q.value.slice(0, q.selectionStart);
  const m = upto.match(/[A-Za-z]+$/);
  return m ? m[0] : '';
}
function showAC() {
  const word = currentWord();
  const box = document.getElementById('autocomplete');
  if (word.length < 1) { box.style.display = 'none'; return; }
  const hits = CALLS.filter(c =>
      c.toLowerCase().startsWith(word.toLowerCase()));
  if (!hits.length) { box.style.display = 'none'; return; }
  acSel = Math.min(acSel, hits.length - 1);
  box.innerHTML = '';
  hits.forEach((h, i) => {
    const d = document.createElement('div');
    d.textContent = h;
    if (i === acSel) d.className = 'sel';
    d.onmousedown = (ev) => { ev.preventDefault(); acceptAC(h); };
    box.appendChild(d);
  });
  box.style.display = 'block';
}
function acceptAC(call) {
  const q = qEl();
  const word = currentWord();
  const pos = q.selectionStart;
  q.value = q.value.slice(0, pos - word.length) + call +
            q.value.slice(q.selectionEnd);
  const cursor = pos - word.length + call.indexOf('(') + 1;
  q.selectionStart = q.selectionEnd = cursor;
  document.getElementById('autocomplete').style.display = 'none';
  q.focus();
}
qEl().addEventListener('input', () => { acSel = 0; showAC(); });
qEl().addEventListener('keydown', (e) => {
  const box = document.getElementById('autocomplete');
  const open = box.style.display === 'block';
  if ((e.ctrlKey || e.metaKey) && e.key === 'Enter') {
    e.preventDefault(); runQuery(); return;
  }
  if (!open) return;
  const n = box.children.length;
  if (e.key === 'ArrowDown') { e.preventDefault(); acSel = (acSel+1)%n; showAC(); }
  else if (e.key === 'ArrowUp') { e.preventDefault(); acSel = (acSel+n-1)%n; showAC(); }
  else if (e.key === 'Tab' || e.key === 'Enter') {
    e.preventDefault(); acceptAC(box.children[acSel].textContent);
  } else if (e.key === 'Escape') { box.style.display = 'none'; }
});
qEl().addEventListener('blur', () => setTimeout(() =>
    document.getElementById('autocomplete').style.display = 'none', 150));

refreshMeta();
renderHistory();
"""),
}
