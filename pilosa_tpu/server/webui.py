"""Minimal embedded web console (ref: webui/ single-page console —
query textarea, schema sidebar, result rendering)."""

INDEX_HTML = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>pilosa-tpu console</title>
<style>
 body { font-family: monospace; margin: 2em; background: #111; color: #ddd; }
 h1 { font-size: 1.2em; }
 #schema { float: right; width: 30%; border-left: 1px solid #444;
           padding-left: 1em; white-space: pre; }
 textarea { width: 60%; height: 6em; background: #222; color: #ddd;
            border: 1px solid #444; padding: .5em; }
 input[type=text] { background: #222; color: #ddd; border: 1px solid #444; }
 button { background: #2a6; color: #fff; border: 0; padding: .4em 1em; }
 pre { background: #181818; padding: 1em; overflow-x: auto; }
</style>
</head>
<body>
<h1>pilosa-tpu console</h1>
<div id="schema">loading schema…</div>
<p>index: <input type="text" id="index" value="i" size="12"></p>
<textarea id="query"
 placeholder='Count(Bitmap(frame="f", rowID=1))'></textarea><br>
<button onclick="runQuery()">Query</button>
<pre id="result"></pre>
<script>
async function refreshSchema() {
  const r = await fetch('/schema');
  const s = await r.json();
  document.getElementById('schema').textContent =
      JSON.stringify(s, null, 2);
}
async function runQuery() {
  const idx = document.getElementById('index').value;
  const q = document.getElementById('query').value;
  const r = await fetch('/index/' + idx + '/query', {method: 'POST', body: q});
  document.getElementById('result').textContent =
      JSON.stringify(await r.json(), null, 2);
  refreshSchema();
}
refreshSchema();
</script>
</body>
</html>
"""
