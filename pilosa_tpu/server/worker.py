"""Worker frontend process: SO_REUSEPORT HTTP listener relaying to the
master's plan socket (see workers.py for the architecture).

Run as ``python -m pilosa_tpu.server.worker --bind host:port --socket
/path/plan.sock``. The kernel's ``SO_REUSEPORT`` group spreads incoming
connections across the master and every worker (ref contrast: Go's
single listener feeds goroutines, server.go:205-217; a CPython process
can't fan one listener across cores, so we fan the listener itself).

Each HTTP connection gets a ThreadingHTTPServer thread whose requests
ride ONE persistent unix-socket connection to the master
(thread-local), so a keep-alive client costs one master thread and
zero reconnects.
"""
import argparse
import json
import os
import socket
import threading

from pilosa_tpu.server.respcache import ResponseCache  # noqa: F401 — re-export
from pilosa_tpu.server.workers import FrameError, read_frame, write_frame

_local = threading.local()


def _master_conn(sock_path):
    conn = getattr(_local, "conn", None)
    if conn is None:
        conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        conn.connect(sock_path)
        _local.conn = conn
    return conn


def _relay(sock_path, frame):
    """Round-trip one request frame, reconnecting once on a dead
    master connection (master restart between keep-alive requests)."""
    for attempt in (0, 1):
        try:
            conn = _master_conn(sock_path)
            write_frame(conn, frame)
            resp = read_frame(conn)
            if resp is not None:
                return resp
        except (OSError, FrameError):
            pass
        try:
            if getattr(_local, "conn", None) is not None:
                _local.conn.close()
        except OSError:
            pass
        _local.conn = None
    return (503, "application/json", b'{"error": "master unavailable"}')



def serve(bind, sock_path, tls_cert=None, tls_key=None, wexec=None,
          cache=None, max_body_size=None):
    """Run the worker loop. ``wexec`` (WorkerExecutor) lets phase-2
    worker-local execution intercept before the relay (its dispatch
    returns None to fall through, and its relay-vs-local cost model is
    fed the relay's wall time via relay_observed). ``cache``
    (ResponseCache) replays epoch-valid identical read responses
    before either. The HTTP plumbing is make_http_server's — the
    worker only supplies this dispatch chain."""
    from pilosa_tpu.server.handler import make_http_server

    dispatch = wexec.dispatch if wexec is not None else None

    def worker_dispatch(method, path, qp, body, headers):
        if method == "GET" and path == "/debug/worker":
            # Worker-local observability (the master's /debug/vars
            # can't see inside worker processes): response-cache
            # counters + which serving mode this worker runs + the
            # relay-vs-local cost model's choices and arm minima.
            stats = {"pid": os.getpid(),
                     "mode": "exec" if dispatch is not None else "relay",
                     "cache": cache.stats() if cache is not None
                     else None,
                     "cost_model": wexec.cost.snapshot()
                     if wexec is not None else None}
            return (200, "application/json",
                    json.dumps(stats).encode(),
                    {"X-Pilosa-Served-By": "worker"})
        key = epoch = None
        # ?profile=true / ?explain= responses must never replay from
        # cache — a profile IS a measurement of a real execution, and
        # an explain describes the serving decision a replay skips
        # (the master's Handler.dispatch applies the same exclusions
        # on its tier).
        if (cache is not None and "profile" not in (qp or ())
                and "explain" not in (qp or ())
                and cache.cacheable(method, path, body)):
            key = cache.make_key(path, qp, body, headers)
            hit = cache.get(key)
            if hit is not None:
                return hit + ({"X-Pilosa-Served-By": "worker-cache"},)
            epoch = cache.pre_epoch(path)
        resp = None
        if dispatch is not None:
            resp = dispatch(method, path, qp, body, headers)
        if resp is None:
            resp = _relay(sock_path, (method, path, qp, body, headers))
            if wexec is not None:
                wexec.relay_observed(resp)
        if key is not None:
            cache.put(key, epoch, resp)
        return resp

    kwargs = {} if max_body_size is None \
        else {"max_body_size": max_body_size}
    httpd = make_http_server(worker_dispatch, bind, reuse_port=True,
                             **kwargs)
    if tls_cert:
        import ssl

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(tls_cert, tls_key or None)
        httpd.socket = ctx.wrap_socket(httpd.socket, server_side=True)
    httpd.serve_forever()


def _parent_watchdog(parent_pid):
    """Exit when the spawning master dies (this process reparents
    away from ``parent_pid``) — a SIGKILLed master must not leave
    orphan listeners holding the port's REUSEPORT group. The EXPECTED
    pid arrives via --parent-pid: capturing os.getppid() at thread
    start raced a master that died during this worker's multi-second
    boot — the captured baseline was already init's, so the orphan
    never saw a 'change' and lived forever (observed in the
    worker-mode crash soak). Checking against the explicit pid first,
    sleep after, also catches an already-dead parent immediately."""
    import os
    import time

    while True:
        cur = os.getppid()
        # parent_pid None = flag omitted (hand-launched worker): fall
        # back to the observed parent, treating an init parent as
        # ALREADY orphaned. BEST-EFFORT only — under a subreaper
        # (systemd --user, tmux) an already-orphaned flagless worker
        # is indistinguishable from a live one, which is why
        # WorkerPool always passes --parent-pid, the reliable
        # mechanism.
        if parent_pid is None:
            if cur == 1:
                os._exit(0)
            parent_pid = cur
        if cur != parent_pid:
            os._exit(0)
        # 0.5 s bounds how long a dead master's orphan can linger in
        # the SO_REUSEPORT group answering 503s after a SIGKILL.
        time.sleep(0.5)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--bind", required=True)
    ap.add_argument("--socket", required=True)
    ap.add_argument("--tls-cert")
    ap.add_argument("--tls-key")
    ap.add_argument("--data-dir")
    ap.add_argument("--parent-pid", type=int, default=None)
    ap.add_argument("--exec-reads", action="store_true")
    ap.add_argument("--cluster-epochs", action="store_true")
    ap.add_argument("--max-body-size", type=int, default=None)
    opts = ap.parse_args(argv)
    threading.Thread(target=_parent_watchdog, args=(opts.parent_pid,),
                     daemon=True).start()
    # With master-side tracing on, this worker is a pure relay: local
    # execution and cached replay would serve queries the master's
    # tracer never sees (missing from /debug/traces, slow-query
    # metrics, ?profile=true). Master-side QoS client quotas force the
    # same relay mode — a worker-served response would be quota-free.
    master_only = bool(os.environ.get("PILOSA_TPU_MASTER_TRACING")
                       or os.environ.get("PILOSA_TPU_MASTER_QOS"))
    wexec = None
    if opts.exec_reads and opts.data_dir and not master_only:
        from pilosa_tpu.server.worker_exec import WorkerExecutor

        wexec = WorkerExecutor(opts.data_dir)
    cache = None
    if opts.data_dir and not master_only and os.environ.get(
            "PILOSA_TPU_WORKER_CACHE", "1") not in ("0", "false", "no"):
        epoch_path = os.path.join(opts.data_dir, ".mutation_epoch")
        if os.path.exists(epoch_path):
            from pilosa_tpu.storage.fragment import open_published_epochs

            raw = open_published_epochs(epoch_path)
            if opts.cluster_epochs:
                # Multi-node master: the published pair is (local
                # total, cluster vector version). Version 0 means the
                # master lost peer visibility — COLD, never stale.
                def reader(_path, _raw=raw):
                    tok = _raw()
                    return None if tok[1] == 0 else tok
            else:
                def reader(_path, _raw=raw):
                    return _raw()
            cache = ResponseCache(reader)
    serve(opts.bind, opts.socket, tls_cert=opts.tls_cert,
          tls_key=opts.tls_key, wexec=wexec, cache=cache,
          max_body_size=opts.max_body_size)


if __name__ == "__main__":
    main()
