"""Worker frontend process: SO_REUSEPORT HTTP listener relaying to the
master's plan socket (see workers.py for the architecture).

Run as ``python -m pilosa_tpu.server.worker --bind host:port --socket
/path/plan.sock``. The kernel's ``SO_REUSEPORT`` group spreads incoming
connections across the master and every worker (ref contrast: Go's
single listener feeds goroutines, server.go:205-217; a CPython process
can't fan one listener across cores, so we fan the listener itself).

Each HTTP connection gets a ThreadingHTTPServer thread whose requests
ride ONE persistent unix-socket connection to the master
(thread-local), so a keep-alive client costs one master thread and
zero reconnects.
"""
import argparse
import json
import os
import socket
import threading

from pilosa_tpu.pql.ast import WRITE_CALLS
from pilosa_tpu.server.workers import FrameError, read_frame, write_frame

_local = threading.local()


def _master_conn(sock_path):
    conn = getattr(_local, "conn", None)
    if conn is None:
        conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        conn.connect(sock_path)
        _local.conn = conn
    return conn


def _relay(sock_path, frame):
    """Round-trip one request frame, reconnecting once on a dead
    master connection (master restart between keep-alive requests)."""
    for attempt in (0, 1):
        try:
            conn = _master_conn(sock_path)
            write_frame(conn, frame)
            resp = read_frame(conn)
            if resp is not None:
                return resp
        except (OSError, FrameError):
            pass
        try:
            if getattr(_local, "conn", None) is not None:
                _local.conn.close()
        except OSError:
            pass
        _local.conn = None
    return (503, "application/json", b'{"error": "master unavailable"}')


class ResponseCache:
    """Epoch-validated replay of identical READ-query responses.

    Correctness argument: the handler is deterministic, and the
    master's published mutation epoch moves (before the write's HTTP
    response) on every data or schema change — so replaying the exact
    bytes previously produced for (path, body, accept headers) is
    indistinguishable from re-executing, as long as the epoch read
    BEFORE the original request still equals the current one. Writes
    are never cached (conservative substring gate derived from
    pql.ast.WRITE_CALLS: any body containing a write-call name is
    passed through, so a new write call added to WRITE_CALLS is
    automatically never cached), and a cached entry can never
    acknowledge a write it didn't perform. This is the warm-dashboard
    fast path for EVERY backend: on TPU it answers repeats without
    touching the master or the chip.
    """

    MAX = 512
    MAX_BYTES = 64 << 20  # payload budget, as the master's result memo
    _WRITE_MARKERS = tuple(name.encode() for name in WRITE_CALLS)

    def __init__(self, epoch_reader):
        self._epoch = epoch_reader
        self._mu = threading.Lock()
        self._entries = {}
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    def cacheable(self, method, path, body):
        return (method == "POST" and path.endswith("/query")
                and not any(m in body for m in self._WRITE_MARKERS))

    def pre_epoch(self):
        """Read BEFORE issuing the request: a write landing mid-flight
        makes the stored epoch stale and the entry a harmless miss —
        never the reverse."""
        return self._epoch()

    def get(self, key):
        cur = self._epoch()
        with self._mu:
            hit = self._entries.get(key)
            if hit is None:
                self.misses += 1
                return None
            if hit[0] != cur:
                # Stale entries are dead weight — evict on discovery
                # instead of waiting for the count cap's full clear.
                del self._entries[key]
                self._bytes -= len(hit[1][2])
                self.misses += 1
                return None
            self.hits += 1
        return hit[1]

    def stats(self):
        with self._mu:
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "hits": self.hits, "misses": self.misses}

    def put(self, key, epoch, resp):
        status, _, payload = resp[:3]
        if status != 200 or len(payload) > self.MAX_BYTES // 8:
            return
        with self._mu:
            old = self._entries.get(key)
            if old is not None:
                self._bytes -= len(old[1][2])
            if (len(self._entries) >= self.MAX
                    or self._bytes + len(payload) > self.MAX_BYTES):
                self._entries.clear()
                self._bytes = 0
            self._entries[key] = (epoch, resp[:3])
            self._bytes += len(payload)


def serve(bind, sock_path, tls_cert=None, tls_key=None, wexec=None,
          cache=None):
    """Run the worker loop. ``wexec`` (WorkerExecutor) lets phase-2
    worker-local execution intercept before the relay (its dispatch
    returns None to fall through, and its relay-vs-local cost model is
    fed the relay's wall time via relay_observed). ``cache``
    (ResponseCache) replays epoch-valid identical read responses
    before either. The HTTP plumbing is make_http_server's — the
    worker only supplies this dispatch chain."""
    from pilosa_tpu.server.handler import make_http_server

    dispatch = wexec.dispatch if wexec is not None else None

    def worker_dispatch(method, path, qp, body, headers):
        if method == "GET" and path == "/debug/worker":
            # Worker-local observability (the master's /debug/vars
            # can't see inside worker processes): response-cache
            # counters + which serving mode this worker runs + the
            # relay-vs-local cost model's choices and arm minima.
            stats = {"pid": os.getpid(),
                     "mode": "exec" if dispatch is not None else "relay",
                     "cache": cache.stats() if cache is not None
                     else None,
                     "cost_model": wexec.cost.snapshot()
                     if wexec is not None else None}
            return (200, "application/json",
                    json.dumps(stats).encode(),
                    {"X-Pilosa-Served-By": "worker"})
        key = epoch = None
        if cache is not None and cache.cacheable(method, path, body):
            # Encoding negotiation is part of the response bytes.
            # parse_qs values are LISTS — tuple them or the key is
            # unhashable and every ?param=... query request crashes.
            key = (path,
                   tuple((k, tuple(v)) for k, v in sorted(qp.items()))
                   if qp else None,
                   body, headers.get("Content-Type"),
                   headers.get("Accept"))
            hit = cache.get(key)
            if hit is not None:
                return hit + ({"X-Pilosa-Served-By": "worker-cache"},)
            epoch = cache.pre_epoch()
        resp = None
        if dispatch is not None:
            resp = dispatch(method, path, qp, body, headers)
        if resp is None:
            resp = _relay(sock_path, (method, path, qp, body, headers))
            if wexec is not None:
                wexec.relay_observed(resp)
        if key is not None:
            cache.put(key, epoch, resp)
        return resp

    httpd = make_http_server(worker_dispatch, bind, reuse_port=True)
    if tls_cert:
        import ssl

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(tls_cert, tls_key or None)
        httpd.socket = ctx.wrap_socket(httpd.socket, server_side=True)
    httpd.serve_forever()


def _parent_watchdog(parent_pid):
    """Exit when the spawning master dies (this process reparents
    away from ``parent_pid``) — a SIGKILLed master must not leave
    orphan listeners holding the port's REUSEPORT group. The EXPECTED
    pid arrives via --parent-pid: capturing os.getppid() at thread
    start raced a master that died during this worker's multi-second
    boot — the captured baseline was already init's, so the orphan
    never saw a 'change' and lived forever (observed in the
    worker-mode crash soak). Checking against the explicit pid first,
    sleep after, also catches an already-dead parent immediately."""
    import os
    import time

    while True:
        cur = os.getppid()
        # parent_pid None = flag omitted (hand-launched worker): fall
        # back to the observed parent, treating an init parent as
        # ALREADY orphaned. BEST-EFFORT only — under a subreaper
        # (systemd --user, tmux) an already-orphaned flagless worker
        # is indistinguishable from a live one, which is why
        # WorkerPool always passes --parent-pid, the reliable
        # mechanism.
        if parent_pid is None:
            if cur == 1:
                os._exit(0)
            parent_pid = cur
        if cur != parent_pid:
            os._exit(0)
        # 0.5 s bounds how long a dead master's orphan can linger in
        # the SO_REUSEPORT group answering 503s after a SIGKILL.
        time.sleep(0.5)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--bind", required=True)
    ap.add_argument("--socket", required=True)
    ap.add_argument("--tls-cert")
    ap.add_argument("--tls-key")
    ap.add_argument("--data-dir")
    ap.add_argument("--parent-pid", type=int, default=None)
    ap.add_argument("--exec-reads", action="store_true")
    opts = ap.parse_args(argv)
    threading.Thread(target=_parent_watchdog, args=(opts.parent_pid,),
                     daemon=True).start()
    wexec = None
    if opts.exec_reads and opts.data_dir:
        from pilosa_tpu.server.worker_exec import WorkerExecutor

        wexec = WorkerExecutor(opts.data_dir)
    cache = None
    if opts.data_dir and os.environ.get(
            "PILOSA_TPU_WORKER_CACHE", "1") not in ("0", "false", "no"):
        epoch_path = os.path.join(opts.data_dir, ".mutation_epoch")
        if os.path.exists(epoch_path):
            from pilosa_tpu.storage.fragment import open_published_epochs

            cache = ResponseCache(open_published_epochs(epoch_path))
    serve(opts.bind, opts.socket, tls_cert=opts.tls_cert,
          tls_key=opts.tls_key, wexec=wexec, cache=cache)


if __name__ == "__main__":
    main()
