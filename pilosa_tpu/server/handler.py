"""HTTP API handler (ref: handler.go:98-151 route table, ~40 routes).

stdlib ``ThreadingHTTPServer`` + a regex route table standing in for
gorilla/mux. JSON is the primary representation; the reference's
protobuf content negotiation (handler.go:1067-1162) is mirrored for the
query/import endpoints via ``pilosa_tpu.server.wireproto`` when the
client sends ``application/x-protobuf``.

Every request is wrapped in panic-recovery (ref: handler.go:157-194):
errors become JSON ``{"error": ...}`` bodies with appropriate status.
"""
import base64
import io
import json
import re
import threading
import time
import traceback
from datetime import datetime
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np

from pilosa_tpu import SLICE_WIDTH, __version__
from pilosa_tpu import autopilot as autopilot_mod
from pilosa_tpu import errors as perr
from pilosa_tpu import faults as faults_mod
from pilosa_tpu import lockcheck
from pilosa_tpu import qos as qos_mod
from pilosa_tpu import querystats
from pilosa_tpu import stats as stats_mod
from pilosa_tpu import tracing
from pilosa_tpu.config import DEFAULT_MAX_BODY_SIZE
from pilosa_tpu.observe import costmodel as costmodel_mod
from pilosa_tpu.observe import devprof as devprof_mod
from pilosa_tpu.observe import events as events_mod
from pilosa_tpu.observe import explain as explain_mod
from pilosa_tpu.observe import heatmap as heatmap_mod
from pilosa_tpu.observe import kerneltime as kerneltime_mod
from pilosa_tpu.observe import profiler as profiler_mod
from pilosa_tpu.observe import replica as replica_mod
from pilosa_tpu.observe import slo as slo_mod
from pilosa_tpu.bitmap import Bitmap
from pilosa_tpu.cluster import hedge as hedge_mod
from pilosa_tpu.executor import ExecOptions, SumCount
from pilosa_tpu.pql.parser import ParseError
from pilosa_tpu.storage.frame import Field
from pilosa_tpu.storage.index import FrameOptions


def result_to_json(result):
    """QueryResult encoding (ref: QueryResult tagged union,
    internal/public.proto:60-70 + handler.go JSON path)."""
    if isinstance(result, Bitmap):
        return {"attrs": result.attrs, "bits": result.columns().tolist()}
    if isinstance(result, SumCount):
        return {"sum": result.sum, "count": result.count}
    if isinstance(result, list):  # pairs
        return [{"id": rid, "count": cnt} for rid, cnt in result]
    return result  # bool / int / None


def _decode_checksum(s):
    """Anti-entropy checksums are 8 bytes (xxhash64): Go-style base64
    is 12 chars with padding; round-1 in-house peers sent 16 hex chars.
    The shapes are disjoint, so both generations parse correctly."""
    if len(s) == 16:
        try:
            return bytes.fromhex(s)
        except ValueError:
            pass
    return base64.b64decode(s)


def _retry_after(seconds):
    """RFC 7231 delay-seconds is an INTEGER (1*DIGIT) — fractional
    values are unparseable to conforming clients (urllib3 Retry, Go
    net/http), which would silently drop the backoff hint."""
    import math

    return str(max(1, math.ceil(seconds)))


class HTTPError(Exception):
    """``headers`` (optional dict) ride the error response — how a
    shed carries its ``Retry-After`` hint."""

    def __init__(self, status, message, headers=None):
        self.status = status
        self.message = message
        self.headers = headers
        super().__init__(message)


class Handler:
    """Routing + endpoint logic, transport-independent."""

    def __init__(self, holder, executor, cluster=None, broadcaster=None,
                 local_host=None, version=__version__, tracer=None,
                 qos=None, histograms=None, epochs=None,
                 rebalancer=None, ingest=None, slo=None,
                 events=None, vitals=None, autopilot=None, hedger=None,
                 device_trace_dir=""):
        self.holder = holder
        self.executor = executor
        self.cluster = cluster
        self.broadcaster = broadcaster
        self.local_host = local_host
        self.version = version
        self.tracer = tracer or tracing.NOP
        # Distributed mutation-epoch registry (cluster/epochs.py) on
        # multi-node servers; None on single-node keeps every hook to
        # one attribute read and the wire format header-free.
        self.epochs = epochs
        # Elastic-topology rebalancer (cluster/rebalancer.py) on
        # multi-node servers: owns POST /cluster/resize,
        # GET /debug/rebalance, and the placement-state message.
        self.rebalancer = rebalancer
        # Streaming bulk-ingest pipeline (ingest/pipeline.py): owns
        # POST /index/<i>/ingest. None = route answers 501 ([ingest]
        # enabled = false, or a bare Handler).
        self.ingest = ingest
        # QoS tier (qos.py): admission gate + quotas + deadline
        # stamping on the heavy serving routes. The nop default keeps
        # the hot path to one `.enabled` attribute read.
        self.qos = qos or qos_mod.NOP
        # Runtime-telemetry histograms ([metrics] config) rendered on
        # /metrics; /cluster/metrics fan-out is gated by the server's
        # [metrics] cluster-aggregation flag.
        self.histograms = histograms or stats_mod.NOP_HISTOGRAMS
        # SLO tracker ([slo] config, observe/slo.py): fed one record
        # per query/ingest request from dispatch(); the nop default
        # keeps the request path to one attribute read.
        self.slo = slo or slo_mod.NOP
        # Control-plane flight recorder + replica vitals (observe/
        # events.py, observe/replica.py): /debug/events + /debug/
        # replicas surfaces and the pilosa_events_total /
        # pilosa_replica_* metric families. Nop defaults keep a bare
        # Handler (tests) to one `.enabled` attribute read.
        self.events = events or events_mod.NOP
        self.vitals = vitals or replica_mod.NOP
        # Heat-driven autopilot ([autopilot] config, autopilot/
        # controller.py): owns POST /cluster/autopilot/plan (dry-run
        # preview) and GET /debug/autopilot. The nop default keeps a
        # bare Handler to one `.enabled` attribute read.
        self.autopilot = autopilot or autopilot_mod.NOP
        # Tail-tolerant reads (cluster/hedge.py): owns GET
        # /debug/hedge and the pilosa_hedge_* metric family. The nop
        # default keeps a bare Handler to one `.enabled` read.
        self.hedger = hedger or hedge_mod.NOP
        # Default output directory for POST /debug/profile/device
        # trace captures ([profile] device-trace-dir); requests may
        # name their own via ?dir=.
        self.device_trace_dir = device_trace_dir
        self.cluster_metrics_enabled = True
        self._scrape_mu = lockcheck.register("handler.Handler._scrape_mu",
                                             threading.Lock())
        self._scrape_errors = {}  # peer host -> failed scrape count
        self._resp_cache = None  # enable_response_cache (master only)
        # Graceful drain (Server.close / SIGTERM): while _drain is
        # set, new work on the heavy serving routes sheds with 503 +
        # Retry-After and /status answers LEAVING; _inflight counts
        # requests currently inside dispatch so the drain loop knows
        # when the node is quiet. The counter is two uncontended lock
        # acquisitions per request — the price of close() being able
        # to wait for in-flight queries at all.
        self._inflight = 0
        self._inflight_mu = lockcheck.register(
            "handler.Handler._inflight_mu", threading.Lock())
        self._drain = None
        self._drain_shed_total = 0
        self.routes = self._build_routes()

    def enable_response_cache(self):
        """Master-side response replay (the worker ResponseCache, one
        tier deeper): identical read queries replay their exact
        response bytes while the index's mutation-epoch token stands —
        skipping parse, dispatch, execution, and JSON encoding
        entirely. Single-node validates against the process-local
        per-index epoch (attr writes bump it too, attrs.py);
        multi-node validates against the cluster epoch VECTOR
        (cluster/epochs.py — unknown/stale peers mean cold, never
        stale). OFF whenever the executor's result memos are off
        (PILOSA_TPU_RESULT_MEMO=0, cold benchmarks, pinned paths) so
        measurements never time dict lookups.
        PILOSA_TPU_RESPONSE_CACHE=0 disables independently."""
        import os as _os

        from pilosa_tpu.server.respcache import ResponseCache
        from pilosa_tpu.storage.fragment import mutation_epoch

        if _os.environ.get("PILOSA_TPU_RESPONSE_CACHE", "1") in (
                "0", "false", "no"):
            return
        if self.epochs is not None:
            self._resp_cache = ResponseCache(self._cluster_epoch_token)
        else:
            # Scoped to the query's index (path is /index/<i>/query,
            # guaranteed by cacheable()) so a write-heavy index no
            # longer flushes other indexes' replays.
            self._resp_cache = ResponseCache(
                lambda path: mutation_epoch(path.split("/", 3)[2]))

    def _cluster_epoch_token(self, path):
        """Multi-node replay validity: the epoch vector over every
        cluster node (a whole-index query reads slices from all of
        them under jump-hash placement — the conservative owner set),
        refreshed by probes when stale, PLUS the local slice-universe
        bounds. The universe term closes a restart hole: a rebooted
        node relearns peer max-slices via heartbeat WITHOUT any epoch
        movement, and an entry cached over the smaller universe would
        otherwise replay a stale partial count until the next write.
        None -> cold."""
        index = path.split("/", 3)[2]
        tok = self.epochs.ensure_fresh(
            index, [n.host for n in self.cluster.nodes])
        if tok is None:
            return None
        idx = self.holder.index(index)
        if idx is None:
            return tok
        # Via the plan cache's epoch-memoized universe (validation is
        # an O(1) token compare), NOT a per-request max_slice() walk
        # over every view of every frame — the replay tier must never
        # re-pay the walk PR 6 removed.
        std, inv = self.executor.plans.slice_universe(index, idx)
        return (tok, len(std), len(inv))

    def _build_routes(self):
        return [
            ("POST", r"^/index/(?P<index>[^/]+)/query$", self.post_query),
            ("GET", r"^/index/(?P<index>[^/]+)/query$",
             self.method_not_allowed),
            ("GET", r"^/index$", self.get_schema),
            ("GET", r"^/schema$", self.get_schema),
            ("POST", r"^/schema$", self.post_schema),
            ("GET", r"^/status$", self.get_status),
            ("GET", r"^/version$", self.get_version),
            ("GET", r"^/hosts$", self.get_hosts),
            ("GET", r"^/id$", self.get_id),
            ("GET", r"^/slices/max$", self.get_slices_max),
            ("GET", r"^/index/(?P<index>[^/]+)$", self.get_index),
            ("POST", r"^/index/(?P<index>[^/]+)$", self.post_index),
            ("DELETE", r"^/index/(?P<index>[^/]+)$", self.delete_index),
            ("PATCH", r"^/index/(?P<index>[^/]+)/time-quantum$",
             self.patch_index_time_quantum),
            ("POST", r"^/index/(?P<index>[^/]+)/attr/diff$",
             self.post_index_attr_diff),
            ("POST", r"^/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)$",
             self.post_frame),
            ("DELETE", r"^/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)$",
             self.delete_frame),
            ("PATCH",
             r"^/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)/time-quantum$",
             self.patch_frame_time_quantum),
            ("POST",
             r"^/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)/attr/diff$",
             self.post_frame_attr_diff),
            ("POST",
             r"^/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)"
             r"/field/(?P<field>[^/]+)$", self.post_field),
            ("DELETE",
             r"^/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)"
             r"/field/(?P<field>[^/]+)$", self.delete_field),
            ("GET", r"^/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)/fields$",
             self.get_fields),
            ("POST",
             r"^/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)"
             r"/views/(?P<view>[^/]+)$", self.post_view),
            ("GET", r"^/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)/views$",
             self.get_views),
            ("DELETE",
             r"^/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)"
             r"/view/(?P<view>[^/]+)$", self.delete_view),
            ("POST",
             r"^/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)/restore$",
             self.post_frame_restore),
            ("POST", r"^/index/(?P<index>[^/]+)/input-definition/(?P<def>[^/]+)$",
             self.post_input_definition),
            ("GET", r"^/index/(?P<index>[^/]+)/input-definition/(?P<def>[^/]+)$",
             self.get_input_definition),
            ("DELETE",
             r"^/index/(?P<index>[^/]+)/input-definition/(?P<def>[^/]+)$",
             self.delete_input_definition),
            ("POST", r"^/index/(?P<index>[^/]+)/input/(?P<def>[^/]+)$",
             self.post_input),
            ("POST", r"^/index/(?P<index>[^/]+)/ingest$",
             self.post_ingest),
            ("POST", r"^/import$", self.post_import),
            ("POST", r"^/import-value$", self.post_import_value),
            ("GET", r"^/export$", self.get_export),
            ("GET", r"^/fragment/data$", self.get_fragment_data),
            ("POST", r"^/fragment/data$", self.post_fragment_data),
            ("GET", r"^/fragment/blocks$", self.get_fragment_blocks),
            ("GET", r"^/fragment/digest$", self.get_fragment_digest),
            ("GET", r"^/fragment/block/data$", self.get_fragment_block_data),
            ("GET", r"^/fragment/nodes$", self.get_fragment_nodes),
            ("POST", r"^/cluster/message$", self.post_cluster_message),
            ("POST", r"^/cluster/resize$", self.post_cluster_resize),
            ("POST", r"^/cluster/autopilot/plan$",
             self.post_cluster_autopilot_plan),
            ("GET", r"^/debug/rebalance$", self.get_debug_rebalance),
            ("GET", r"^/internal/probe$", self.get_internal_probe),
            ("GET", r"^/internal/epochs$", self.get_internal_epochs),
            ("POST", r"^/internal/heartbeat$",
             self.post_internal_heartbeat),
            ("POST", r"^/recalculate-caches$", self.post_recalculate_caches),
            ("GET", r"^/debug/vars$", self.get_debug_vars),
            ("GET", r"^/debug/traces$", self.get_debug_traces),
            ("GET", r"^/debug/qos$", self.get_debug_qos),
            ("GET", r"^/debug/lockcheck$", self.get_debug_lockcheck),
            ("GET", r"^/debug/drain$", self.get_debug_drain),
            ("GET", r"^/debug/faults$", self.get_debug_faults),
            ("POST", r"^/debug/faults$", self.post_debug_faults),
            ("GET", r"^/debug/memory$", self.get_debug_memory),
            ("GET", r"^/debug/epochs$", self.get_debug_epochs),
            ("GET", r"^/debug/plans$", self.get_debug_plans),
            ("GET", r"^/debug/mesh$", self.get_debug_mesh),
            ("GET", r"^/debug/kernels$", self.get_debug_kernels),
            ("GET", r"^/debug/profile$", self.get_debug_profile),
            ("POST", r"^/debug/profile/device$",
             self.post_profile_device),
            ("GET", r"^/debug/heatmap$", self.get_debug_heatmap),
            ("GET", r"^/debug/slo$", self.get_debug_slo),
            ("GET", r"^/debug/costmodel$", self.get_debug_costmodel),
            ("GET", r"^/debug/events$", self.get_debug_events),
            ("GET", r"^/debug/replicas$", self.get_debug_replicas),
            ("GET", r"^/debug/autopilot$", self.get_debug_autopilot),
            ("GET", r"^/debug/hedge$", self.get_debug_hedge),
            ("GET", r"^/debug$", self.get_debug_index),
            ("GET", r"^/metrics$", self.get_metrics),
            ("GET", r"^/cluster/metrics$", self.get_cluster_metrics),
            ("GET", r"^/debug/worker$", self.get_debug_worker),
            ("POST", r"^/debug/profile/start$", self.post_profile_start),
            ("POST", r"^/debug/profile/stop$", self.post_profile_stop),
            ("GET", r"^/$", self.get_webui),
            ("GET", r"^/assets/(?P<file>[^/]+)$", self.get_asset),
        ]

    def dispatch(self, method, path, query_params, body, headers):
        """-> (status, content_type, payload bytes)."""
        with self._inflight_mu:
            self._inflight += 1
        slo = self.slo
        track = (slo.enabled and method == "POST"
                 and (path.endswith("/query")
                      or path.endswith("/ingest")))
        t0 = time.monotonic() if track else 0.0
        try:
            out = self._dispatch(method, path, query_params, body,
                                 headers)
        finally:
            with self._inflight_mu:
                self._inflight -= 1
        if track:
            # One SLO record per serving request, by admitted priority
            # class. 5xx (shed, fail-stop, expiry, crash) burns the
            # availability budget; the latency objective judges the
            # wall time of everything else — cache replays included,
            # they are answers the client waited for.
            prio = headers.get(qos_mod.PRIORITY_HEADER)
            if not prio and path.endswith("/ingest"):
                prio_cls = qos_mod.PRIO_INGEST
            else:
                prio_cls = qos_mod.parse_priority(prio)
            slo.record(qos_mod.priority_name(prio_cls),
                       time.monotonic() - t0, error=out[0] >= 500)
        ep = self.epochs
        if ep is not None:
            # Epoch piggyback (the ONE header pair per RPC): computed
            # AFTER the handler ran, so a write's own response carries
            # its bumped counter — the coordinator that relayed the
            # write observes it in-line, making read-your-writes
            # through any relaying coordinator strict. Memoized on the
            # process epoch total: steady state costs one int compare
            # + one dict copy.
            extra = dict(out[3]) if len(out) > 3 and out[3] else {}
            extra[ep.HEADER] = ep.header_value()
            out = out[:3] + (extra,)
        return out

    def _dispatch(self, method, path, query_params, body, headers):
        cache = self._resp_cache
        key = epoch = None
        if (cache is not None
                and not self.tracer.enabled
                and "profile" not in (query_params or ())
                and "explain" not in (query_params or ())
                and headers.get(querystats.COLLECT_HEADER) is None
                and not self.executor._result_memo_off
                and getattr(self.executor, "_force_path", None) is None
                and cache.cacheable(method, path, body)):
            key = cache.make_key(path, query_params, body, headers)
            hit = cache.get(key)
            if hit is not None:
                if self._drain is not None:
                    # A draining node stops answering queries even
                    # from cache — the client must move to a replica
                    # before the listener goes away.
                    return self._drain_response()
                shed = self._replay_shed(query_params, headers)
                if shed is not None:
                    return shed
                return hit + ({"X-Pilosa-Response-Cache": "hit"},)
            epoch = cache.pre_epoch(path)
        out = self._dispatch_route(method, path, query_params, body,
                                   headers)
        if key is not None:
            cache.put(key, epoch, out)
        return out

    def _dispatch_route(self, method, path, query_params, body, headers):
        for m, pattern, fn in self.routes:
            if m != method:
                continue
            match = re.match(pattern, path)
            if match:
                try:
                    return fn(match.groupdict(), query_params, body, headers)
                except HTTPError as e:
                    resp = (e.status, "application/json",
                            json.dumps({"error": e.message}).encode())
                    return resp + (e.headers,) if e.headers else resp
                except perr.ErrFragmentFailStop as e:
                    # A fail-stopped fragment is a node-health
                    # condition, not a caller mistake: 503 tells the
                    # client (and a coordinating peer) to retry
                    # against a replica while this fragment waits for
                    # operator attention / reopen.
                    return (503, "application/json",
                            json.dumps({"error": str(e)}).encode(),
                            {"Retry-After": "1"})
                except (perr.PilosaError, ParseError, ValueError) as e:
                    # Parse/validation errors only: a KeyError here
                    # used to map to 400 too, misreporting an internal
                    # missing-dict-key bug as the caller's fault —
                    # genuine handler bugs now surface as 500 with the
                    # traceback; request bodies are validated
                    # explicitly (_require) where missing keys ARE the
                    # caller's fault.
                    return (400, "application/json",
                            json.dumps({"error": str(e)}).encode())
                except Exception as e:  # panic recovery (handler.go:157-194)
                    traceback.print_exc()
                    return (500, "application/json",
                            json.dumps({"error": str(e)}).encode())
        return 404, "application/json", json.dumps({"error": "not found"}).encode()

    # ------------------------------------------------------------- drain

    def begin_drain(self, timeout):
        """Flip the node into the LEAVING state: every new request on
        a gated serving route (query/import/input — and cached
        replays) sheds with 503 + ``Retry-After`` so clients and
        coordinating peers move to replicas, while the in-flight ones
        run to completion. Idempotent."""
        with self._inflight_mu:
            if self._drain is None:
                # Wall "started" is the user-facing timestamp; the
                # monotonic twin is what elapsed arithmetic uses (an
                # admin clock step must not distort drain progress).
                self._drain = {"started": time.time(),
                               "started_mono": time.monotonic(),
                               "timeout": float(timeout)}

    def drain(self, timeout):
        """begin_drain + wait (up to ``timeout`` seconds) for every
        in-flight request to finish. Op-log writes flush synchronously
        inside their requests, so a quiet dispatch means durable
        state is settled too. Returns (seconds waited, drained?,
        requests still in flight at the deadline)."""
        self.begin_drain(timeout)
        t0 = time.monotonic()
        deadline = t0 + timeout
        while True:
            with self._inflight_mu:
                n = self._inflight
            if n <= 0 or time.monotonic() >= deadline:
                break
            time.sleep(0.01)
        waited = time.monotonic() - t0
        with self._inflight_mu:
            self._drain["waited"] = waited
            self._drain["remaining"] = n
        return waited, n <= 0, n

    def _drain_response(self):
        """The 503 a draining node answers new serving work with."""
        with self._inflight_mu:
            self._drain_shed_total += 1
            retry = self._drain["timeout"] if self._drain else 1.0
        stats = getattr(self.executor.holder, "stats", None)
        if stats is not None:
            stats.count("drain_shed_total", 1)
        return (503, "application/json",
                json.dumps({"error": "node is draining"}).encode(),
                {"Retry-After": _retry_after(retry)})

    def get_debug_drain(self, params, qp, body, headers):
        """Drain introspection (mirrors /debug/qos): whether the node
        is leaving, how long it has been draining, what is still in
        flight (excluding this request), and how much new work was
        shed."""
        with self._inflight_mu:
            d = dict(self._drain) if self._drain else None
            inflight = max(0, self._inflight - 1)
            shed = self._drain_shed_total
        out = {"draining": d is not None, "inFlight": inflight,
               "shedTotal": shed}
        if d:
            out["startedAt"] = d["started"]
            out["drainTimeout"] = d["timeout"]
            out["elapsed"] = round(time.monotonic() - d["started_mono"], 3)
            if "waited" in d:
                out["waited"] = round(d["waited"], 3)
                out["remainingAtDeadline"] = d["remaining"]
        return 200, "application/json", json.dumps(out).encode()

    # -------------------------------------------------------- failpoints

    def get_debug_faults(self, params, qp, body, headers):
        """Failpoint snapshot — answers even when the subsystem is
        disabled ({"enabled": false}), like /debug/qos."""
        return (200, "application/json",
                json.dumps(faults_mod.ACTIVE.snapshot()).encode())

    def post_debug_faults(self, params, qp, body, headers):
        """Runtime failpoint control, test-only: 403 unless fault
        injection was enabled out-of-band (PILOSA_FAULTS env or the
        [faults] config table) — a production node must not grow a
        remote crash-me endpoint by default. Body:
        ``{"spec": "<point>=<action>...", "clear": true|"<point>"}``;
        clear runs first, so one call can swap armings."""
        if not faults_mod.ACTIVE.enabled:
            raise HTTPError(
                403, "fault injection disabled "
                     "(set PILOSA_FAULTS or [faults] enabled)")
        req = json.loads(body or b"{}")
        clear = req.get("clear")
        if clear:
            faults_mod.ACTIVE.clear(
                None if clear is True else str(clear))
        spec = req.get("spec")
        if spec:
            try:
                faults_mod.ACTIVE.configure(spec)
            except ValueError as e:
                raise HTTPError(400, str(e))
        return (200, "application/json",
                json.dumps(faults_mod.ACTIVE.snapshot()).encode())

    # --------------------------------------------------------------- qos

    def _replay_shed(self, qp, headers):
        """QoS checks a response-cache replay still owes: a replay
        skips _dispatch_route (and so _serve_qos), but a client's
        request-rate quota counts every request it issues — cached or
        not — and an already-expired deadline must 504 regardless of
        cache state (docs promise expiry semantics independent of it).
        The gate itself is deliberately skipped: a replay consumes no
        executor capacity. Returns an error response tuple to send,
        None to proceed with the replay."""
        q = self.qos
        if not q.enabled:
            return None
        try:
            deadline = q.request_deadline(qp, headers)
        except qos_mod.ShedError as e:
            return (e.status, "application/json",
                    json.dumps({"error": e.reason}).encode())
        if deadline is not None and time.monotonic() > deadline:
            q.note_deadline_expired()
            return (504, "application/json",
                    json.dumps({"error": "deadline exceeded"}).encode())
        if qos_mod.parse_priority(
                headers.get(qos_mod.PRIORITY_HEADER)) \
                == qos_mod.PRIO_INTERNAL:
            return None
        try:
            q.quotas.allow(headers.get(qos_mod.CLIENT_HEADER))
        except qos_mod.ShedError as e:
            q.note_shed(e.reason)
            return (e.status, "application/json",
                    json.dumps({"error": e.reason}).encode(),
                    {"Retry-After": _retry_after(e.retry_after)})
        return None

    def _gated(self, inner, params, qp, body, headers,
               default_priority=None):
        """Route a heavy serving endpoint through the QoS tier. The
        disabled path is one attribute read and a plain call — no
        closure is ever built (the nop-tracer discipline). A draining
        node sheds the request before either path: the same 503 +
        Retry-After contract as QoS overload, minus the gate.
        ``default_priority`` overrides the headerless default (the
        ingest route parks at qos.PRIO_INGEST, not interactive)."""
        if self._drain is not None:
            return self._drain_response()
        if not self.qos.enabled:
            return inner(params, qp, body, headers)
        return self._serve_qos(
            qp, headers, lambda: inner(params, qp, body, headers),
            default_priority=default_priority)

    def _serve_qos(self, qp, headers, fn, default_priority=None):
        """Run ``fn`` under the QoS tier: resolve the request deadline
        (X-Pilosa-Deadline header wins, else ?timeout=, else the
        configured default), quota-check the client, admit through the
        gate (priority-aware; internal fan-out never queues), install
        the deadline scope the executor checks mid-query, and map
        shed/expiry to 429/503 (+Retry-After) / 504. One attribute
        read when QoS is disabled — no locks, no allocations."""
        q = self.qos
        if not q.enabled:
            return fn()
        try:
            deadline = q.request_deadline(qp, headers)
        except qos_mod.ShedError as e:  # malformed deadline/timeout
            raise HTTPError(e.status, e.reason)
        if deadline is not None and time.monotonic() > deadline:
            q.note_deadline_expired()
            raise HTTPError(504, "deadline exceeded")
        prio_header = headers.get(qos_mod.PRIORITY_HEADER)
        if not prio_header and default_priority is not None:
            prio = default_priority
        else:
            prio = qos_mod.parse_priority(prio_header)
        client = headers.get(qos_mod.CLIENT_HEADER)
        try:
            with tracing.span("qos.admit",
                              priority=qos_mod.priority_name(prio)) as sp:
                waited = q.admit(prio, client, deadline)
                if waited:
                    sp.tag(queued_ms=round(waited * 1000, 3))
        except qos_mod.ShedError as e:
            raise HTTPError(
                e.status, e.reason,
                headers=({"Retry-After": _retry_after(e.retry_after)}
                         if e.retry_after else None))
        except qos_mod.DeadlineExceeded:
            raise HTTPError(504, "deadline exceeded")
        try:
            # The admitted priority rides a thread-local scope next to
            # the deadline: the executor's coalescer reads it so
            # interactive coalescees admit ahead of batch/ingest ones.
            with qos_mod.deadline_scope(deadline), \
                    qos_mod.priority_scope(prio):
                try:
                    return fn()
                except qos_mod.DeadlineExceeded:
                    q.note_deadline_expired()
                    raise HTTPError(504, "deadline exceeded")
        finally:
            q.release()

    def get_debug_qos(self, params, qp, body, headers):
        """QoS introspection, mirroring /debug/traces: gate occupancy
        and queue depth, shed counters by reason, per-client quota
        table size, and every peer breaker's state."""
        return (200, "application/json",
                json.dumps(self.qos.snapshot()).encode())

    def get_debug_lockcheck(self, params, qp, body, headers):
        """Lock-instrumentation report (PILOSA_LOCKCHECK): observed
        order-graph size, any cycles / locks held across io points,
        and per-lock held-duration histograms. {"enabled": false}
        when the instrumentation is off — the lockcheck-enabled
        acceptance tests assert ``cycles == []`` here."""
        return (200, "application/json",
                json.dumps(lockcheck.report()).encode())

    # ------------------------------------------------------------- query

    def post_query(self, params, qp, body, headers):
        """(ref: handlePostQuery handler.go:243-309). With tracing
        enabled (or ``?profile=true``) the whole serve runs under a
        root span: an incoming X-Pilosa-Trace-Id/X-Pilosa-Span-Id pair
        (coordinator fan-out) is adopted so this node's spans join the
        coordinator's trace; the trace id rides back on the response
        headers, and ``?profile=true`` inlines the span tree next to
        the results (the reference's Profile option that never
        shipped). ``?explain=true`` additionally inlines the query
        inspector's plan tree + observed tier attribution
        (observe/explain.py); ``?explain=only`` plans WITHOUT
        executing. Profile and explain compose — one query may return
        both blocks."""
        tracer = self.tracer
        profile = qp.get("profile", ["false"])[0] == "true"
        explain_mode = qp.get("explain", ["false"])[0]
        if explain_mode not in ("false", "true", "only"):
            raise HTTPError(400, "explain must be true, only or false")
        explain_on = explain_mode != "false"
        # A profiling coordinator asks fan-out targets to count their
        # side and return it in the stats footer header (querystats).
        collect = headers.get(querystats.COLLECT_HEADER) is not None
        if not (tracer.enabled or profile or collect or explain_on):
            return self._post_query(params, qp, body, headers)
        if not tracer.enabled:
            # Per-request profiling on a tracing-disabled server: an
            # ephemeral recorder, no ring/stats side effects.
            tracer = tracing.Tracer(ring_size=1, stats=None)
        trace_id = headers.get(tracing.TRACE_HEADER)
        parent_id = headers.get(tracing.SPAN_HEADER)
        root = tracer.start(
            "query.remote" if trace_id else "query",
            trace_id=trace_id, parent_id=parent_id,
            index=params["index"], host=self.local_host or "")
        qs = querystats.QueryStats()
        # Journal watermark BEFORE execution: any control-plane event
        # that fires during the query's lifetime (breaker flip, shed
        # onset, placement phase change...) gets its id stamped onto
        # the root span, so a slow-query ring entry names the cluster
        # transitions that overlapped it.
        ev_wm = self.events.last_id() if self.events.enabled else None
        with root, querystats.scope(qs):
            if explain_mode == "only":
                resp = self._explain_only(params, qp, body, headers)
            else:
                resp = self._post_query(params, qp, body, headers)
        # Resource counts ride with the trace into the recent/slow
        # rings (Trace.to_dict inlines them) — tier attribution tags
        # included, so the slow-query flight recorder answers "what
        # did it COST and which tier served it" next to "where did
        # the time go".
        root.trace.resources = qs.to_dict()
        if ev_wm is not None:
            ids = self.events.ids_since(ev_wm)
            if ids:
                root.tag(controlEvents=ids)
        status, ctype, payload = resp[:3]
        doc = None
        if (ctype == "application/json" and payload.startswith(b"{")
                and status == 200):
            if profile:
                doc = json.loads(payload)
                doc["profile"] = root.trace.to_dict()
            if explain_on and explain_mode == "true":
                # The explain-only path already inlined its block;
                # here the query EXECUTED — the static plan renders
                # next to the observed tier tags it predicted.
                q_string, q_slices = self._query_body(qp, body,
                                                      headers)
                if q_string:
                    if doc is None:
                        doc = json.loads(payload)
                    try:
                        doc["explain"] = explain_mod.explain_query(
                            self.executor, params["index"], q_string,
                            slices=q_slices, qs=qs, executed=True)
                    except Exception as e:  # noqa: BLE001; pilint: disable=swallow
                        # The query EXECUTED — a render failure (e.g.
                        # a DDL race mid-walk) must degrade to an
                        # inline error, never 500 computed results.
                        doc["explain"] = {"error": str(e)}
            if doc is not None:
                payload = json.dumps(doc).encode()
        extra = {tracing.TRACE_HEADER: root.trace.trace_id}
        if collect:
            # The footer a coordinating peer merges into its own
            # accumulator — this node's partial only (tier tags
            # included, so a coordinator's explain reports the union
            # of every node's serving decisions).
            extra[querystats.STATS_HEADER] = querystats.encode(
                qs.to_dict())
        return (status, ctype, payload, extra)

    @staticmethod
    def _query_body(qp, body, headers):
        """(PQL text, explicit slice restriction or None) from a
        query request — ONE decode for the explain surface (protobuf
        bodies carry both fields in the same QueryRequest; text
        bodies take slices from ``?slices=``). (None, None) when
        undecodable — explain is best-effort on exotic encodings,
        never a new failure mode for the query itself."""
        if headers.get("Content-Type") == "application/x-protobuf":
            from pilosa_tpu.server import wireproto

            try:
                req = wireproto.decode_query_request(body)
                return req["query"], req.get("slices") or None
            except Exception:  # noqa: BLE001 — best-effort decode
                return None, None
        try:
            q_string = body.decode()
        except UnicodeDecodeError:
            return None, None
        slices = None
        sl = qp.get("slices")
        if sl:
            try:
                slices = [int(s) for s in sl[0].split(",")
                          if s] or None
            except ValueError:
                slices = None
        return q_string, slices

    def _explain_only(self, params, qp, body, headers):
        """``?explain=only``: plan the query without executing it —
        no result memo, no plan-cache write, no device program (the
        read-only contract observe/explain.py documents and the tests
        assert). Runs through the same QoS gate as a real query: an
        overloaded node sheds inspection work too."""
        return self._gated(self._explain_only_inner, params, qp, body,
                           headers)

    def _explain_only_inner(self, params, qp, body, headers):
        q_string, q_slices = self._query_body(qp, body, headers)
        if not q_string:
            raise HTTPError(400, "query required")
        out = explain_mod.explain_query(
            self.executor, params["index"], q_string,
            slices=q_slices, executed=False)
        return (200, "application/json",
                json.dumps({"results": None, "explain": out}).encode())

    def _post_query(self, params, qp, body, headers):
        return self._gated(self._post_query_inner, params, qp, body,
                           headers)

    def _post_query_inner(self, params, qp, body, headers):
        index = params["index"]
        ctype = headers.get("Content-Type", "")
        if ctype == "application/x-protobuf":
            from pilosa_tpu.server import wireproto
            try:
                req = wireproto.decode_query_request(body)
            except HTTPError:
                raise
            except Exception:  # noqa: BLE001 — any undecodable body:
                # wrong wire types surface as AttributeError/TypeError,
                # truncation as IndexError, bad UTF-8 as ValueError
                # (ref: handler.go:252 "unmarshal body error" → 400).
                raise HTTPError(400, "unmarshal body error")
            q_string = req["query"]
            slices = req.get("slices") or None
            opt = ExecOptions(remote=req.get("remote", False),
                              exclude_attrs=req.get("exclude_attrs", False),
                              exclude_bits=req.get("exclude_bits", False))
        else:
            q_string = body.decode()
            slices = None
            sl = qp.get("slices")
            if sl:
                slices = [int(s) for s in sl[0].split(",") if s]
            opt = ExecOptions(
                remote=qp.get("remote", ["false"])[0] == "true",
                exclude_attrs=qp.get("excludeAttrs", ["false"])[0] == "true",
                exclude_bits=qp.get("excludeBits", ["false"])[0] == "true")
        if not q_string:
            raise HTTPError(400, "query required")

        try:
            # The raw string goes to the executor: it parses (same
            # ParseError surfaces) and can recognize SetBit bursts
            # without building an AST.
            results = self.executor.execute(index, q_string, slices=slices,
                                            opt=opt)
        except perr.ErrFragmentFailStop:
            # Node-health condition, not a query error: let the route
            # dispatcher map it to 503 + Retry-After.
            raise
        except (perr.PilosaError, ValueError) as e:
            if headers.get("Accept") == "application/x-protobuf" or \
                    ctype == "application/x-protobuf":
                from pilosa_tpu.server import wireproto
                return (400, "application/x-protobuf",
                        wireproto.encode_query_response([], error=str(e)))
            return (400, "application/json",
                    json.dumps({"error": str(e)}).encode())

        if (headers.get("Accept") == "application/x-protobuf"
                or ctype == "application/x-protobuf"):
            from pilosa_tpu.server import wireproto
            return (200, "application/x-protobuf",
                    wireproto.encode_query_response(results))
        return (200, "application/json", json.dumps(
            {"results": [result_to_json(r) for r in results]}).encode())

    # ------------------------------------------------------------ schema

    def get_schema(self, params, qp, body, headers):
        return (200, "application/json",
                json.dumps({"indexes": self.holder.schema()}).encode())

    def post_schema(self, params, qp, body, headers):
        """Merge a remote schema into this holder."""
        schema = json.loads(body or b"{}")
        self.holder.apply_schema(schema.get("indexes", []))
        return 200, "application/json", b"{}"

    def get_status(self, params, qp, body, headers):
        if "protobuf" in headers.get("Accept", ""):
            # internal.NodeStatus bytes (private.proto:127-132) — what
            # the reference exchanges in gossip state push/pull
            # (gossip.go LocalState/MergeRemoteState).
            from pilosa_tpu.server import wireproto

            scheme = "http"
            if self.cluster and self.local_host:
                me = self.cluster.node_by_host(self.local_host)
                if me is not None:
                    scheme = me.scheme
            schema = self.holder.schema(include_meta=True)
            max_slices = self.holder.max_slices()
            for idx in schema:
                idx["maxSlice"] = max_slices.get(idx["name"], 0)
            ns = wireproto.encode_node_status({
                "host": self.local_host or "",
                "state": self._node_state(),
                "scheme": scheme,
                "indexes": schema,
            })
            return 200, "application/x-protobuf", ns
        status = {
            "state": self._node_state(),
            "nodes": (self.cluster.status()["nodes"] if self.cluster else []),
            "indexes": self.holder.schema(),
        }
        if self.cluster:
            states = self.cluster.node_states()
            status["nodeStates"] = states
            cluster_status = self.cluster.status()
            if "placement" in cluster_status:
                # Elastic topology: committed generation + phase +
                # per-node JOINING/LEAVING roles while a resize runs.
                status["placement"] = cluster_status["placement"]
            # Reference wire shape: Go json-marshals the ClusterStatus
            # proto struct, so ecosystem clients parse CAPITALIZED
            # keys — docs/getting-started.md:37 shows
            # {"status":{"Nodes":[{"Host":":10101","State":"UP"}]}}.
            # Served alongside the richer lowercase fields.
            status["Nodes"] = [
                {"Host": n.host, "State": states.get(n.host, "UP")}
                for n in self.cluster.nodes]
        return (200, "application/json",
                json.dumps({"status": status}).encode())

    def _node_state(self):
        """How this node announces itself: LEAVING while draining (the
        graceful-shutdown broadcast — peers and load balancers polling
        /status stop routing new work here), NORMAL otherwise."""
        return "LEAVING" if self._drain is not None else "NORMAL"

    def get_version(self, params, qp, body, headers):
        return (200, "application/json",
                json.dumps({"version": self.version}).encode())

    def get_hosts(self, params, qp, body, headers):
        hosts = (self.cluster.status()["nodes"] if self.cluster
                 else [{"host": self.local_host or "localhost"}])
        return 200, "application/json", json.dumps(hosts).encode()

    def get_id(self, params, qp, body, headers):
        return 200, "text/plain", (self.holder.local_id or "").encode()

    def get_slices_max(self, params, qp, body, headers):
        if qp.get("inverse", ["false"])[0] == "true":
            m = self.holder.max_inverse_slices()
        else:
            m = self.holder.max_slices()
        return (200, "application/json",
                json.dumps({"maxSlices": m}).encode())

    # ----------------------------------------------------------- indexes

    def _index(self, name):
        idx = self.holder.index(name)
        if idx is None:
            raise HTTPError(404, str(perr.ErrIndexNotFound()))
        return idx

    def get_index(self, params, qp, body, headers):
        idx = self._index(params["index"])
        return (200, "application/json", json.dumps({
            "index": {"name": idx.name, "columnLabel": idx.column_label,
                      "timeQuantum": idx.time_quantum}}).encode())

    def post_index(self, params, qp, body, headers):
        opts = json.loads(body or b"{}").get("options", {})
        try:
            self.holder.create_index(
                params["index"],
                column_label=opts.get("columnLabel", ""),
                time_quantum=opts.get("timeQuantum", ""))
        except perr.ErrIndexExists as e:
            raise HTTPError(409, str(e))
        self._broadcast({"type": "create-index", "index": params["index"],
                         "options": opts})
        return 200, "application/json", b"{}"

    def delete_index(self, params, qp, body, headers):
        # holder.on_index_drop releases the index's plan-cache state
        # (entries, universe memos, stats) on every removal path.
        self.holder.delete_index(params["index"])
        self._broadcast({"type": "delete-index", "index": params["index"]})
        return 200, "application/json", b"{}"

    def patch_index_time_quantum(self, params, qp, body, headers):
        q = json.loads(body or b"{}").get("timeQuantum", "")
        self._index(params["index"]).set_time_quantum(q)
        return 200, "application/json", b"{}"

    def _attr_blocks(self, req):
        """Validated (id, checksum) pairs from an attr-diff body — a
        malformed entry is the caller's 400, not a KeyError-500."""
        out = []
        for b in req.get("blocks", []):
            self._require(b, "id", "checksum")
            out.append((b["id"], _decode_checksum(b["checksum"])))
        return out

    def post_index_attr_diff(self, params, qp, body, headers):
        """(ref: handler.go:545 handlePostIndexAttrDiff)."""
        idx = self._index(params["index"])
        req = json.loads(body or b"{}")
        blocks = self._attr_blocks(req)
        diff_ids = idx.column_attr_store.blocks_diff(blocks)
        attrs = {}
        for block_id in diff_ids:
            for id_, m in idx.column_attr_store.block_data(block_id).items():
                attrs[str(id_)] = m
        return (200, "application/json",
                json.dumps({"attrs": attrs}).encode())

    # ------------------------------------------------------------ frames

    def _frame(self, index, frame):
        fr = self._index(index).frame(frame)
        if fr is None:
            raise HTTPError(404, str(perr.ErrFrameNotFound()))
        return fr

    def post_frame(self, params, qp, body, headers):
        opts = json.loads(body or b"{}").get("options", {})
        try:
            self._index(params["index"]).create_frame(
                params["frame"], FrameOptions.from_dict(opts))
        except perr.ErrFrameExists as e:
            raise HTTPError(409, str(e))
        self._broadcast({"type": "create-frame", "index": params["index"],
                         "frame": params["frame"], "options": opts})
        return 200, "application/json", b"{}"

    def delete_frame(self, params, qp, body, headers):
        self._index(params["index"]).delete_frame(params["frame"])
        self._broadcast({"type": "delete-frame", "index": params["index"],
                         "frame": params["frame"]})
        return 200, "application/json", b"{}"

    def patch_frame_time_quantum(self, params, qp, body, headers):
        q = json.loads(body or b"{}").get("timeQuantum", "")
        self._frame(params["index"], params["frame"]).set_time_quantum(q)
        return 200, "application/json", b"{}"

    def post_frame_attr_diff(self, params, qp, body, headers):
        fr = self._frame(params["index"], params["frame"])
        req = json.loads(body or b"{}")
        blocks = self._attr_blocks(req)
        diff_ids = fr.row_attr_store.blocks_diff(blocks)
        attrs = {}
        for block_id in diff_ids:
            for id_, m in fr.row_attr_store.block_data(block_id).items():
                attrs[str(id_)] = m
        return (200, "application/json",
                json.dumps({"attrs": attrs}).encode())

    def post_field(self, params, qp, body, headers):
        opts = json.loads(body or b"{}")
        field = Field(params["field"], opts.get("type", "int"),
                      opts.get("min", 0), opts.get("max", 0))
        self._frame(params["index"], params["frame"]).create_field(field)
        self._broadcast({"type": "create-field", "index": params["index"],
                         "frame": params["frame"],
                         "field": field.to_dict()})
        return 200, "application/json", b"{}"

    def delete_field(self, params, qp, body, headers):
        self._frame(params["index"], params["frame"]).delete_field(
            params["field"])
        self._broadcast({"type": "delete-field", "index": params["index"],
                         "frame": params["frame"], "field": params["field"]})
        return 200, "application/json", b"{}"

    def get_fields(self, params, qp, body, headers):
        fr = self._frame(params["index"], params["frame"])
        return (200, "application/json", json.dumps(
            {"fields": [f.to_dict() for f in fr.fields]}).encode())

    def post_view(self, params, qp, body, headers):
        self._frame(params["index"], params["frame"]).create_view_if_not_exists(
            params["view"])
        return 200, "application/json", b"{}"

    def get_views(self, params, qp, body, headers):
        fr = self._frame(params["index"], params["frame"])
        return (200, "application/json", json.dumps(
            {"views": sorted(fr.views)}).encode())

    # -------------------------------------------------- input definitions

    def post_input_definition(self, params, qp, body, headers):
        req = json.loads(body or b"{}")
        for fr in req.get("frames", []):
            # Malformed entries are the CALLER's fault (400) — without
            # this, the storage layer's fr["name"] KeyError would
            # surface as a 500 handler bug.
            self._require(fr, "name")
        self._index(params["index"]).create_input_definition(
            params["def"], req.get("frames", []), req.get("fields", []))
        return 200, "application/json", b"{}"

    def get_input_definition(self, params, qp, body, headers):
        idef = self._index(params["index"]).input_definition(params["def"])
        return (200, "application/json",
                json.dumps(idef.to_dict()).encode())

    def delete_input_definition(self, params, qp, body, headers):
        self._index(params["index"]).delete_input_definition(params["def"])
        return 200, "application/json", b"{}"

    def post_input(self, params, qp, body, headers):
        return self._gated(self._post_input_inner, params, qp, body,
                           headers)

    def _post_input_inner(self, params, qp, body, headers):
        """JSON records through an input definition
        (ref: handler.go:1907-2014)."""
        idx = self._index(params["index"])
        idef = idx.input_definition(params["def"])
        records = json.loads(body or b"[]")
        bits_by_frame = idef.parse_records(records)
        for frame, bits in bits_by_frame.items():
            idx.input_bits(frame, [
                (row, col,
                 datetime.fromtimestamp(t) if t is not None else None)
                for row, col, t in bits])
        return 200, "application/json", b"{}"

    # ------------------------------------------------------------ import

    @staticmethod
    def _require(req, *keys):
        """Explicit request-body validation: a missing field is the
        CALLER's fault (400) — since _dispatch_route stopped mapping
        KeyError to 400, bare ``req[...]`` on client input would
        misreport malformed bodies as handler bugs (500)."""
        for key in keys:
            if key not in req:
                raise HTTPError(400, f"missing field: {key}")

    def post_import(self, params, qp, body, headers):
        return self._gated(self._post_import_inner, params, qp, body,
                           headers)

    def _post_import_inner(self, params, qp, body, headers):
        """Bulk bit import (ref: handlePostImport handler.go:1164-1243).
        Body: protobuf ImportRequest or JSON {index, frame, slice,
        rowIDs, columnIDs, timestamps?}."""
        if headers.get("Content-Type") == "application/x-protobuf":
            from pilosa_tpu.server import wireproto
            req = wireproto.decode_import_request(body)
        else:
            req = json.loads(body)
        self._require(req, "index", "frame")
        index, frame = req["index"], req["frame"]
        fr = self._frame(index, frame)
        timestamps = req.get("timestamps")
        ts = None
        if timestamps and any(timestamps):
            ts = [datetime.fromtimestamp(t) if t else None for t in timestamps]
        if req.get("rowKeys") or req.get("columnKeys"):
            return self._post_import_keyed(index, fr, req, ts, body,
                                           headers)
        slice_num = int(req.get("slice", 0))
        self._check_slice_ownership(index, slice_num)
        self._require(req, "rowIDs", "columnIDs")
        # New-slice broadcast happens in View.create_fragment_if_not_exists
        # (once per genuinely new slice), so no per-request message here.
        fr.import_bits(req["rowIDs"], req["columnIDs"], ts)
        return 200, "application/json", b"{}"

    def _post_import_keyed(self, index, fr, req, ts, body, headers):
        """Keyed import: the reference carries RowKeys/ColumnKeys on the
        wire (public.proto:77-78, ImportK client.go:307) but its server
        never reads them; here the keys become dense IDs (row keys per
        frame, column keys per index) and the bits flow through the
        normal ownership-routed pipeline.

        Key→ID allocation must be a single authority or two nodes would
        mint conflicting IDs for the same key, so non-authority nodes
        proxy the request to the cluster's key authority (the lowest
        host — deterministic from static membership); the authority
        translates and fans the bits out to each slice's owners."""
        row_keys = req.get("rowKeys") or []
        col_keys = req.get("columnKeys") or []
        if len(row_keys) != len(col_keys):
            raise HTTPError(400, "row/column key length mismatch")
        if ts is not None and len(ts) != len(row_keys):
            raise HTTPError(400, "timestamp length mismatch")

        if self.cluster is not None and len(self.cluster.nodes) > 1:
            c = getattr(self.executor, "client", None)
            if c is None:
                # A multi-node keyed import needs the internal client
                # both to proxy to the authority and to fan translated
                # bits out to slice owners; translating locally instead
                # would mint conflicting key→ID allocations.
                raise HTTPError(
                    500, "no internal client for multi-node keyed import")
            authority = min(self.cluster.nodes, key=lambda n: n.host)
            if authority.host != self.local_host:
                from pilosa_tpu.cluster import client as cclient

                # Internal-plane hop: this node already holds its own
                # admission slot for the request, so the authority must
                # not queue (or quota-charge) the proxied leg behind
                # user traffic; the remaining deadline budget rides
                # along as header and caps the socket timeout (which
                # never exceeds the client's flat health timeout — a
                # generous budget must not disable dead-peer
                # detection, the execute_query discipline).
                fwd = {qos_mod.PRIORITY_HEADER: "internal"}
                timeout = None
                budget_bound = False
                dl = qos_mod.current_deadline()
                if dl is not None:
                    remaining = dl - time.monotonic()
                    if remaining <= 0:
                        raise HTTPError(504, "deadline exceeded")
                    fwd[qos_mod.DEADLINE_HEADER] = \
                        f"{qos_mod.wall_deadline(dl):.6f}"
                    timeout = min(c.timeout, remaining)
                    budget_bound = remaining < c.timeout
                try:
                    status, data, _ = c._do(
                        "POST", cclient._node_url(authority, "/import"),
                        body,
                        content_type=headers.get("Content-Type",
                                                 "application/json"),
                        extra_headers=fwd, timeout=timeout,
                        budget_timeout=budget_bound)
                except cclient.ClientError as e:
                    if e.timed_out and budget_bound:
                        raise HTTPError(504, "deadline exceeded")
                    raise
                return (status, "application/json",
                        data or b"{}")

        idx = self._index(index)
        row_ids = np.asarray(fr.row_key_store.translate(row_keys),
                             dtype=np.int64)
        col_ids = np.asarray(idx.column_key_store.translate(col_keys),
                             dtype=np.int64)
        if self.cluster is None or len(self.cluster.nodes) <= 1:
            # Frame.import_bits partitions by slice itself — and takes
            # the arrays NATIVELY (it np.asarray's its inputs): the
            # old .tolist() round-trip re-boxed every id into a Python
            # int just to re-vectorize it one frame deeper.
            fr.import_bits(row_ids, col_ids, ts)
            return 200, "application/json", b"{}"
        # Fan translated bits out to every slice owner through the
        # internal import path (same routing as the non-keyed client).
        slices = col_ids // SLICE_WIDTH
        order = np.argsort(slices, kind="stable")
        bounds = np.flatnonzero(np.diff(slices[order])) + 1
        for g in np.split(order, bounds):
            if not len(g):
                continue
            gts = ([int(ts[i].timestamp()) if ts[i] else 0 for i in g]
                   if ts else None)
            self.executor.client.import_bits(
                self.cluster, index, fr.name, int(slices[g[0]]),
                row_ids[g].tolist(), col_ids[g].tolist(), gts)
        return 200, "application/json", b"{}"

    def post_import_value(self, params, qp, body, headers):
        return self._gated(self._post_import_value_inner, params, qp,
                           body, headers)

    def _post_import_value_inner(self, params, qp, body, headers):
        """(ref: handler.go:1244+). Body: {index, frame, field, slice,
        columnIDs, values}."""
        if headers.get("Content-Type") == "application/x-protobuf":
            from pilosa_tpu.server import wireproto
            req = wireproto.decode_import_value_request(body)
        else:
            req = json.loads(body)
        self._require(req, "index", "frame", "field", "columnIDs",
                      "values")
        index = req["index"]
        self._check_slice_ownership(index, int(req.get("slice", 0)))
        fr = self._frame(index, req["frame"])
        fr.import_value(req["field"], req["columnIDs"], req["values"])
        return 200, "application/json", b"{}"

    # ------------------------------------------------------------ ingest

    def post_ingest(self, params, qp, body, headers):
        """Streaming bulk-ingest route (ingest/pipeline.py): large
        columnar (row, column[, timestamp]) or (column, value) batches
        in ONE request — binary columnar
        (``application/x-pilosa-ingest``, ingest/codec.py) or JSON —
        admitted at the dedicated ``ingest`` QoS priority so a
        saturated gate back-pressures bulk loads (503 + Retry-After)
        before they can crowd out serving reads. Chunked
        transfer-encoding is accepted (the streaming producer shape).
        ``?slice=`` marks a coordinator's slice-targeted fan-out leg:
        ownership-checked (412), installed locally."""
        return self._gated(self._post_ingest_inner, params, qp, body,
                           headers,
                           default_priority=qos_mod.PRIO_INGEST)

    def _post_ingest_inner(self, params, qp, body, headers):
        from pilosa_tpu.ingest import codec as ingest_codec
        from pilosa_tpu.ingest.pipeline import IngestError

        if self.ingest is None:
            raise HTTPError(
                501, "ingest pipeline disabled ([ingest] enabled)")
        index = params["index"]
        if headers.get("Content-Type") == ingest_codec.CONTENT_TYPE:
            try:
                req = ingest_codec.decode(body)
            except ingest_codec.CodecError as e:
                raise HTTPError(400, str(e))
        else:
            req = json.loads(body or b"{}")
        self._require(req, "frame")
        self._frame(index, req["frame"])  # 404 like the legacy import
        local = "slice" in qp
        if local:
            self._check_slice_ownership(index, int(qp["slice"][0]))
        try:
            if req.get("values") is not None:
                self._require(req, "field", "columns", "values")
                out = self.ingest.ingest_values(
                    index, req["frame"], req["field"], req["columns"],
                    req["values"], local=local)
            else:
                self._require(req, "rows", "columns")
                ts = req.get("timestamps")
                if ts is not None and isinstance(ts, list):
                    # JSON twin: null = no timestamp (0 on the wire).
                    ts = [int(t) if t else 0 for t in ts]
                out = self.ingest.ingest_bits(
                    index, req["frame"], req["rows"], req["columns"],
                    ts, local=local)
        except IngestError as e:
            raise HTTPError(e.status, str(e))
        return 200, "application/json", json.dumps(out).encode()

    def _check_slice_ownership(self, index, slice_num):
        """Precondition check (ref: handler.go:1199-1203)."""
        if self.cluster and self.local_host:
            if not self.cluster.owns_fragment(self.local_host, index,
                                              slice_num):
                raise HTTPError(412, "host does not own slice")

    def get_export(self, params, qp, body, headers):
        """CSV export of one view+slice (ref: handler.go:1314-1364)."""
        index = qp.get("index", [""])[0]
        frame = qp.get("frame", [""])[0]
        view = qp.get("view", ["standard"])[0]
        slice_num = int(qp.get("slice", ["0"])[0])
        frag = self.holder.fragment(index, frame, view, slice_num)
        out = io.StringIO()
        if frag is not None:
            for row_id in frag.rows():
                words = frag.row_words(row_id)
                bits = np.flatnonzero(np.unpackbits(
                    words.view(np.uint8), bitorder="little"))
                for col in bits:
                    out.write(f"{row_id},"
                              f"{int(col) + slice_num * SLICE_WIDTH}\n")
        return 200, "text/csv", out.getvalue().encode()

    # --------------------------------------------------------- fragments

    def _fragment_params(self, qp):
        return (qp.get("index", [""])[0], qp.get("frame", [""])[0],
                qp.get("view", ["standard"])[0],
                int(qp.get("slice", ["0"])[0]))

    def get_fragment_data(self, params, qp, body, headers):
        """Stream a fragment backup tar (ref: handler.go:1387-1414)."""
        index, frame, view, slice_num = self._fragment_params(qp)
        frag = self.holder.fragment(index, frame, view, slice_num)
        if frag is None:
            raise HTTPError(404, str(perr.ErrFragmentNotFound()))
        buf = io.BytesIO()
        frag.write_to(buf)
        return 200, "application/octet-stream", buf.getvalue()

    def post_fragment_data(self, params, qp, body, headers):
        """Restore a fragment from a backup tar (ref: handler.go:1416-1446).

        ``?merge=1`` (the elastic-rebalance install path) unions the
        snapshot's bits into the current fragment instead of replacing
        it — a replace would wipe dual writes applied to this replica
        while the snapshot was in flight."""
        index, frame, view, slice_num = self._fragment_params(qp)
        want = headers.get("X-Pilosa-Fragment-Checksum")
        if want:
            # Pre-apply transit verification (the rebalancer always
            # stamps it): a corrupted payload must be rejected BEFORE
            # it merges — merged garbage bits cannot be re-shipped
            # away.
            import hashlib

            got = hashlib.sha256(body or b"").hexdigest()
            if got != want.strip().lower():
                raise HTTPError(
                    422, f"fragment payload checksum mismatch "
                         f"(got {got[:16]}..., want {want[:16]}...)")
        fr = self._frame(index, frame)
        frag = fr.create_view_if_not_exists(view).create_fragment_if_not_exists(
            slice_num)
        if qp.get("merge", ["0"])[0] in ("1", "true"):
            frag.merge_from(io.BytesIO(body))
        else:
            frag.read_from(io.BytesIO(body))
        return 200, "application/json", b"{}"

    def get_fragment_blocks(self, params, qp, body, headers):
        """(ref: handler.go:1486). JSON with base64 checksums — Go
        marshals []byte as base64, so reference tooling parses this."""
        index, frame, view, slice_num = self._fragment_params(qp)
        frag = self.holder.fragment(index, frame, view, slice_num)
        if frag is None:
            raise HTTPError(404, str(perr.ErrFragmentNotFound()))
        blocks = [{"id": b, "checksum": base64.b64encode(cs).decode()}
                  for b, cs in frag.blocks()]
        return (200, "application/json",
                json.dumps({"blocks": blocks}).encode())

    def get_fragment_digest(self, params, qp, body, headers):
        """Fragment-level anti-entropy digest (beyond-ref: the
        reference walks block checksums unconditionally,
        fragment.go:1703-1782; this one value lets replicas agree in
        O(1) wire bytes). 404 when the fragment doesn't exist — the
        syncer maps that to the canonical empty digest."""
        index, frame, view, slice_num = self._fragment_params(qp)
        frag = self.holder.fragment(index, frame, view, slice_num)
        if frag is None:
            raise HTTPError(404, str(perr.ErrFragmentNotFound()))
        return (200, "application/json",
                json.dumps({"digest": frag.digest().hex()}).encode())

    def get_fragment_block_data(self, params, qp, body, headers):
        """(ref: handler.go:1448-1484): the reference protocol is a
        protobuf BlockDataRequest in the request BODY and a protobuf
        BlockDataResponse back. Query-param/JSON remains as a
        debugging convenience when no body is sent."""
        from pilosa_tpu.server import wireproto

        if body:
            try:
                req = wireproto.decode_block_data_request(body)
            except (ValueError, IndexError):
                raise HTTPError(400, "unmarshal body error")
            frag = self.holder.fragment(req["index"], req["frame"],
                                        req["view"], req["slice"])
            if frag is None:
                raise HTTPError(404, str(perr.ErrFragmentNotFound()))
            rows, cols = frag.block_data(req["block"])
            return (200, "application/protobuf",
                    wireproto.encode_block_data_response(
                        rows.tolist(), cols.tolist()))
        index, frame, view, slice_num = self._fragment_params(qp)
        block = int(qp.get("block", ["0"])[0])
        frag = self.holder.fragment(index, frame, view, slice_num)
        if frag is None:
            raise HTTPError(404, str(perr.ErrFragmentNotFound()))
        rows, cols = frag.block_data(block)
        return (200, "application/json", json.dumps({
            "rowIDs": rows.tolist(), "columnIDs": cols.tolist()}).encode())

    def get_fragment_nodes(self, params, qp, body, headers):
        """(ref: handler.go:1366)."""
        index = qp.get("index", [""])[0]
        slice_num = int(qp.get("slice", ["0"])[0])
        if self.cluster:
            nodes = [{"host": n.host, "scheme": n.scheme}
                     for n in self.cluster.fragment_nodes(index, slice_num)]
        else:
            nodes = [{"host": self.local_host or "localhost",
                      "scheme": "http"}]
        return 200, "application/json", json.dumps(nodes).encode()

    # ----------------------------------------------------------- cluster

    def post_cluster_message(self, params, qp, body, headers):
        """DDL broadcast receiver (ref: handler.go:2041,
        Server.ReceiveMessage server.go:359-442). The reference
        protocol is a 1-type-byte + protobuf envelope
        (broadcast.go:139-196); JSON bodies remain accepted for
        older in-house peers."""
        ctype = headers.get("Content-Type", "")
        if "protobuf" in ctype:
            from pilosa_tpu.server import wireproto

            try:
                msg = wireproto.decode_cluster_message(body)
            except (ValueError, IndexError):
                raise HTTPError(400, "unmarshal body error")
        else:
            msg = json.loads(body)
        self.receive_message(msg)
        return 200, "application/json", b"{}"

    def receive_message(self, msg):
        t = msg.get("type")
        if t == "create-index":
            try:
                opts = msg.get("options", {})
                self.holder.create_index(
                    msg["index"], column_label=opts.get("columnLabel", ""),
                    time_quantum=opts.get("timeQuantum", ""))
            except perr.ErrIndexExists:
                pass
        elif t == "delete-index":
            try:
                self.holder.delete_index(msg["index"])
            except perr.ErrIndexNotFound:
                pass
        elif t == "create-frame":
            idx = self.holder.index(msg["index"])
            if idx is not None:
                try:
                    idx.create_frame(msg["frame"], FrameOptions.from_dict(
                        msg.get("options", {})))
                except perr.ErrFrameExists:
                    pass
        elif t == "delete-frame":
            idx = self.holder.index(msg["index"])
            if idx is not None:
                idx.delete_frame(msg["frame"])
        elif t == "create-field":
            idx = self.holder.index(msg["index"])
            fr = idx.frame(msg["frame"]) if idx is not None else None
            if fr is not None:
                try:
                    fr.create_field(Field.from_dict(msg["field"]))
                except perr.ErrFieldExists:
                    pass
        elif t == "delete-field":
            idx = self.holder.index(msg["index"])
            fr = idx.frame(msg["frame"]) if idx is not None else None
            if fr is not None:
                fr.delete_field(msg["field"])
        elif t == "delete-view":
            idx = self.holder.index(msg["index"])
            fr = idx.frame(msg["frame"]) if idx is not None else None
            if fr is not None:
                try:
                    fr.delete_view(msg["view"])
                except perr.ErrInvalidView:
                    pass
        elif t == "create-slice":
            idx = self.holder.index(msg["index"])
            if idx is not None:
                if msg.get("inverse"):
                    idx.set_remote_max_inverse_slice(msg["slice"])
                else:
                    idx.set_remote_max_slice(msg["slice"])
        elif t == "create-input-definition":
            idx = self.holder.index(msg["index"])
            if idx is not None:
                d = msg["definition"]
                try:
                    idx.create_input_definition(
                        msg["name"], d.get("frames", []), d.get("fields", []))
                except perr.ErrInputDefinitionExists:
                    pass
        elif t == "delete-input-definition":
            idx = self.holder.index(msg["index"])
            if idx is not None:
                idx.delete_input_definition(msg["name"])
        elif t == "placement-state":
            # Elastic topology: a resize coordinator's full placement
            # state (begin/commit/cleanup/abort all ship the same
            # shape; seq-guarded, so re-delivery is a no-op). STRICT:
            # a stale sender or a local pending-hints veto answers an
            # error the coordinator must abort on, never a silent 200.
            if self.rebalancer is not None:
                from pilosa_tpu.cluster.rebalancer import RebalanceError

                try:
                    self.rebalancer.receive_state(msg.get("state"),
                                                  strict=True)
                except RebalanceError as e:
                    raise HTTPError(409, str(e))

    def post_internal_heartbeat(self, params, qp, body, headers):
        """Bidirectional NodeStatus exchange riding the membership
        probe (the memberlist push/pull analog, gossip.go
        LocalState/MergeRemoteState): merge the prober's compact
        status, reply with ours. Both merge operations are create-only
        /monotonic, so out-of-order or repeated exchanges are safe."""
        st = json.loads(body or b"{}")
        if st:
            if self.epochs is not None and isinstance(
                    st.get("epochs"), dict) and st.get("host"):
                # Epoch piggyback rides the heartbeat both directions
                # (the membership probe is the freshness backstop that
                # keeps the serving path from ever needing to probe).
                self.epochs.observe(st["host"], st["epochs"])
            if self.rebalancer is not None:
                # Placement piggyback, receive side: a peer that
                # missed a resize broadcast converges from the
                # prober's state (seq-guarded; re-application no-ops).
                self.rebalancer.merge_placement(st)
            try:
                self.holder.merge_remote_status(st)
            except Exception:  # noqa: BLE001 — a malformed peer status; pilint: disable=swallow
                pass           # must not fail the liveness exchange
        local = self.holder.node_status_compact(self.local_host or "")
        if self.epochs is not None:
            from pilosa_tpu.cluster import epochs as epochs_mod

            local["epochs"] = epochs_mod.local_epochs(self.holder)
        if self.cluster is not None and self.cluster.placement.active:
            # ...and ride our placement back so the PROBER converges
            # off our state too (its merge_fn applies the reply).
            local["placement"] = self.cluster.placement.wire_state()
        if (st.get("schemaDigest")
                and st.get("schemaDigest") == local.get("schemaDigest")):
            # The prober already holds an identical schema: reply with
            # digest + max-slice maps only (steady-state probes stay
            # tiny on the wire in both directions).
            local.pop("schema", None)
        return 200, "application/json", json.dumps(local).encode()

    def post_cluster_resize(self, params, qp, body, headers):
        """Begin an online resize: ``{"hosts": [...]}`` names the new
        generation's ordered host list (order matters — the jump hash
        is evaluated over it). Returns 202 with the migration summary;
        the stream runs in the background (GET /debug/rebalance).
        409 when a resize is already in flight, 400 on validation
        errors, 501 on single-node servers (no broadcast plane)."""
        from pilosa_tpu.cluster.rebalancer import RebalanceError

        if self.rebalancer is None:
            raise HTTPError(
                501, "resize requires a multi-node server "
                     "(configure [cluster] hosts)")
        try:
            req = json.loads(body or b"{}")
        except ValueError:
            raise HTTPError(400, "invalid JSON body")
        hosts = req.get("hosts")
        if not isinstance(hosts, list) or not hosts \
                or not all(isinstance(h, str) and h for h in hosts):
            raise HTTPError(
                400, 'body must be {"hosts": ["host:port", ...]}')
        try:
            out = self.rebalancer.resize(hosts)
        except RebalanceError as e:
            msg = str(e)
            status = 409 if ("already" in msg or "in flight" in msg) \
                else 400
            raise HTTPError(status, msg)
        return 202, "application/json", json.dumps(out).encode()

    def post_cluster_autopilot_plan(self, params, qp, body, headers):
        """Dry-run one autopilot control cycle NOW: sense, plan, and
        return the actions the controller WOULD take — with the full
        sensor evidence inline — without actuating anything, without
        journaling an apply, and without consuming a rate-limit
        token. The operator's preview before trusting a loop with the
        cluster. 400 when the autopilot is disabled."""
        ap = self.autopilot
        if not ap.enabled:
            raise HTTPError(
                400, "autopilot is disabled (configure [autopilot] "
                     "enabled = true or PILOSA_AUTOPILOT_ENABLED=1)")
        try:
            plan = ap.plan()
        except Exception as e:  # noqa: BLE001 — surface, don't 500-trace
            raise HTTPError(500, f"autopilot plan failed: {e}")
        out = {k: v for k, v in plan.items() if not k.startswith("_")}
        out["dryRun"] = True
        return 200, "application/json", json.dumps(out).encode()

    def get_debug_rebalance(self, params, qp, body, headers):
        """Migration introspection: placement generations/phase/roles,
        stream counters, per-peer transfer stats, last error. Serves a
        placement-only view on nodes without a rebalancer."""
        if self.rebalancer is not None:
            out = self.rebalancer.snapshot()
        elif self.cluster is not None:
            out = {"running": False,
                   "placement": self.cluster.placement.snapshot()}
        else:
            out = {"running": False, "placement": None}
        return 200, "application/json", json.dumps(out).encode()

    def get_internal_epochs(self, params, qp, body, headers):
        """Epoch probe target (cluster/epochs.py ensure_fresh): this
        node's per-index mutation counters. Answers on single-node
        servers too — a peer joining a rolling upgrade may probe
        before this node knows it is part of a cluster."""
        from pilosa_tpu.cluster import epochs as epochs_mod

        return (200, "application/json", json.dumps({
            "host": self.local_host or "",
            "epochs": epochs_mod.local_epochs(self.holder)}).encode())

    def get_debug_epochs(self, params, qp, body, headers):
        """Epoch-vector introspection (mirrors /debug/qos): local
        counters, every peer's last-observed vector with age and
        freshness verdict, probe/cold counters. ``{"enabled": false}``
        on single-node servers."""
        snap = (self.epochs.snapshot() if self.epochs is not None
                else {"enabled": False})
        return 200, "application/json", json.dumps(snap).encode()

    def get_debug_plans(self, params, qp, body, headers):
        """Slice-plan cache introspection (mirrors /debug/epochs):
        entry counts by kind, totals, per-index hit rates with the
        current validity epochs, and the slice-universe memo state.
        ``{"enabled": false}`` when [executor] plan-cache-entries=0.
        The ``planner`` block (planner.py) reports the adaptive
        planner's switches and decision counters — reorders,
        short-circuits by kind, tier overrides by from->to — whose
        memoized plans are the cache's ``planner`` entry kind."""
        snap = self.executor.plans.snapshot()
        snap["planner"] = self.executor.planner.snapshot()
        return 200, "application/json", json.dumps(snap).encode()

    def get_debug_mesh(self, params, qp, body, headers):
        """Collective data plane introspection (mirrors /debug/plans):
        peer-group membership with mesh coordinates, collective
        launches by kind, HTTP fallbacks by reason, and the staged
        sharded-stack cache. ``{"enabled": false}`` when [mesh] is
        off."""
        mp = getattr(self.executor, "meshplane", None)
        snap = mp.snapshot() if mp is not None else {"enabled": False}
        return 200, "application/json", json.dumps(snap).encode()

    def get_internal_probe(self, params, qp, body, headers):
        """SWIM-style indirect ping helper: probe the target's /id on
        behalf of a suspicious peer (the memberlist indirect-probe
        analog; membership.py suspicion path). The target must be a
        cluster member — this endpoint is NOT a general fetch proxy
        (scheme/URI come from our own membership record, never the
        request), so it cannot be used to scan internal networks."""
        host = qp.get("host", [""])[0]
        if not host:
            raise HTTPError(400, "host required")
        node = self.cluster.node_by_host(host) if self.cluster else None
        if node is None:
            raise HTTPError(400, "host is not a cluster member")
        client = getattr(self.executor, "client", None)
        if client is not None:
            ok = client.probe(node, timeout=3)
        else:  # single-node server asked to probe: best-effort plain GET
            import urllib.request

            try:
                with urllib.request.urlopen(f"{node.uri()}/id",
                                            timeout=3) as resp:
                    ok = resp.status == 200
            except OSError:
                ok = False
        return 200, "application/json", json.dumps({"ok": ok}).encode()

    def _broadcast(self, msg):
        if self.broadcaster:
            self.broadcaster.send_sync(msg)

    # -------------------------------------------------------------- misc

    def post_recalculate_caches(self, params, qp, body, headers):
        """(ref: handler.go:2016) — REBUILDS the TopN caches from
        storage (previously this only persisted them, so a crash that
        lost the cache sidecars left ranked TopN empty forever)."""
        self.holder.recalculate_caches()
        return 204, "application/json", b""

    def get_debug_worker(self, params, qp, body, headers):
        """Which process answered: worker frontends intercept this
        route locally with their cache counters (worker.py); a
        connection the kernel routed to the master gets this stub so
        the route never 404s mid-group."""
        import os as _os

        return (200, "application/json",
                json.dumps({"pid": _os.getpid(), "mode": "master",
                            "cache": None}).encode())

    def _stats_snapshot(self):
        """(expvar snapshot dict, governor) — shared by /debug/vars
        and /metrics so the two ops surfaces can't drift."""
        stats = getattr(self.executor.holder, "stats", None)
        snapshot = getattr(stats, "snapshot", None)
        return (snapshot() if snapshot else {},
                getattr(self.holder, "governor", None))

    def get_debug_vars(self, params, qp, body, headers):
        """expvar-style counters (ref: handler.go:1631), extended with
        the round-2 subsystems: host-memory governor gauges and the
        adaptive path model's per-shape choices."""
        data, gov = self._stats_snapshot()
        if gov is not None:
            data["hostMemGovernor"] = gov.snapshot()
        model = self.executor.path_model_snapshot()
        if model:
            data["pathModel"] = model
        # Always present (knobs + counters even before the first
        # round), like the qos/faults/memory groups below.
        data["countCoalescer"] = self.executor.coalesce_snapshot()
        rb = getattr(self.executor, "_rb_stats", None)
        if rb and rb.get("rounds"):
            data["remoteBatcher"] = dict(rb)
        if self._resp_cache is not None:
            data["responseCache"] = self._resp_cache.stats()
        warm = getattr(self.executor, "_warm_stats", None)
        if warm and (warm.get("compiled") or warm.get("failed")):
            data["widthWarmer"] = dict(warm)
        if self.tracer.enabled:
            data["tracing"] = self.tracer.summary()
        # One consistent snapshot: the qos/faults/memory groups answer
        # ALWAYS (disabled subsystems report {"enabled": false}-style
        # state) instead of ad-hoc counters appearing only when armed.
        data["qos"] = self.qos.snapshot()
        data["faults"] = faults_mod.ACTIVE.snapshot()
        data["memory"] = self._memory_snapshot()
        data["epochs"] = (self.epochs.snapshot()
                          if self.epochs is not None
                          else {"enabled": False})
        data["rebalance"] = (self.rebalancer.snapshot()
                             if self.rebalancer is not None
                             else {"running": False})
        data["ingest"] = (self.ingest.snapshot()
                          if self.ingest is not None
                          else {"enabled": False})
        data["planCache"] = self.executor.plans.snapshot()
        # Workload-observatory groups, always present like qos/faults
        # (disabled tiers answer {"enabled": false}).
        data["observe"] = {
            "kernels": kerneltime_mod.ACTIVE.enabled,
            "heatmap": heatmap_mod.ACTIVE.enabled,
            "sampleRate": kerneltime_mod.ACTIVE.sample_rate,
        }
        data["slo"] = self.slo.snapshot()
        data["costModel"] = costmodel_mod.ACTIVE.snapshot()
        data["autopilot"] = self.autopilot.snapshot()
        if self.histograms.enabled:
            data["histograms"] = self.histograms.snapshot()
        return 200, "application/json", json.dumps(data).encode()

    def _memory_snapshot(self):
        """Holder memory rollup + the executor/handler cache tiers —
        shared by /debug/vars and GET /debug/memory. Shallow-copied:
        the holder memoizes its rollup, and the executor/cache keys
        added here must not leak into the shared memo."""
        mem = dict(self.holder.memory_stats())
        ex = self.executor
        mem["executor"] = {
            "stackCacheBytes": getattr(ex, "_stack_cache_bytes", 0),
            "stackCacheEntries": len(getattr(ex, "_stack_cache", ())),
            "resultMemoBytes": getattr(ex, "_result_memo_bytes", 0),
            "resultMemoEntries": len(getattr(ex, "_result_memo", ())),
        }
        if self._resp_cache is not None:
            mem["responseCache"] = self._resp_cache.stats()
        return mem

    def get_debug_memory(self, params, qp, body, headers):
        """Memory accounting rollup: per-index packed block bytes
        (host), device (HBM) mirror bytes, evicted-read memo bytes,
        disk bytes, cache occupancy; governor + executor cache tiers.
        The JSON twin of the /metrics ``pilosa_memory_*`` series."""
        return (200, "application/json",
                json.dumps(self._memory_snapshot()).encode())

    def get_debug_kernels(self, params, qp, body, headers):
        """Kernel-cost table (observe/kerneltime.py): per-(op,
        format-cell, shape-bucket) call counts and durations with
        compile-time separated from steady state, device-sampled
        means, jit cache sizes, and the transfer rollup — the measured
        cost model the planner (ROADMAP item 5) reads. {"enabled":
        false} when the observatory is off."""
        return (200, "application/json",
                json.dumps(kerneltime_mod.ACTIVE.snapshot()).encode())

    def get_debug_profile(self, params, qp, body, headers):
        """Continuous wall-clock profile (observe/profiler.py): the
        always-on stack sampler's subsystem shares and top stacks.
        Default is the standing two-generation window; ``?seconds=N``
        (cap 30) blocks that long and returns only stacks sampled
        during the wait; ``?format=folded`` renders flamegraph-ready
        collapsed-stack text instead of JSON. {"enabled": false} when
        [profile] sample-hz is 0."""
        prof = profiler_mod.ACTIVE
        fmt = qp.get("format", ["json"])[0]
        if fmt not in ("json", "folded"):
            raise HTTPError(400, "format must be json or folded")
        seconds = qp.get("seconds", [None])[0]
        if seconds is not None:
            try:
                seconds = float(seconds)
            except ValueError:
                raise HTTPError(400, "seconds must be a number")
            if seconds <= 0:
                raise HTTPError(400, "seconds must be > 0")
            out = prof.collect(min(seconds, 30.0))
            if fmt == "folded":
                lines = [f"{s['stack']} {s['samples']}"
                         for s in out.get("topStacks", ())]
                return (200, "text/plain; charset=utf-8",
                        ("\n".join(lines) + "\n").encode())
            return (200, "application/json",
                    json.dumps(out).encode())
        if fmt == "folded":
            return (200, "text/plain; charset=utf-8",
                    (prof.folded() + "\n").encode())
        return (200, "application/json",
                json.dumps(prof.snapshot()).encode())

    def post_profile_device(self, params, qp, body, headers):
        """Arm a bounded device-kernel trace capture (observe/
        devprof.py): starts a jax.profiler trace into ``?dir=`` (or
        the [profile] device-trace-dir default) and schedules its stop
        after ``?seconds=`` (cap 30) — view in TensorBoard. 501 when
        no profiling-capable backend is present, 409 while a capture
        is already armed."""
        trace_dir = (qp.get("dir", [None])[0]
                     or self.device_trace_dir
                     or "/tmp/pilosa_tpu_trace")
        try:
            seconds = float(qp.get("seconds", ["5"])[0])
        except ValueError:
            raise HTTPError(400, "seconds must be a number")
        try:
            out = devprof_mod.ACTIVE.device_capture(trace_dir, seconds)
        except devprof_mod.Unsupported as e:
            raise HTTPError(501, str(e))
        except RuntimeError as e:  # capture already armed
            raise HTTPError(409, str(e))
        return 200, "application/json", json.dumps(out).encode()

    def get_debug_heatmap(self, params, qp, body, headers):
        """Decayed slice/row heat (observe/heatmap.py): the bounded
        top-K of both tables plus per-index query pressure and
        conversion churn. The JSON twin of the top-K-only
        ``pilosa_slice_heat``/``pilosa_row_heat`` series.
        ``?scope=cluster`` fans out to every reachable peer and merges
        the per-node tables into one cluster-wide heat map — the
        autopilot placement planner's sensor, served for operators
        too."""
        snap = heatmap_mod.ACTIVE.snapshot()
        if qp.get("scope", [None])[0] != "cluster":
            return (200, "application/json", json.dumps(snap).encode())

        # Cluster scope: same degraded-peer fan-out model as
        # /debug/events — skip breaker-open peers, budget each leg
        # against the request deadline, report unreachable peers in an
        # ``errors`` map instead of failing the merge.
        try:
            deadline = self.qos.request_deadline(qp, headers)
        except qos_mod.ShedError as e:
            raise HTTPError(e.status, e.reason)
        client = getattr(self.executor, "client", None)
        nodes = list(self.cluster.nodes) if self.cluster else []
        per_node = {}
        errors = {}
        for node in nodes or [None]:
            host = node.host if node is not None else (
                self.local_host or "localhost")
            if node is None or node.host == self.local_host:
                per_node[host] = snap
                continue
            if client is None:
                errors[host] = "no client"
                continue
            brk = getattr(client, "breakers", None)
            if brk is not None and brk.is_open(host):
                errors[host] = "breaker open"
                continue
            timeout = 5.0
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    errors[host] = "deadline exhausted"
                    continue
                timeout = min(timeout, remaining)
            try:
                per_node[host] = client.heatmap_json(node,
                                                     timeout=timeout)
            except Exception as e:  # noqa: BLE001 — degraded, not failed
                errors[host] = str(e) or type(e).__name__
        out = heatmap_mod.merge_snapshots(per_node)
        out["scope"] = "cluster"
        out["nodes"] = sorted(per_node)
        out["errors"] = errors
        return 200, "application/json", json.dumps(out).encode()

    def get_debug_slo(self, params, qp, body, headers):
        """SLO state (observe/slo.py): declared objectives, 5m/1h
        burn rates per priority class, and the advisory level the
        runbook maps to page/ticket."""
        return (200, "application/json",
                json.dumps(self.slo.snapshot()).encode())

    def get_debug_costmodel(self, params, qp, body, headers):
        """Cost-model calibration state (observe/costmodel.py):
        per-tier predicted-vs-measured medians over the recent sample
        ring, learned dispatch overheads, and the per-(tier, op,
        format-cell) sample table. The accuracy surface the ROADMAP-5
        planner calibration consumes. {"enabled": false} when the
        observatory is off."""
        return (200, "application/json",
                json.dumps(costmodel_mod.ACTIVE.snapshot()).encode())

    def get_debug_events(self, params, qp, body, headers):
        """Control-plane flight recorder (observe/events.py): the
        node's journal of membership/placement/rebalance/breaker/
        epoch/QoS/SLO/fault transitions. ``?kind=`` filters by exact
        kind or dotted prefix (comma list), ``?since=<id>`` returns
        only newer events, ``?limit=`` bounds the count, and
        ``?scope=cluster`` fans out to every reachable peer and merges
        the journals into one causally-ordered timeline.
        {"enabled": false} when the recorder is off."""
        rec = self.events
        if not rec.enabled:
            return (200, "application/json",
                    json.dumps({"enabled": False}).encode())
        kinds = qp.get("kind", [None])[0]
        kinds = ([k for k in kinds.split(",") if k]
                 if kinds else None)
        try:
            since = int(qp.get("since", ["0"])[0])
            limit = max(1, min(int(qp.get("limit", ["256"])[0]), 4096))
        except ValueError:
            raise HTTPError(400, "since and limit must be integers")
        out = rec.snapshot()
        if qp.get("scope", [None])[0] != "cluster":
            out["events"] = rec.recent(kinds=kinds, since=since,
                                       limit=limit)
            return 200, "application/json", json.dumps(out).encode()

        # Cluster scope: same degraded-peer fan-out model as
        # /cluster/metrics — skip breaker-open peers, budget each leg
        # against the request deadline, report unreachable peers
        # instead of failing the merge.
        try:
            deadline = self.qos.request_deadline(qp, headers)
        except qos_mod.ShedError as e:
            raise HTTPError(e.status, e.reason)
        client = getattr(self.executor, "client", None)
        nodes = list(self.cluster.nodes) if self.cluster else []
        per_node = {}
        errors = {}
        # A ``since`` watermark is per-node (ids are local sequence
        # numbers), so only the local leg honors it; peers get the
        # kind/limit filters only.
        params_out = {"limit": str(limit)}
        if kinds:
            params_out["kind"] = ",".join(kinds)
        for node in nodes or [None]:
            host = node.host if node is not None else (
                self.local_host or "localhost")
            if node is None or node.host == self.local_host:
                per_node[host] = rec.recent(kinds=kinds, since=since,
                                            limit=limit)
                continue
            if client is None:
                errors[host] = "no client"
                continue
            brk = getattr(client, "breakers", None)
            if brk is not None and brk.is_open(host):
                errors[host] = "breaker open"
                continue
            timeout = 5.0
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    errors[host] = "deadline exhausted"
                    continue
                timeout = min(timeout, remaining)
            try:
                peer = client.events_json(node, timeout=timeout,
                                          **params_out)
                per_node[host] = peer.get("events", [])
            except Exception as e:  # noqa: BLE001 — degraded, not failed
                errors[host] = str(e) or type(e).__name__
        out["scope"] = "cluster"
        out["nodes"] = sorted(per_node)
        out["errors"] = errors
        out["events"] = events_mod.merge_timelines(per_node)[-limit:]
        return 200, "application/json", json.dumps(out).encode()

    def get_debug_replicas(self, params, qp, body, headers):
        """Per-replica vitals (observe/replica.py): streaming latency
        quantiles per (peer, op-class, priority), EWMA error rates,
        live in-flight counts, epoch-probe staleness, the slow-replica
        watchdog's baseline/degraded state, and the rolled-up health
        score per peer. {"enabled": false} when vitals are off."""
        vt = self.vitals
        if vt.enabled:
            # Surface reads drive idle-window rotation so a peer that
            # went quiet still ages out of degraded state.
            vt.watchdog_tick()
        return (200, "application/json",
                json.dumps(vt.snapshot()).encode())

    def get_debug_autopilot(self, params, qp, body, headers):
        """Autopilot introspection (autopilot/controller.py): which
        loops are enabled, the hysteresis knobs, rate-limit budget
        state, per-loop dwell clocks, action/abort counters, and the
        recent plan ring with sensor evidence. {"enabled": false}
        when the controller is off."""
        return (200, "application/json",
                json.dumps(self.autopilot.snapshot()).encode())

    def get_debug_hedge(self, params, qp, body, headers):
        """Tail-tolerant read state (cluster/hedge.py): routing /
        hedging switches, delay and headroom knobs, the token-budget
        bucket (ratio/burst/live tokens), leg and win/cancel/error
        counters, live hedge in-flight gauge, and per-reason
        suppression counts. {"enabled": false} when hedging and
        replica routing are both off."""
        return (200, "application/json",
                json.dumps(self.hedger.snapshot()).encode())

    # Per-route enabled-state probes for the /debug catalog: routes
    # not listed here are unconditionally live. Lambdas read the SAME
    # state the handlers themselves serve, so the catalog can't drift
    # from the endpoints' own {"enabled": false} answers.
    def _debug_enabled_probes(self):
        return {
            "/debug/qos": lambda: self.qos.enabled,
            "/debug/traces": lambda: self.tracer.enabled,
            "/debug/faults": lambda: faults_mod.ACTIVE.enabled,
            "/debug/lockcheck": lambda: lockcheck.ACTIVE.enabled,
            "/debug/epochs": lambda: self.epochs is not None,
            "/debug/plans": lambda: self.executor.plans.capacity != 0,
            "/debug/mesh": lambda: getattr(
                self.executor, "meshplane", None) is not None,
            "/debug/kernels": lambda: kerneltime_mod.ACTIVE.enabled,
            "/debug/profile": lambda: profiler_mod.ACTIVE.enabled,
            "/debug/profile/device": lambda: devprof_mod.ACTIVE.enabled,
            "/debug/heatmap": lambda: heatmap_mod.ACTIVE.enabled,
            "/debug/slo": lambda: self.slo.enabled,
            "/debug/costmodel": lambda: costmodel_mod.ACTIVE.enabled,
            "/debug/rebalance": lambda: self.rebalancer is not None,
            "/debug/events": lambda: self.events.enabled,
            "/debug/replicas": lambda: self.vitals.enabled,
            "/debug/autopilot": lambda: self.autopilot.enabled,
            "/debug/hedge": lambda: self.hedger.enabled,
        }

    def get_debug_index(self, params, qp, body, headers):
        """Machine-readable catalog of every ``/debug/*`` endpoint:
        path, methods, one-line description (each handler's own
        docstring — the catalog is ROUTE-TABLE-DRIVEN, so a new debug
        route appears here by construction, asserted by test), and
        whether the backing subsystem is currently enabled."""
        probes = self._debug_enabled_probes()
        by_path = {}
        for method, pattern, fn in self.routes:
            path = pattern.strip("^$")
            if not path.startswith("/debug") or path == "/debug":
                continue
            ent = by_path.setdefault(path, {
                "path": path, "methods": [],
                "description": (fn.__doc__ or "").strip()
                .split("\n", 1)[0].rstrip(),
                "enabled": True,
            })
            if method not in ent["methods"]:
                ent["methods"].append(method)
            probe = probes.get(path)
            if probe is not None:
                try:
                    ent["enabled"] = bool(probe())
                except Exception:  # noqa: BLE001; pilint: disable=swallow
                    pass  # a probe racing subsystem teardown leaves
                    # the default True — the catalog row survives
        out = {"endpoints": sorted(by_path.values(),
                                   key=lambda e: e["path"])}
        return 200, "application/json", json.dumps(out).encode()

    def get_debug_traces(self, params, qp, body, headers):
        """Recent traces as JSON span trees (the trace-level analog of
        /debug/vars). ``?slow=true`` reads the slow-query ring,
        ``?traceId=`` filters (how a cross-node trace is gathered for
        stitching), ``?n=`` bounds the count."""
        try:
            n = max(1, min(int(qp.get("n", ["32"])[0]), 512))
        except ValueError:
            raise HTTPError(400, "n must be an integer")
        slow = qp.get("slow", ["false"])[0] == "true"
        trace_id = qp.get("traceId", [None])[0]
        tr = self.tracer
        out = {
            "enabled": tr.enabled,
            "slowThresholdMs": round(tr.slow_threshold * 1000, 3),
            "summary": tr.summary(),
            "traces": tr.recent(n, slow=slow, trace_id=trace_id),
        }
        return 200, "application/json", json.dumps(out).encode()

    def _metrics_text(self):
        """The node's full exposition text — /metrics body, and the
        local leg of /cluster/metrics."""
        from pilosa_tpu.stats import prometheus_exposition

        data, gov = self._stats_snapshot()
        groups = []
        if gov is not None:
            groups.append(("host_mem", gov.snapshot()))
        # pilosa_coalesce_* — micro-batching tick counters (rounds,
        # fused-by-tier, lane launches, declines by reason), always
        # present like plan_cache; the group-size distribution rides
        # the coalesce_group_size histogram family below.
        groups.append(("coalesce", self.executor.coalesce_metrics()))
        if self.qos.enabled:
            # pilosa_qos_shed_total, queue depth/in-flight gauges, and
            # pilosa_qos_breaker_state{peer=...} series.
            groups.append(("qos", self.qos.metrics()))
        if faults_mod.ACTIVE.enabled:
            # pilosa_faults_triggered_total (+ per-point series).
            groups.append(("faults", faults_mod.ACTIVE.metrics()))
        if self.epochs is not None:
            # pilosa_epoch_* — observation/probe/cold counters and the
            # cluster vector version (multi-node only).
            groups.append(("epoch", self.epochs.metrics()))
        if self.rebalancer is not None:
            # pilosa_rebalance_* — slices moved/pending, bytes
            # streamed, generation, per-peer stream totals.
            groups.append(("rebalance", self.rebalancer.metrics()))
        if self.ingest is not None:
            # pilosa_ingest_* — batches/bits/values ingested, slice
            # groups, fan-out posts, device pack passes, containers
            # seeded by format, rejects/errors.
            groups.append(("ingest", self.ingest.metrics()))
        # pilosa_plan_cache_{hits,misses,invalidations,entries} — the
        # slice-plan cache counters (plancache.py), present even when
        # the cache is disabled (entries/capacity report 0).
        groups.append(("plan_cache", self.executor.plans.metrics()))
        # pilosa_plan_{reorder,shortcircuit,tier_override}_total — the
        # adaptive planner's decision counters (planner.py): untagged
        # totals always present (zeroed from boot); kind= and from=/
        # to= tagged children appear with their first event.
        groups.append(("plan", self.executor.planner.metrics()))
        mp = getattr(self.executor, "meshplane", None)
        if mp is not None:
            # pilosa_mesh_* — collective data plane: launches by kind,
            # HTTP fallbacks by reason (pre-seeded so every series
            # exists from boot), staged-stack cache gauges.
            groups.append(("mesh", mp.metrics()))
        # Workload observatory: pilosa_kernel_* cost cells,
        # pilosa_slice_heat / pilosa_row_heat top-K series (bounded
        # cardinality by construction; /cluster/metrics merges them
        # with node= labels so the rebalancer sees cluster-wide heat),
        # pilosa_observe_* bookkeeping, pilosa_slo_* burn rates. All
        # empty (absent) when the respective tier is disabled.
        groups.append(("kernel", kerneltime_mod.ACTIVE.metrics()))
        # pilosa_profile_* — continuous-profiler bookkeeping: total/
        # per-subsystem sample counters, trie occupancy, generation
        # rotations, overflow. Absent entirely when sample-hz is 0.
        groups.append(("profile", profiler_mod.ACTIVE.metrics()))
        # pilosa_cost_model_* — predicted-vs-measured calibration
        # counters by (tier, op, format-cell); untagged totals always
        # present while the model is enabled. The error-ratio
        # distribution rides the cost_model_error histogram family.
        groups.append(("cost_model", costmodel_mod.ACTIVE.metrics()))
        hm = heatmap_mod.ACTIVE
        groups.append(("slice", hm.slice_metrics()))
        groups.append(("row", hm.row_metrics()))
        groups.append(("observe", hm.observe_metrics()))
        groups.append(("slo", self.slo.metrics()))
        if self.events.enabled:
            # pilosa_events_total{kind=...} — flight-recorder journal
            # counters (bounded cardinality: one series per event
            # kind actually emitted).
            groups.append(("events", self.events.metrics()))
        if self.vitals.enabled:
            # pilosa_replica_* — per-peer latency quantiles, in-flight
            # gauges, EWMA error rates, watchdog degraded flags, and
            # health scores (empty until the first fan-out call).
            groups.append(("replica", self.vitals.metrics()))
        if self.autopilot.enabled:
            # pilosa_autopilot_* — plans/actions/aborts/cooldown
            # counters, rate-limit budget gauge, per-loop enabled
            # flags (absent entirely when the controller is off).
            groups.append(("autopilot", self.autopilot.metrics()))
        if self.hedger.enabled:
            # pilosa_hedge_* — primary/hedge leg counters, armed/
            # fired/won/cancelled race outcomes, per-reason
            # suppression counts, the live hedge in-flight gauge,
            # and the token-budget level (absent when hedging and
            # replica routing are both off).
            groups.append(("hedge", self.hedger.metrics()))
        # pilosa_memory_fragment_bytes{index=...} & friends — the
        # HBM/host accounting rollup (holder.memory_metrics).
        groups.append(("memory", self.holder.memory_metrics()))
        hset = self.histograms if self.histograms.enabled else None
        return prometheus_exposition(data, groups, histograms=hset)

    def get_metrics(self, params, qp, body, headers):
        """Prometheus text exposition (beyond-ref; the reference
        offers expvar + statsd only, stats.go:87-165): the expvar
        snapshot with tags as labels, plus governor/coalescer/qos/
        faults/memory gauges and the tagged histogram families. Works
        when the server runs the expvar stats backend (the default);
        other backends expose what they have."""
        return (200, "text/plain; version=0.0.4; charset=utf-8",
                self._metrics_text().encode())

    def _note_scrape_error(self, host):
        # The handler dict is the ONLY home for this counter: it
        # renders as pilosa_cluster_scrape_errors_total{node="peer"}
        # in the merged payload. A parallel untagged expvar counter
        # would ride this node's own /metrics into the merge and come
        # back relabeled node="<coordinator>" — every failure counted
        # twice, half of it blaming the healthy coordinator.
        with self._scrape_mu:
            self._scrape_errors[host] = self._scrape_errors.get(
                host, 0) + 1

    def get_cluster_metrics(self, params, qp, body, headers):
        """Cluster-wide metrics aggregation: fan out to every peer's
        /metrics (breaker-aware — an open breaker's peer is skipped,
        not probed — and bounded by the request's deadline budget),
        merge same-named families with a ``node=`` label per sample,
        and degrade gracefully: an unreachable peer becomes a
        ``pilosa_cluster_scrape_errors_total{node=...}`` sample, never
        an HTTP error. One scrape target for the whole cluster."""
        if not self.cluster_metrics_enabled:
            raise HTTPError(
                403, "cluster metrics aggregation disabled "
                     "([metrics] cluster-aggregation)")
        try:
            deadline = self.qos.request_deadline(qp, headers)
        except qos_mod.ShedError as e:
            raise HTTPError(e.status, e.reason)
        client = getattr(self.executor, "client", None)
        nodes = list(self.cluster.nodes) if self.cluster else []
        texts = []
        for node in nodes or [None]:
            host = node.host if node is not None else (
                self.local_host or "localhost")
            if node is None or node.host == self.local_host:
                texts.append((host, self._metrics_text()))
                continue
            if client is None:
                self._note_scrape_error(host)
                continue
            brk = getattr(client, "breakers", None)
            if brk is not None and brk.is_open(host):
                # A breaker-open peer already proved dead moments ago;
                # scraping it would pay the timeout per poll (and a
                # metrics scrape must not consume the half-open probe
                # slot a real query deserves).
                self._note_scrape_error(host)
                continue
            timeout = 5.0
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._note_scrape_error(host)
                    continue
                timeout = min(timeout, remaining)
            try:
                texts.append((host, client.metrics_text(
                    node, timeout=timeout)))
            except Exception:  # noqa: BLE001 — degraded, not failed
                self._note_scrape_error(host)
        with self._scrape_mu:
            errors = dict(self._scrape_errors)
        merged = stats_mod.merge_expositions(texts,
                                             scrape_errors=errors)
        return (200, "text/plain; version=0.0.4; charset=utf-8",
                merged.encode())

    def post_profile_start(self, params, qp, body, headers):
        """Start a JAX/XPlane device trace — the TPU-native replacement
        for /debug/pprof (ref: handler.go:102-103); view in TensorBoard."""
        import jax

        trace_dir = qp.get("dir", ["/tmp/pilosa_tpu_trace"])[0]
        jax.profiler.start_trace(trace_dir)
        return (200, "application/json",
                json.dumps({"tracing": trace_dir}).encode())

    def post_profile_stop(self, params, qp, body, headers):
        """Stop the JAX/XPlane device trace post_profile_start began
        (400 when none is running)."""
        import jax

        try:
            jax.profiler.stop_trace()
        except RuntimeError as e:  # not started
            raise HTTPError(400, str(e))
        return 200, "application/json", b"{}"

    def get_webui(self, params, qp, body, headers):
        from pilosa_tpu.server.webui import INDEX_HTML
        return 200, "text/html", INDEX_HTML.encode()

    def get_asset(self, params, qp, body, headers):
        """Console assets (ref: /assets/{file} handler.go:101)."""
        from pilosa_tpu.server.webui import ASSETS
        asset = ASSETS.get(params["file"])
        if asset is None:
            raise HTTPError(404, "asset not found")
        ctype, content = asset
        return 200, ctype, content.encode()

    def method_not_allowed(self, params, qp, body, headers):
        """(ref: methodNotAllowedHandler handler.go:147)."""
        return 405, "application/json", b""

    def delete_view(self, params, qp, body, headers):
        """(ref: handleDeleteView handler.go:127; frame.DeleteView)."""
        fr = self._frame(params["index"], params["frame"])
        try:
            fr.delete_view(params["view"])
        except perr.ErrInvalidView:
            # Views do not exist on every node (slice distribution);
            # the reference ignores this error too.
            pass
        self._broadcast({"type": "delete-view", "index": params["index"],
                         "frame": params["frame"], "view": params["view"]})
        return 200, "application/json", b"{}"

    def post_frame_restore(self, params, qp, body, headers):
        """Pull every owned slice of a frame from a remote cluster host
        (ref: handlePostFrameRestore handler.go:121, :1680+)."""
        from pilosa_tpu.cluster.client import ClientError, InternalClient
        from pilosa_tpu.cluster.cluster import Node
        from pilosa_tpu.utils.uri import URI

        host = qp.get("host", [""])[0]
        if not host:
            raise HTTPError(400, "host required")
        index, frame = params["index"], params["frame"]
        fr = self._frame(index, frame)
        u = URI.parse(host)
        remote = Node(u.host_port(), scheme=u.scheme)
        # Reuse the executor's client so TLS skip-verify carries over
        # (ref: h.RemoteClient handler.go).
        client = getattr(self.executor, "client", None) or InternalClient()

        max_slices = client.max_slices(remote)
        max_inverse = client.max_slices(remote, inverse=True)
        views = client.frame_views(remote, index, frame)
        for view in views:
            # Inverse views span the inverse (row-derived) slice range,
            # which can exceed the standard one (ref: MaxInverseSlices
            # handler.go:323-337).
            inverse = view == "inverse" or view.startswith("inverse_")
            max_slice = (max_inverse if inverse else max_slices).get(index, 0)
            for slice_num in range(max_slice + 1):
                if (self.cluster is not None
                        and not self.cluster.owns_fragment(
                            self.local_host, index, slice_num)):
                    continue
                try:
                    tar = client.backup_fragment(
                        remote, index, frame, view, slice_num)
                except ClientError:
                    continue  # slice doesn't exist on the remote
                v = fr.create_view_if_not_exists(view)
                frag = v.create_fragment_if_not_exists(slice_num)
                frag.read_from(io.BytesIO(tar))
        return 200, "application/json", b"{}"


class _FastHeaders(dict):
    """Case-insensitive header mapping with Title-Case canonical keys
    (the cheap dict stand-in for email.Message in the fast parse
    path — handlers receive it via ``dict(self.headers)`` and look
    keys up in canonical form)."""

    def get(self, key, default=None):
        return dict.get(self, key.title(), default)

    def __contains__(self, key):
        return dict.__contains__(self, key.title())


def make_http_server(handler, bind="localhost:0", reuse_port=False,
                     max_body_size=DEFAULT_MAX_BODY_SIZE):
    """Wrap a Handler (or a bare ``dispatch(method, path, qp, body,
    headers) -> (status, ctype, payload[, extra_headers])`` callable —
    worker frontends pass one, see worker.py) in a
    ThreadingHTTPServer. ``reuse_port`` joins an SO_REUSEPORT group so
    worker processes can share the public port (see workers.py).
    Requests advertising a body larger than ``max_body_size`` are
    rejected with 413 BEFORE any body byte is buffered (0 disables
    the check)."""
    host, _, port = bind.rpartition(":")
    dispatch = handler.dispatch if hasattr(handler, "dispatch") \
        else handler

    class _Req(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # Headers and payload go out as separate writes; with Nagle on,
        # the payload segment waits out the peer's delayed ACK (~40 ms
        # per keep-alive request). Go's net/http sets TCP_NODELAY too.
        disable_nagle_algorithm = True

        def parse_request(self):
            """Fast request parse: the stdlib routes headers through
            email.feedparser (~130 µs/request — profiled at ~25% of a
            warm serve, paid again by every worker frontend and every
            internal-plane request). Plain `METHOD path HTTP/1.x`
            requests take a direct line loop into a case-insensitive
            dict; anything unusual in the REQUEST LINE delegates to
            the stdlib implementation before any header byte is
            consumed, so exotic protocol handling is unchanged. As a
            side effect header lookups become properly
            case-insensitive downstream (dict(email.Message) used to
            preserve client casing, missing lowercase senders)."""
            line = str(self.raw_requestline, "iso-8859-1").rstrip("\r\n")
            words = line.split()
            if (len(words) != 3
                    or words[2] not in ("HTTP/1.1", "HTTP/1.0")):
                return super().parse_request()
            self.requestline = line
            self.command, self.path, self.request_version = words
            self.close_connection = words[2] == "HTTP/1.0"
            headers = _FastHeaders()
            last = None
            for _ in range(201):
                hline = self.rfile.readline(65537)
                if len(hline) > 65536:
                    self.send_error(431)  # header line too long
                    return False
                if hline in (b"\r\n", b"\n", b""):
                    break
                if hline[0] in (32, 9):
                    if last is not None:
                        # Obsolete line folding: append to the
                        # anchoring field's value.
                        headers[last] += " " + hline.strip().decode(
                            "iso-8859-1")
                    continue
                name, sep, value = hline.decode("iso-8859-1") \
                    .partition(":")
                if not sep or not name.strip():
                    last = None
                    continue  # junk line: tolerated, as email parser
                if name != name.strip():
                    # RFC 7230 §3.2.4: whitespace between field name
                    # and colon MUST be rejected — a proxy that drops
                    # such a field while we honored it is a
                    # request-smuggling differential.
                    self.send_error(400, "whitespace in header name")
                    return False
                key = name.title()
                value = value.strip()
                if key in headers:
                    if key == "Content-Length" \
                            and dict.get(headers, key) != value:
                        # Conflicting lengths desync body framing
                        # between parsers — reject outright.
                        self.send_error(400,
                                        "conflicting Content-Length")
                        return False
                    last = None  # duplicate: FIRST value wins, as
                    continue     # email.Message.get; folds dropped
                headers[key] = value
                last = key
            else:
                self.send_error(431)  # too many headers
                return False
            self.headers = headers
            conntype = headers.get("Connection", "").lower()
            if conntype == "close":
                self.close_connection = True
            elif conntype == "keep-alive":
                self.close_connection = False
            # The stdlib tail this path replaces: 100-continue must
            # be answered or body-bearing clients (curl >1 KB) stall
            # waiting for it while we block on rfile.read.
            if (headers.get("Expect", "").lower() == "100-continue"
                    and self.protocol_version >= "HTTP/1.1"
                    and self.request_version >= "HTTP/1.1"):
                if not self.handle_expect_100():
                    return False
            return True

        def _content_length(self):
            """Declared body length; None for an unparseable or
            negative header (the caller answers 400 — an uncaught
            ValueError would kill the connection with no response,
            and a negative length would reach ``rfile.read(-1)``,
            buffering until EOF past the 413 gate)."""
            try:
                length = int(self.headers.get("Content-Length") or 0)
            except ValueError:
                return None
            return None if length < 0 else length

        _INGEST_PATH = re.compile(r"^/index/[^/]+/ingest$")

        # Bulk-ingest bodies must not buffer unbounded (chunked OR
        # Content-Length): a hard sanity ceiling, far above any
        # configured batch bound ([ingest] max-batch-bits rejects
        # first in practice — this guard is the OOM backstop).
        _INGEST_HARD_CAP = 2 << 30

        def _body_cap(self, path):
            """Byte ceiling for this route's request body, 0 =
            uncapped. The 413 gate applies to every route except
            fragment restore and bulk ingest: POST /fragment/data
            legitimately carries multi-GB backup tars
            (storage/fragment.py write_to) on the intra-cluster plane
            and stays uncapped (pre-existing contract); the ingest
            route's whole point is batches far beyond the default cap,
            so it gets the hard sanity ceiling instead of the
            configured one."""
            if path == "/fragment/data":
                return 0
            if self._INGEST_PATH.match(path):
                return self._INGEST_HARD_CAP
            return max_body_size

        def _read_chunked(self, cap):
            """RFC 7230 §4.1 chunked-body decode with cumulative cap
            enforcement — the streaming-producer shape the ingest
            route accepts (a producer can start sending before it
            knows the batch size). ``cap`` 0 = uncapped, the same
            contract as the Content-Length path (POST /fragment/data
            legitimately streams multi-GB tars). Returns (body, None)
            or (None, error): "bad" = malformed framing (400),
            "too_large" = the cumulative size crossed ``cap`` (413)
            — detected mid-stream, before the rest buffers."""
            total = 0
            parts = []
            while True:
                line = self.rfile.readline(65537)
                if not line or len(line) > 65536:
                    return None, "bad"
                try:
                    size = int(line.split(b";")[0].strip(), 16)
                except ValueError:
                    return None, "bad"
                if size < 0:
                    return None, "bad"
                if size == 0:
                    while True:  # trailer section
                        t = self.rfile.readline(65537)
                        if t in (b"\r\n", b"\n", b""):
                            break
                    return b"".join(parts), None
                total += size
                if cap and total > cap:
                    return None, "too_large"
                data = self.rfile.read(size)
                if len(data) < size:
                    return None, "bad"
                parts.append(data)
                if self.rfile.read(2) != b"\r\n":
                    return None, "bad"

        def handle_expect_100(self):
            """Answer 413 instead of `100 Continue` when the declared
            body is oversized — an Expect-aware client then never
            sends the body at all."""
            length = self._content_length()
            if length is None:
                self.send_error(400, "bad Content-Length")
                return False
            cap = self._body_cap(urlparse(self.path).path)
            if cap and length > cap:
                self.send_error(413, "request body too large")
                return False
            return super().handle_expect_100()

        def _serve(self):
            parsed = urlparse(self.path)
            qp = parse_qs(parsed.query)
            te = (self.headers.get("Transfer-Encoding") or "").lower()
            if "chunked" in te:
                body, err = self._read_chunked(
                    self._body_cap(parsed.path))
                if err is not None:
                    # Mid-stream abort: the peer may still be sending,
                    # so the connection can't be reused either way.
                    self.close_connection = True
                    if err == "too_large":
                        self._reject_oversized()
                    else:
                        self.send_error(400, "bad chunked encoding")
                    return
                resp = dispatch(self.command, parsed.path, qp, body,
                                dict(self.headers))
                self._respond(resp)
                return
            length = self._content_length()
            if length is None:
                self.close_connection = True
                self.send_error(400, "bad Content-Length")
                return
            cap = self._body_cap(parsed.path)
            if cap and length > cap:
                # Reject BEFORE buffering: an arbitrarily large POST
                # must not pin server memory. The body is never read,
                # so the connection can't be reused — close it (the
                # client may still be blocked mid-send).
                self.close_connection = True
                self._reject_oversized()
                return
            body = self.rfile.read(length) if length else b""
            resp = dispatch(self.command, parsed.path, qp, body,
                            dict(self.headers))
            self._respond(resp)

        def _reject_oversized(self):
            payload = json.dumps(
                {"error": "request body too large"}).encode()
            self.send_response(413)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.send_header("Connection", "close")
            self.end_headers()
            self.wfile.write(payload)

        def _respond(self, resp):
            status, ctype, payload = resp[:3]
            extra = resp[3] if len(resp) > 3 else None
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            if extra:
                for k, v in extra.items():
                    self.send_header(k, v)
            # One sendall for headers + small payload (end_headers +
            # wfile.write would issue two): saves a syscall AND the
            # delayed-ACK interplay between the header segment and the
            # payload segment (~4x warm HTTP serving, measured). Large
            # bodies keep the separate zero-copy write — joining them
            # into the header buffer would memcpy the whole payload.
            # HTTP/0.9 has no _headers_buffer (stdlib skips buffering)
            # and takes the classic path too.
            if (len(payload) < 16384
                    and hasattr(self, "_headers_buffer")):
                self._headers_buffer.append(b"\r\n")
                self._headers_buffer.append(payload)
                self.flush_headers()
            else:
                self.end_headers()
                self.wfile.write(payload)

        do_GET = do_POST = do_DELETE = do_PATCH = _serve

        def setup(self):
            super().setup()
            self.server.track_conn(self.connection, True)

        def finish(self):
            self.server.track_conn(self.connection, False)
            super().finish()

        def log_message(self, fmt, *args):  # quiet test output
            pass

    class _Server(ThreadingHTTPServer):
        # Python's default listen backlog is 5 — a 32-client connect
        # burst gets connection-reset before a thread ever runs. The
        # reference's http.Serve inherits Go's default (SOMAXCONN).
        request_queue_size = 128
        daemon_threads = True

        def server_bind(self):
            if reuse_port:
                import socket as _socket

                self.socket.setsockopt(_socket.SOL_SOCKET,
                                       _socket.SO_REUSEPORT, 1)
            super().server_bind()

        # Established keep-alive connections outlive shutdown() —
        # ThreadingHTTPServer only stops the ACCEPT loop, while every
        # per-connection daemon thread keeps answering requests
        # against the closed server's (stale) state. A pooled internal
        # client would keep "succeeding" against a closed node — a
        # write acknowledged into state about to be discarded. Track
        # open connections and sever them in server_close(), as the
        # reference's http.Server.Close closes active conns.
        def __init__(self, *args, **kw):
            import threading as _threading

            from pilosa_tpu import lockcheck as _lockcheck

            self._open_conns = set()
            self._conns_mu = _lockcheck.register(
                "handler._Server._conns_mu", _threading.Lock())
            super().__init__(*args, **kw)

        def track_conn(self, sock, on):
            with self._conns_mu:
                if on:
                    self._open_conns.add(sock)
                else:
                    self._open_conns.discard(sock)

        def server_close(self):
            super().server_close()
            import socket as _socket

            with self._conns_mu:
                conns = list(self._open_conns)
                self._open_conns.clear()
            for sock in conns:
                try:
                    sock.shutdown(_socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    sock.close()
                except OSError:
                    pass

    return _Server((host or "localhost", int(port or 0)), _Req)
