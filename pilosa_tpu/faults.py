"""Deterministic fault injection — failpoints.

The crash-fuzz suite proves the op-log format survives arbitrary torn
tails, but nothing in the repo can *make* an fsync fail, a peer stall,
or a fragment file rot on demand — the failure paths the QoS and
anti-entropy tiers exist to absorb were untestable (the reference
leaves even torn logs as a FIXME, roaring.go:724). This module gives
every layer named injection points, activated per-point:

- ``PILOSA_FAULTS=<spec>`` environment (read once at import),
- the ``[faults]`` config table (``enabled`` + ``spec``),
- ``POST /debug/faults`` at runtime (test-only: 403 unless the
  subsystem is already enabled by one of the first two).

Spec grammar (comma-separated entries)::

    point=action[(arg)][:p=<prob>][:after=<n>][:count=<m>]

    fragment.append.fsync=error(ENOSPC)
    client.fanout.slow=delay(0.25):p=0.5
    fragment.read.corrupt=corrupt:after=1:count=3

Actions: ``error(ERRNO|int)`` raises an OSError subclass
(``FaultError``) with that errno at the site; ``delay(seconds)``
sleeps; ``corrupt`` returns the verdict string so the site mutilates
its own bytes (the registry cannot know the layout); ``panic[(code)]``
hard-exits the process via ``os._exit`` — the crash-injection action
for subprocess-driven tests. Triggers: ``p`` fires with that
probability (deterministic seam: ``_rand`` is injectable), ``after=n``
skips the first n hits, ``count=m`` fires at most m times then
disarms. Every firing counts into ``pilosa_faults_triggered_total``
(plus a per-point tagged series) and tags the active tracing span.

Disabled — the default — the module global ``ACTIVE`` is a shared nop
object, so every injection site costs one ``ACTIVE.enabled`` attribute
read behind an ``if`` (the NopTracer / NopStatsClient / NopQoS
discipline): no locks, no allocations, no spec parsing on the hot
path. Registered point names (the contract the chaos suite drives):

    fragment.append.fsync     op-log write/flush/fsync (storage/fragment.py)
    fragment.snapshot.rename  snapshot temp-file promote (storage/fragment.py)
    fragment.read.corrupt     fault-in file read (storage/fragment.py)
    holder.open.partial       per-index holder boot (storage/holder.py)
    client.fanout.error       internal-plane request (cluster/client.py)
    client.fanout.slow        internal-plane request, pre-dial (cluster/client.py)
    client.fanout.corrupt     internal-plane response bytes (cluster/client.py)
    client.hedge.slow         hedged second leg, pre-dispatch
                              (executor.py): the hedge itself stalls —
                              the primary should win the race and the
                              loser's sample stays suppressed
    client.hedge.error        hedged second leg, pre-dispatch: the
                              hedge dies before (or instead of) the
                              wire — the merged result must stay
                              bit-exact on the primary's answer, the
                              in-flight hedge gauge must return to
                              zero, and replica vitals must not
                              double-count the leg
    client.epoch.stale        epoch-vector propagation (cluster/epochs.py):
                              armed, every observation — piggyback,
                              heartbeat, probe — is dropped, modeling a
                              partition of the epoch plane; caches must
                              degrade to cold, never serve stale

    syncer.blocks.error       anti-entropy block fetch (cluster/syncer.py)
    executor.slice.delay      per-slice serial execution (executor.py)
    rebalance.stream.error    migration fragment stream (cluster/
                              rebalancer.py): a firing error aborts the
                              resize — the new generation never commits
    rebalance.stream.slow     migration stream pacing (delay action)
    rebalance.stream.corrupt  migration payload bytes: the per-fragment
                              digest verification must catch the
                              mutilation and re-ship
    rebalance.commit.partial  commit broadcast delivery: armed, the
                              coordinator "loses" deliveries to peers —
                              the heartbeat placement piggyback must
                              converge them, and cleanup waits for full
                              acknowledgement
    ingest.stream.slow        bulk-ingest batch entry (ingest/
                              pipeline.py; delay action) — a stalled
                              producer stream
    ingest.pack.error         the device pack/classify pass of one
                              slice group: fires BEFORE anything
                              installs, so a failed batch never acks
                              and never leaves a partially-installed
                              container (retries are idempotent)
    autopilot.plan.error      controller plan pass (autopilot/
                              controller.py): a firing error journals
                              ``autopilot.abort`` and the tick stands
                              down — no budget token is consumed
    autopilot.apply.slow      controller action apply, pre-actuator
                              (delay action): a wedged action; the
                              mid-flight kill switch aborts it
                              cleanly and releases its cooldown token

Unknown names are accepted (a site may be added later); ``fire`` on an
unconfigured point is a dict miss.
"""
import errno as errno_mod
import os
import random
import re
import threading
import time

from pilosa_tpu import tracing

from pilosa_tpu import lockcheck


class FaultError(OSError):
    """An injected I/O error. Subclasses OSError so the hardened
    ``except OSError`` paths treat it exactly like the real ENOSPC/EIO
    it stands in for — the point of the exercise."""


_ENTRY_RE = re.compile(
    r"^(?P<name>[A-Za-z0-9_.-]+)=(?P<kind>error|delay|corrupt|panic)"
    r"(?:\((?P<arg>[^)]*)\))?(?P<mods>(?::[a-z]+=[0-9.]+)*)$")

def _parse_errno(arg):
    if not arg:
        return errno_mod.EIO
    try:
        return int(arg)
    except ValueError:
        num = getattr(errno_mod, arg.strip().upper(), None)
        if num is None:
            raise ValueError(f"unknown errno name: {arg!r}")
        return num


class Failpoint:
    """One armed injection point; counters guarded by the registry."""

    __slots__ = ("name", "kind", "arg", "p", "after", "count",
                 "hits", "fired")

    def __init__(self, name, kind, arg=None, p=1.0, after=0, count=0):
        self.name = name
        self.kind = kind
        self.arg = arg
        self.p = float(p)
        self.after = int(after)
        self.count = int(count)  # 0 = unlimited
        self.hits = 0
        self.fired = 0

    @classmethod
    def parse(cls, entry):
        m = _ENTRY_RE.match(entry.strip())
        if m is None:
            raise ValueError(f"bad failpoint spec: {entry!r}")
        kind, raw_arg = m.group("kind"), m.group("arg")
        if kind == "error":
            arg = _parse_errno(raw_arg)
        elif kind == "delay":
            arg = float(raw_arg) if raw_arg else 0.0
            if arg < 0:
                raise ValueError(f"negative delay: {entry!r}")
        elif kind == "panic":
            arg = int(raw_arg) if raw_arg else 77
        else:
            arg = None
        mods = {}
        for mod in filter(None, m.group("mods").split(":")):
            k, _, v = mod.partition("=")
            if k not in ("p", "after", "count"):
                raise ValueError(f"unknown failpoint modifier: {k!r}")
            mods[k] = float(v) if k == "p" else int(float(v))
        p = mods.get("p", 1.0)
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"probability out of range: {entry!r}")
        return cls(m.group("name"), kind, arg, p,
                   mods.get("after", 0), mods.get("count", 0))

    def snapshot(self):
        return {"action": self.kind, "arg": self.arg, "p": self.p,
                "after": self.after, "count": self.count,
                "hits": self.hits, "fired": self.fired}


def parse_spec(spec):
    """Spec string (or point->entry dict) -> {name: Failpoint}. Raises
    ValueError on any malformed entry — config validation calls this so
    a bad ``[faults] spec`` fails at startup, not at first fire."""
    points = {}
    if isinstance(spec, dict):
        entries = [f"{k}={v}" for k, v in spec.items()]
    else:
        entries = [e for e in (spec or "").split(",") if e.strip()]
    for entry in entries:
        fp = Failpoint.parse(entry)
        points[fp.name] = fp
    return points


class FaultRegistry:
    """The enabled registry: named failpoints + firing counters.

    ``fire(name)`` is the single site API — it looks the point up,
    honors the triggers, counts, tags the active tracing span, and
    performs the action (raise / sleep / hard-exit), returning the
    action name for ``corrupt`` (the site owns the byte mutilation)
    and None when nothing fired. Process-global by design: fragments
    and clients hold no server reference, and an in-process
    ``ServerCluster`` sharing one registry is exactly what the chaos
    suite wants."""

    enabled = True

    def __init__(self, _rand=None, _sleep=None):
        self._mu = lockcheck.register("faults.FaultRegistry._mu",
                                      threading.Lock())
        self._points = {}
        self._rand = _rand or random.random   # deterministic test seam
        self._sleep = _sleep or time.sleep
        self.triggered_total = 0
        self._triggered_by_point = {}
        # Flight recorder (observe.events), server-installed; None
        # when off. Arming/clearing points journals chaos experiments
        # next to the transitions they cause; per-fire emission would
        # flood the ring (delay points fire per slice).
        self.events = None

    # -------------------------------------------------------- configure

    def configure(self, spec):
        """Merge a spec string/dict into the live point table (counters
        of re-specified points reset — the new arming is a new
        experiment)."""
        parsed = parse_spec(spec)
        with self._mu:
            self._points.update(parsed)
        ev = self.events
        if ev is not None:
            for name, fp in parsed.items():
                ev.emit("faults.armed", point=name, action=fp.kind)
        return self

    def clear(self, name=None):
        """Disarm one point, or all of them (counters survive — the
        chaos suite reads them after the run)."""
        with self._mu:
            if name is None:
                self._points.clear()
            else:
                self._points.pop(name, None)
        ev = self.events
        if ev is not None:
            ev.emit("faults.cleared", point=name or "all")

    # ------------------------------------------------------------- fire

    def fire(self, name):
        """Evaluate the point. May raise FaultError, sleep, or
        ``os._exit``; returns the action name when the site must act
        (``corrupt``), else None."""
        fp = self._points.get(name)
        if fp is None:
            return None
        with self._mu:
            fp.hits += 1
            if fp.hits <= fp.after:
                return None
            if fp.count and fp.fired >= fp.count:
                return None
            if fp.p < 1.0 and self._rand() >= fp.p:
                return None
            fp.fired += 1
            self.triggered_total += 1
            self._triggered_by_point[name] = (
                self._triggered_by_point.get(name, 0) + 1)
            kind, arg = fp.kind, fp.arg
        sp = tracing.active_span()
        if sp is not None:
            sp.tag(fault=name, fault_action=kind)
        if kind == "error":
            raise FaultError(arg, f"injected fault: {name}")
        if kind == "delay":
            self._sleep(arg)
            return "delay"
        if kind == "panic":
            os._exit(arg)
        return kind  # "corrupt": the site mutilates its own bytes

    # ------------------------------------------------------------- read

    def snapshot(self):
        """Rich JSON for GET /debug/faults."""
        with self._mu:
            return {
                "enabled": True,
                "triggeredTotal": self.triggered_total,
                "points": {name: fp.snapshot()
                           for name, fp in self._points.items()},
            }

    def metrics(self):
        """Flat dict for the /metrics ``pilosa_faults_*`` group;
        ``;point:name`` suffixes render as Prometheus labels."""
        with self._mu:
            out = {"triggered_total": self.triggered_total}
            for name, n in self._triggered_by_point.items():
                out[f"triggered_total;point:{name}"] = n
            return out


class NopFaults:
    """Disabled fault injection: sites guard with ``ACTIVE.enabled``
    and never call further — one attribute read, no locks, no
    allocations. The surfaces still answer for /debug/faults."""

    enabled = False

    def fire(self, name):
        return None

    def configure(self, spec):
        raise RuntimeError("fault injection is disabled")

    def clear(self, name=None):
        pass

    def snapshot(self):
        return {"enabled": False}

    def metrics(self):
        return {}


NOP = NopFaults()


def enable(spec=None):
    """Install (or extend) the process-global registry. ``spec`` may
    be None (enabled, nothing armed — the /debug/faults endpoint can
    arm points later), a spec string, or a point->entry dict."""
    global ACTIVE
    if not isinstance(ACTIVE, FaultRegistry):
        ACTIVE = FaultRegistry()
    if spec:
        ACTIVE.configure(spec)
    return ACTIVE


def disable():
    """Back to the nop object (tests restore the default world)."""
    global ACTIVE
    ACTIVE = NOP


def _from_env():
    """Runs at import, so it must NEVER raise: a typo'd spec crashing
    every ``import pilosa_tpu`` (server, CLI, library use) would be
    worse than the missed injection. Falsy values mean OFF; a
    malformed spec warns and stays OFF (fail safe — faults
    accidentally armed are worse than faults silently absent, and the
    config-table path still reports spec errors as a clean startup
    failure via Config.validate)."""
    spec = os.environ.get("PILOSA_FAULTS", "")
    if not spec or spec.lower() in ("0", "false", "no", "off"):
        return NOP
    reg = FaultRegistry()
    if spec.lower() not in ("1", "true", "yes"):
        try:
            reg.configure(spec)
        except ValueError as e:
            import logging

            logging.getLogger("pilosa_tpu.faults").warning(
                "ignoring malformed PILOSA_FAULTS (injection "
                "DISABLED): %s", e)
            return NOP
    return reg


ACTIVE = _from_env()
