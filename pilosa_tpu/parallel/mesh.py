"""Sharded query kernels over a ``jax.sharding.Mesh``.

The reference scales by slicing columns into 2^20-wide slices and
map/reducing per-slice results (SURVEY §5.7): the map is embarrassingly
parallel, the reduce is associative. That maps 1:1 onto SPMD over a
device mesh:

- **slice axis** — the data-parallel dimension: per-slice row bitmaps
  shard as ``uint32[S, W]`` with S split over devices; Count/Sum reduce
  with ``psum`` over ICI (the reference's goroutine-per-node scatter +
  streaming reduce, executor.go:1502-1575).
- **row axis** — a tensor-parallel extension the reference never had
  (rows span all slices there): TopN's ``[S, R, W]`` popcount shards
  rows too, so per-row counts psum over the slice axis only.

Every kernel here is jitted once per (mesh, shape) and reads sharded
device-resident inputs, so multi-chip execution is one XLA program with
collectives — no host round-trips between map and reduce.
"""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pilosa_tpu.ops import bitops

from pilosa_tpu.parallel.compat import shard_map


def make_mesh(n_devices=None, axis="slice"):
    """1-D device mesh over the slice axis."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


# Sharded-count kernels psum int32 partials: exact while the total set
# bits a single reduce can see stays below 2^31. Callers (the mesh
# data plane) decline slice sets wider than this and fall back to the
# host reduce, which sums per-node partials in Python ints.
INT32_SAFE_SLICES = (2 ** 31 - 1) // (1 << 20)


def eval_plan(plan, args, shape):
    """Left-fold tree evaluation over ``uint32[S_blk, W]`` word blocks
    — the mesh twin of ``Executor._eval_node`` (same plan grammar: the
    batched planner's nested op tuples with leaf/planes/bits arg
    positions), duplicated here so ``parallel/`` never imports the
    executor. "bsi" nodes vmap the per-slice BSI descent kernels over
    the slice axis; "empty" is a statically-known-zero result."""
    from pilosa_tpu.ops import bsi as bsi_ops

    kind = plan[0]
    if kind == "leaf":
        return args[plan[1]]
    if kind == "empty":
        return jnp.zeros(shape, jnp.uint32)
    if kind == "bsi":
        _, ppos, bpos, bkind, op, depth = plan
        planes = args[ppos]
        exists = planes[:, depth, :]
        body = planes[:, :depth, :]
        if bkind == "between":
            return jax.vmap(bsi_ops.bsi_between,
                            in_axes=(0, 0, None, None))(
                body, exists, args[bpos[0]], args[bpos[1]])
        fn = {"==": bsi_ops.bsi_eq, "!=": bsi_ops.bsi_neq,
              "<": bsi_ops.bsi_lt, "<=": bsi_ops.bsi_lte,
              ">": bsi_ops.bsi_gt, ">=": bsi_ops.bsi_gte}[op]
        return jax.vmap(fn, in_axes=(0, 0, None))(
            body, exists, args[bpos[0]])
    out = None
    for kid in plan[1]:
        v = eval_plan(kid, args, shape)
        if out is None:
            out = v
        elif kind == "Intersect":
            out = lax.bitwise_and(out, v)
        elif kind == "Union":
            out = lax.bitwise_or(out, v)
        elif kind == "Difference":
            out = lax.bitwise_and(out, lax.bitwise_not(v))
        else:  # Xor
            out = lax.bitwise_xor(out, v)
    return out


class MeshQueryEngine:
    """Sharded map/reduce kernels bound to one mesh.

    Inputs are "slice-major" stacks: axis 0 indexes slices and is
    sharded over the mesh; padding slices (all-zero) are harmless for
    every op here because the reduces are sums/ors.
    """

    # Compiled collective programs are cached per (plan, shapes); each
    # novel shape costs an XLA compile, so the table is bounded like
    # the executor's batched-fn cache.
    TREE_FN_CACHE_MAX = 64

    def __init__(self, mesh=None):
        self.mesh = mesh or make_mesh()
        self.axis = self.mesh.axis_names[0]
        self.n_devices = self.mesh.devices.size
        self._fns = {}  # (kind, plan str, specs, shapes) -> jitted fn
        self._nv = {}   # n_valid -> committed device scalar (reused
        #                 per call: a fresh jnp.int32 would device_put
        #                 a replicated scalar on EVERY query)
        # Monotone build counter: callers diff it for compile-vs-steady
        # attribution — a len(_fns) delta goes blind once the LRU is
        # full (evictions keep the length constant).
        self.compiles = 0

    # ------------------------------------------------------------ layout

    def pad_slices(self, n):
        """Slices must split evenly over devices; round up."""
        d = self.n_devices
        return (n + d - 1) // d * d

    def shard_rows(self, host_rows):
        """np.uint32[S, W] -> device array sharded over the slice axis,
        zero-padded to a multiple of the device count. This is the HBM
        staging step — the analog of fragment open's mmap attach."""
        s = self.pad_slices(host_rows.shape[0])
        if s != host_rows.shape[0]:
            pad = np.zeros((s - host_rows.shape[0],) + host_rows.shape[1:],
                           dtype=host_rows.dtype)
            host_rows = np.concatenate([host_rows, pad])
        sharding = NamedSharding(self.mesh, P(self.axis))
        return jax.device_put(host_rows, sharding)

    # ----------------------------------------------------------- kernels

    @partial(jax.jit, static_argnums=0)
    def count_and(self, a, b):
        """|A ∩ B| over all slices: per-device fused popcount partials,
        one psum over ICI (ref reduce: executor.go:880-889)."""

        def kernel(a_blk, b_blk):
            part = jnp.sum(
                lax.population_count(lax.bitwise_and(a_blk, b_blk))
                .astype(jnp.int32))
            return lax.psum(part, self.axis)

        return shard_map(
            kernel, mesh=self.mesh,
            in_specs=(P(self.axis), P(self.axis)),
            out_specs=P())(a, b)

    @partial(jax.jit, static_argnums=0)
    def count(self, a):
        def kernel(a_blk):
            part = jnp.sum(lax.population_count(a_blk).astype(jnp.int32))
            return lax.psum(part, self.axis)

        return shard_map(kernel, mesh=self.mesh,
                         in_specs=(P(self.axis),), out_specs=P())(a)

    @partial(jax.jit, static_argnums=(0, 2))
    def nary_count(self, rows, op):
        """Count of an n-ary combine: rows uint32[S, K, W], op one of
        'and'/'or'/'xor'/'andnot' folded over K, counted over S×W, psum."""

        def kernel(blk):
            acc = blk[:, 0, :]
            for k in range(1, blk.shape[1]):
                nxt = blk[:, k, :]
                if op == "and":
                    acc = lax.bitwise_and(acc, nxt)
                elif op == "or":
                    acc = lax.bitwise_or(acc, nxt)
                elif op == "xor":
                    acc = lax.bitwise_xor(acc, nxt)
                else:
                    acc = lax.bitwise_and(acc, lax.bitwise_not(nxt))
            part = jnp.sum(lax.population_count(acc).astype(jnp.int32))
            return lax.psum(part, self.axis)

        return shard_map(kernel, mesh=self.mesh,
                         in_specs=(P(self.axis),), out_specs=P())(rows)

    @partial(jax.jit, static_argnums=0)
    def topn_counts(self, matrix):
        """Per-row global counts for TopN: uint32[S, R, W] sharded on S
        -> int32[R] replicated (psum over the slice axis). One fused
        popcount replaces the reference's per-slice cache walks."""

        def kernel(blk):
            part = jnp.sum(
                lax.population_count(blk).astype(jnp.int32), axis=(0, 2))
            return lax.psum(part, self.axis)

        return shard_map(kernel, mesh=self.mesh,
                         in_specs=(P(self.axis),), out_specs=P())(matrix)

    @partial(jax.jit, static_argnums=0)
    def topn_counts_src(self, matrix, src):
        """Per-row counts of row ∩ src: matrix uint32[S, R, W],
        src uint32[S, W] -> int32[R]."""

        def kernel(blk, src_blk):
            inter = lax.bitwise_and(blk, src_blk[:, None, :])
            part = jnp.sum(
                lax.population_count(inter).astype(jnp.int32), axis=(0, 2))
            return lax.psum(part, self.axis)

        return shard_map(kernel, mesh=self.mesh,
                         in_specs=(P(self.axis), P(self.axis)),
                         out_specs=P())(matrix, src)

    @partial(jax.jit, static_argnums=0)
    def bsi_plane_counts(self, planes, filt):
        """BSI Sum map/reduce: planes uint32[S, D, W], filter uint32[S, W]
        -> int32[D] per-plane global counts (host computes Σ 2^i·c_i)."""

        def kernel(planes_blk, filt_blk):
            inter = lax.bitwise_and(planes_blk, filt_blk[:, None, :])
            part = jnp.sum(
                lax.population_count(inter).astype(jnp.int32), axis=(0, 2))
            return lax.psum(part, self.axis)

        return shard_map(kernel, mesh=self.mesh,
                         in_specs=(P(self.axis), P(self.axis)),
                         out_specs=P())(planes, filt)

    @partial(jax.jit, static_argnums=0)
    def union_gather(self, rows):
        """OR-reduce over the slice axis then all_gather — a cross-slice
        row merge materialized on every device (the Bitmap-merge reduce,
        bitmap.go:45-155, as one collective)."""

        def kernel(blk):
            # Unrolled OR fold: XLA:CPU collectives lack OR-reductions,
            # and the per-shard slice count is small and static.
            local = blk[0]
            for i in range(1, blk.shape[0]):
                local = lax.bitwise_or(local, blk[i])
            return lax.all_gather(local, self.axis)

        out = shard_map(kernel, mesh=self.mesh,
                        in_specs=(P(self.axis),), out_specs=P(self.axis))(rows)
        acc = out[0]
        for i in range(1, out.shape[0]):
            acc = bitops.bitmap_or(acc, out[i])
        return acc

    # ------------------------------------------- planned collective cells
    #
    # The mesh data plane (cluster/meshplane.py) compiles a whole query
    # to ONE of these programs: sharded leaf stacks in, a psum'd scalar
    # or small replicated vector out. Padded slices (the device-count
    # round-up) are masked by GLOBAL slice index inside the kernel, so
    # the reduce is bit-exact even when a reused stack's padding lanes
    # hold garbage — zero-fill alone is only safe for sum-of-popcount
    # reduces, and the mask keeps non-sum reduces (thresholded TopN
    # cells, future extrema descents) on the same contract.

    def _slice_mask(self, per_shard, n_valid):
        """bool[per_shard]: True where this shard's global slice index
        is a real (unpadded) slice. Call inside a shard_map kernel."""
        gpos = (lax.axis_index(self.axis).astype(jnp.int32) * per_shard
                + jnp.arange(per_shard, dtype=jnp.int32))
        return gpos < n_valid

    def _tree_fn(self, kind, plan, specs, shapes, build):
        key = (kind, str(plan), tuple(specs), tuple(shapes))
        fn = self._fns.get(key)
        if fn is None:
            while len(self._fns) >= self.TREE_FN_CACHE_MAX:
                self._fns.pop(next(iter(self._fns)))
            fn = self._fns[key] = build()
            self.compiles += 1
        return fn

    def _nv_arg(self, n_valid):
        arr = self._nv.get(n_valid)
        if arr is None:
            if len(self._nv) > 4096:
                self._nv.clear()
            arr = self._nv[n_valid] = jnp.int32(n_valid)
        return arr

    def _in_specs(self, specs):
        return tuple(P(self.axis) if s == "slice" else P()
                     for s in specs)

    def tree_count(self, plan, args, specs, n_valid):
        """|tree| over all real slices as ONE collective program:
        eval_plan fold + per-slice popcount, padded lanes masked, one
        ``psum`` over the slice axis (the reference's streaming count
        reduce, executor.go:880-889, as a single collective). int32
        partials — callers bound n_valid by INT32_SAFE_SLICES."""
        shapes = tuple(a.shape for a in args)
        s_idx = specs.index("slice")
        per = shapes[s_idx][0] // self.n_devices
        width = shapes[s_idx][-1]
        mask_fn = self._slice_mask

        def build():
            def kernel(nv, *blks):
                out = eval_plan(plan, blks, (per, width))
                cnt = jnp.sum(
                    lax.population_count(out).astype(jnp.int32), axis=1)
                part = jnp.sum(jnp.where(mask_fn(per, nv), cnt, 0))
                return lax.psum(part, self.axis)

            return jax.jit(shard_map(
                kernel, mesh=self.mesh,
                in_specs=(P(),) + self._in_specs(specs), out_specs=P()))

        fn = self._tree_fn("count", plan, specs, shapes, build)
        return fn(self._nv_arg(n_valid), *args)

    def topn_tree_counts(self, matrix, src_plan, src_args, specs,
                         n_valid):
        """TopN's exact re-count as one collective: ``matrix``
        uint32[S, R, W] sharded on S, optional src tree folded from
        its own sharded leaf stacks, -> int32[R] replicated global
        counts (psum over the slice axis). The masked padding is what
        makes the per-row counts safe to threshold afterwards: a
        garbage pad lane can neither create nor destroy a candidate."""
        all_args = (matrix,) + tuple(src_args)
        all_specs = ("slice",) + tuple(specs)
        shapes = tuple(a.shape for a in all_args)
        per = matrix.shape[0] // self.n_devices
        width = matrix.shape[-1]
        mask_fn = self._slice_mask

        def build():
            def kernel(nv, blk, *src_blks):
                if src_plan is not None:
                    src = eval_plan(src_plan, src_blks, (per, width))
                    inter = lax.bitwise_and(blk, src[:, None, :])
                else:
                    inter = blk
                cnt = jnp.sum(
                    lax.population_count(inter).astype(jnp.int32),
                    axis=2)                                     # [per, R]
                cnt = jnp.where(mask_fn(per, nv)[:, None], cnt, 0)
                return lax.psum(jnp.sum(cnt, axis=0), self.axis)

            return jax.jit(shard_map(
                kernel, mesh=self.mesh,
                in_specs=(P(),) + self._in_specs(all_specs),
                out_specs=P()))

        fn = self._tree_fn("topn", src_plan, all_specs, shapes, build)
        return fn(self._nv_arg(n_valid), *all_args)

    def bsi_sum_counts(self, planes, filt_plan, filt_args, specs,
                       n_valid):
        """BSI Sum as one collective: planes uint32[S, depth+1, W]
        (plane ``depth`` is the exists row) sharded on S, optional
        filter tree -> int32[depth+1] replicated — per-plane global
        counts followed by the filtered-exists count; the host computes
        Σ 2^i·c_i + base·count in arbitrary-precision ints."""
        depth = planes.shape[1] - 1
        all_args = (planes,) + tuple(filt_args)
        all_specs = ("slice",) + tuple(specs)
        shapes = tuple(a.shape for a in all_args)
        per = planes.shape[0] // self.n_devices
        width = planes.shape[-1]
        mask_fn = self._slice_mask

        def build():
            def kernel(nv, blk, *filt_blks):
                exists = blk[:, depth, :]
                if filt_plan is not None:
                    filt = lax.bitwise_and(
                        exists,
                        eval_plan(filt_plan, filt_blks, (per, width)))
                else:
                    filt = exists
                # Masking the FILTER zeroes every downstream count of
                # a padded slice in one place.
                filt = jnp.where(mask_fn(per, nv)[:, None], filt,
                                 jnp.uint32(0))
                inter = lax.bitwise_and(blk[:, :depth, :],
                                        filt[:, None, :])
                counts = jnp.sum(
                    lax.population_count(inter).astype(jnp.int32),
                    axis=(0, 2))                                # [depth]
                fc = jnp.sum(
                    lax.population_count(filt).astype(jnp.int32))
                return lax.psum(
                    jnp.concatenate([counts, fc[None]]), self.axis)

            return jax.jit(shard_map(
                kernel, mesh=self.mesh,
                in_specs=(P(),) + self._in_specs(all_specs),
                out_specs=P()))

        fn = self._tree_fn("bsi_sum", filt_plan, all_specs, shapes,
                           build)
        return fn(self._nv_arg(n_valid), *all_args)

    def bsi_range_count(self, planes, op, bits, n_valid, hi_bits=None):
        """|columns matching a BSI condition| as one collective — the
        Range-condition reduction cell: vmapped bit-descent per slice,
        masked padding, one psum. ``op`` is a comparison operator or
        "><" with ``hi_bits`` for BETWEEN; ``bits`` / ``hi_bits`` are
        value_to_bits vectors (replicated args)."""
        depth = planes.shape[1] - 1
        if op == "><":
            plan = ("bsi", 0, (1, 2), "between", "", depth)
            args = (planes, bits, hi_bits)
            specs = ("slice", "rep", "rep")
        else:
            plan = ("bsi", 0, (1,), "cmp", op, depth)
            args = (planes, bits)
            specs = ("slice", "rep")
        return self.tree_count(plan, args, specs, n_valid)


def full_query_step(engine, frag_rows, src_rows, planes, filt):
    """One end-to-end multi-chip "step": the flagship distributed query
    mix — Count(Intersect), TopN counts, and BSI Sum — compiled as one
    jitted program over the mesh. Used by the multi-chip dry run.
    """

    @jax.jit
    def step(frag_rows, src_rows, planes, filt):
        c = engine.count_and(src_rows, filt)
        t = engine.topn_counts(frag_rows)
        b = engine.bsi_plane_counts(planes, filt)
        u = engine.union_gather(src_rows)
        return c, t, b, u

    return step(frag_rows, src_rows, planes, filt)
