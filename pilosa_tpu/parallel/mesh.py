"""Sharded query kernels over a ``jax.sharding.Mesh``.

The reference scales by slicing columns into 2^20-wide slices and
map/reducing per-slice results (SURVEY §5.7): the map is embarrassingly
parallel, the reduce is associative. That maps 1:1 onto SPMD over a
device mesh:

- **slice axis** — the data-parallel dimension: per-slice row bitmaps
  shard as ``uint32[S, W]`` with S split over devices; Count/Sum reduce
  with ``psum`` over ICI (the reference's goroutine-per-node scatter +
  streaming reduce, executor.go:1502-1575).
- **row axis** — a tensor-parallel extension the reference never had
  (rows span all slices there): TopN's ``[S, R, W]`` popcount shards
  rows too, so per-row counts psum over the slice axis only.

Every kernel here is jitted once per (mesh, shape) and reads sharded
device-resident inputs, so multi-chip execution is one XLA program with
collectives — no host round-trips between map and reduce.
"""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pilosa_tpu.ops import bitops

from pilosa_tpu.parallel.compat import shard_map


def make_mesh(n_devices=None, axis="slice"):
    """1-D device mesh over the slice axis."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


class MeshQueryEngine:
    """Sharded map/reduce kernels bound to one mesh.

    Inputs are "slice-major" stacks: axis 0 indexes slices and is
    sharded over the mesh; padding slices (all-zero) are harmless for
    every op here because the reduces are sums/ors.
    """

    def __init__(self, mesh=None):
        self.mesh = mesh or make_mesh()
        self.axis = self.mesh.axis_names[0]
        self.n_devices = self.mesh.devices.size

    # ------------------------------------------------------------ layout

    def pad_slices(self, n):
        """Slices must split evenly over devices; round up."""
        d = self.n_devices
        return (n + d - 1) // d * d

    def shard_rows(self, host_rows):
        """np.uint32[S, W] -> device array sharded over the slice axis,
        zero-padded to a multiple of the device count. This is the HBM
        staging step — the analog of fragment open's mmap attach."""
        s = self.pad_slices(host_rows.shape[0])
        if s != host_rows.shape[0]:
            pad = np.zeros((s - host_rows.shape[0],) + host_rows.shape[1:],
                           dtype=host_rows.dtype)
            host_rows = np.concatenate([host_rows, pad])
        sharding = NamedSharding(self.mesh, P(self.axis))
        return jax.device_put(host_rows, sharding)

    # ----------------------------------------------------------- kernels

    @partial(jax.jit, static_argnums=0)
    def count_and(self, a, b):
        """|A ∩ B| over all slices: per-device fused popcount partials,
        one psum over ICI (ref reduce: executor.go:880-889)."""

        def kernel(a_blk, b_blk):
            part = jnp.sum(
                lax.population_count(lax.bitwise_and(a_blk, b_blk))
                .astype(jnp.int32))
            return lax.psum(part, self.axis)

        return shard_map(
            kernel, mesh=self.mesh,
            in_specs=(P(self.axis), P(self.axis)),
            out_specs=P())(a, b)

    @partial(jax.jit, static_argnums=0)
    def count(self, a):
        def kernel(a_blk):
            part = jnp.sum(lax.population_count(a_blk).astype(jnp.int32))
            return lax.psum(part, self.axis)

        return shard_map(kernel, mesh=self.mesh,
                         in_specs=(P(self.axis),), out_specs=P())(a)

    @partial(jax.jit, static_argnums=(0, 2))
    def nary_count(self, rows, op):
        """Count of an n-ary combine: rows uint32[S, K, W], op one of
        'and'/'or'/'xor'/'andnot' folded over K, counted over S×W, psum."""

        def kernel(blk):
            acc = blk[:, 0, :]
            for k in range(1, blk.shape[1]):
                nxt = blk[:, k, :]
                if op == "and":
                    acc = lax.bitwise_and(acc, nxt)
                elif op == "or":
                    acc = lax.bitwise_or(acc, nxt)
                elif op == "xor":
                    acc = lax.bitwise_xor(acc, nxt)
                else:
                    acc = lax.bitwise_and(acc, lax.bitwise_not(nxt))
            part = jnp.sum(lax.population_count(acc).astype(jnp.int32))
            return lax.psum(part, self.axis)

        return shard_map(kernel, mesh=self.mesh,
                         in_specs=(P(self.axis),), out_specs=P())(rows)

    @partial(jax.jit, static_argnums=0)
    def topn_counts(self, matrix):
        """Per-row global counts for TopN: uint32[S, R, W] sharded on S
        -> int32[R] replicated (psum over the slice axis). One fused
        popcount replaces the reference's per-slice cache walks."""

        def kernel(blk):
            part = jnp.sum(
                lax.population_count(blk).astype(jnp.int32), axis=(0, 2))
            return lax.psum(part, self.axis)

        return shard_map(kernel, mesh=self.mesh,
                         in_specs=(P(self.axis),), out_specs=P())(matrix)

    @partial(jax.jit, static_argnums=0)
    def topn_counts_src(self, matrix, src):
        """Per-row counts of row ∩ src: matrix uint32[S, R, W],
        src uint32[S, W] -> int32[R]."""

        def kernel(blk, src_blk):
            inter = lax.bitwise_and(blk, src_blk[:, None, :])
            part = jnp.sum(
                lax.population_count(inter).astype(jnp.int32), axis=(0, 2))
            return lax.psum(part, self.axis)

        return shard_map(kernel, mesh=self.mesh,
                         in_specs=(P(self.axis), P(self.axis)),
                         out_specs=P())(matrix, src)

    @partial(jax.jit, static_argnums=0)
    def bsi_plane_counts(self, planes, filt):
        """BSI Sum map/reduce: planes uint32[S, D, W], filter uint32[S, W]
        -> int32[D] per-plane global counts (host computes Σ 2^i·c_i)."""

        def kernel(planes_blk, filt_blk):
            inter = lax.bitwise_and(planes_blk, filt_blk[:, None, :])
            part = jnp.sum(
                lax.population_count(inter).astype(jnp.int32), axis=(0, 2))
            return lax.psum(part, self.axis)

        return shard_map(kernel, mesh=self.mesh,
                         in_specs=(P(self.axis), P(self.axis)),
                         out_specs=P())(planes, filt)

    @partial(jax.jit, static_argnums=0)
    def union_gather(self, rows):
        """OR-reduce over the slice axis then all_gather — a cross-slice
        row merge materialized on every device (the Bitmap-merge reduce,
        bitmap.go:45-155, as one collective)."""

        def kernel(blk):
            # Unrolled OR fold: XLA:CPU collectives lack OR-reductions,
            # and the per-shard slice count is small and static.
            local = blk[0]
            for i in range(1, blk.shape[0]):
                local = lax.bitwise_or(local, blk[i])
            return lax.all_gather(local, self.axis)

        out = shard_map(kernel, mesh=self.mesh,
                        in_specs=(P(self.axis),), out_specs=P(self.axis))(rows)
        acc = out[0]
        for i in range(1, out.shape[0]):
            acc = bitops.bitmap_or(acc, out[i])
        return acc


def full_query_step(engine, frag_rows, src_rows, planes, filt):
    """One end-to-end multi-chip "step": the flagship distributed query
    mix — Count(Intersect), TopN counts, and BSI Sum — compiled as one
    jitted program over the mesh. Used by the multi-chip dry run.
    """

    @jax.jit
    def step(frag_rows, src_rows, planes, filt):
        c = engine.count_and(src_rows, filt)
        t = engine.topn_counts(frag_rows)
        b = engine.bsi_plane_counts(planes, filt)
        u = engine.union_gather(src_rows)
        return c, t, b, u

    return step(frag_rows, src_rows, planes, filt)
