"""Device-mesh parallelism: slice sharding + XLA collectives.

The TPU replacement for the reference's scatter/gather distribution
plane (executor.go:1444-1575 map/reduce over HTTP, broadcast.go,
gossip/): within a host, slices shard over the TPU mesh via
``shard_map`` and reduce with ``psum``/``all_gather`` over ICI;
across hosts the executor's HTTP fan-out (cluster/) still applies,
mirroring the reference's two-level design (ICI ≈ intra-cluster fan-out,
DCN/HTTP ≈ cross-pod).
"""
from pilosa_tpu.parallel.mesh import (  # noqa: F401
    MeshQueryEngine,
    make_mesh,
)
