"""Multi-host distribution: process topology, replica groups, and the
global-mesh staging path.

The reference scales across machines with HTTP scatter/gather plus
synchronous replica write fan-out and anti-entropy repair (SURVEY §2.10,
executor.go:1444-1535, fragment.go:1703). The TPU-native equivalents:

- **inside one pod** — slices shard over chips; map/reduce is a single
  XLA program with ``psum`` over ICI (parallel/mesh.py).
- **across hosts of one pod** — ``jax.distributed.initialize`` forms one
  global device set; arrays are assembled from per-process local shards
  (:func:`stage_process_local`), and the same shard_map kernels run SPMD
  with collectives routed over ICI within the pod slice owned by each
  host.
- **across pods / replica sets (DCN)** — a second, outer mesh axis
  carries ReplicaN copies of every slice block. Queries psum only over
  the slice axis (replicas hold identical data, so each replica computes
  the full answer redundantly — the fault-tolerance trade the reference
  makes with its successor-node replicas, cluster.go:250-271);
  :meth:`ReplicaMeshEngine.replica_digest` is the on-device anti-entropy
  probe: per-replica content digests compared host-side to trigger the
  block-level repair pass (cluster/syncer.py).

Process-level *ownership* (which host's storage holds which slice)
stays on the jump-hash placement in cluster/cluster.py so host HTTP
ownership and device sharding agree (SURVEY §7 "mesh distribution").
"""
import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pilosa_tpu.parallel.compat import UNCHECKED, shard_map

REPLICA_AXIS = "replica"
SLICE_AXIS = "slice"


def init_distributed(coordinator=None, num_processes=None, process_id=None):
    """Join the JAX distributed runtime (multi-host pods).

    No-op for single-process runs (the common dev / single-VM case).
    Reads ``PILOSA_COORDINATOR`` / ``PILOSA_NUM_PROCESSES`` /
    ``PILOSA_PROCESS_ID`` when args are omitted — the TPU-native analog
    of the reference's gossip seed-join config (config.go gossip.seed).
    """
    coordinator = coordinator or os.environ.get("PILOSA_COORDINATOR")
    if not coordinator:
        return False
    if num_processes is None:
        num_processes = int(os.environ.get("PILOSA_NUM_PROCESSES", "1"))
    if process_id is None:  # NOT `or`: 0 is a valid explicit id
        process_id = int(os.environ.get("PILOSA_PROCESS_ID", "0"))
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


def make_replica_mesh(replica_n=1, n_devices=None):
    """2-D mesh ``(replica, slice)``: the outer axis carries ReplicaN
    data copies (across pods → DCN), the inner axis shards slices
    (within a pod → ICI). With replica_n=1 this degenerates to the
    1-D slice mesh."""
    devices = np.asarray(jax.devices())
    if n_devices is not None:
        devices = devices[:n_devices]
    if devices.size % replica_n:
        raise ValueError(
            f"{devices.size} devices not divisible by replica_n={replica_n}")
    grid = devices.reshape(replica_n, devices.size // replica_n)
    return Mesh(grid, (REPLICA_AXIS, SLICE_AXIS))


def process_slice_range(n_slices, mesh):
    """[lo, hi) of the global slice-stack rows this process's local
    devices own under ``P(slice)`` sharding — what the storage layer
    must stage locally. Contiguous because mesh device order is
    process-major within each replica row."""
    axis = mesh.shape[SLICE_AXIS] if SLICE_AXIS in mesh.shape else mesh.devices.size
    per_dev = (n_slices + axis - 1) // axis
    local_ids = [d.id for d in mesh.local_devices]
    cols = []
    flat = mesh.devices.reshape(-1, axis)
    for r in range(flat.shape[0]):
        for c in range(axis):
            if flat[r, c].id in local_ids:
                cols.append(c)
    if not cols:
        return 0, 0
    return min(cols) * per_dev, min((max(cols) + 1) * per_dev, n_slices)


def stage_process_local(local_rows, global_shape, mesh,
                        spec=P(SLICE_AXIS)):
    """Assemble a global sharded array from this process's local shard
    data (np.uint32). Single-process: a plain device_put. Multi-host:
    ``jax.make_array_from_process_local_data`` — each host contributes
    only the slices it owns; no host ever materializes the global
    array (the analog of each node mmapping only its own fragments).
    """
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(np.ascontiguousarray(local_rows), sharding)
    return jax.make_array_from_process_local_data(
        sharding, np.ascontiguousarray(local_rows), global_shape)


class ReplicaMeshEngine:
    """Sharded kernels over a ``(replica, slice)`` mesh.

    Data layout: every replica row of the mesh holds an identical copy
    of the slice-sharded stack (``P(None, 'slice')`` on the slice axis
    of the array — replicas are *not* a sharded array dimension, they
    are redundant copies, matching the reference where each replica
    node stores full fragments, not halves).
    """

    def __init__(self, mesh):
        if mesh.axis_names != (REPLICA_AXIS, SLICE_AXIS):
            raise ValueError(f"want (replica, slice) mesh, got {mesh.axis_names}")
        self.mesh = mesh
        self.replica_n = mesh.shape[REPLICA_AXIS]
        self.slice_devices = mesh.shape[SLICE_AXIS]

    def pad_slices(self, n):
        d = self.slice_devices
        return (n + d - 1) // d * d

    def shard_rows(self, host_rows):
        """np.uint32[S, W] -> sharded on slice axis, replicated over the
        replica axis (each replica group gets a full copy over DCN)."""
        s = self.pad_slices(host_rows.shape[0])
        if s != host_rows.shape[0]:
            pad = np.zeros((s - host_rows.shape[0],) + host_rows.shape[1:],
                           dtype=host_rows.dtype)
            host_rows = np.concatenate([host_rows, pad])
        return jax.device_put(
            host_rows, NamedSharding(self.mesh, P(SLICE_AXIS)))

    # ----------------------------------------------------------- kernels

    @partial(jax.jit, static_argnums=0)
    def count_and(self, a, b):
        """|A ∩ B|: psum over the slice axis only — every replica group
        computes the full count independently (redundant execution =
        failure tolerance; the first replica's answer is returned)."""

        def kernel(a_blk, b_blk):
            part = jnp.sum(
                lax.population_count(lax.bitwise_and(a_blk, b_blk))
                .astype(jnp.int32))
            return lax.psum(part, SLICE_AXIS)

        return shard_map(
            kernel, mesh=self.mesh,
            in_specs=(P(SLICE_AXIS), P(SLICE_AXIS)),
            out_specs=P())(a, b)

    @partial(jax.jit, static_argnums=0)
    def topn_counts(self, matrix):
        def kernel(blk):
            part = jnp.sum(
                lax.population_count(blk).astype(jnp.int32), axis=(0, 2))
            return lax.psum(part, SLICE_AXIS)

        return shard_map(kernel, mesh=self.mesh,
                         in_specs=(P(SLICE_AXIS),), out_specs=P())(matrix)

    @partial(jax.jit, static_argnums=0)
    def replica_digest(self, rows):
        """Anti-entropy probe: per-replica 64-bit-ish content digest of
        the full slice stack, all_gathered over the replica axis so the
        host can compare copies without pulling data (the on-device
        analog of FragmentSyncer's block-checksum exchange,
        fragment.go:1703-1771). Digest = psum over slices of a
        position-salted word mix — associative, order-independent."""

        def kernel(blk):
            # Position-salted mix summed with uint32 wrap-around: mod-2^32
            # sums are associative, so the digest is independent of the
            # psum reduction order. Salting by global position makes
            # "same words, different slice" collisions unlikely.
            idx = jnp.arange(blk.size, dtype=jnp.uint32).reshape(blk.shape)
            base = lax.axis_index(SLICE_AXIS).astype(jnp.uint32)
            mixed = blk ^ ((idx + base * jnp.uint32(blk.size))
                           * jnp.uint32(2654435761))
            local = lax.psum(jnp.sum(mixed), SLICE_AXIS)
            return lax.all_gather(local, REPLICA_AXIS)

        # Replication checking off (compat.UNCHECKED spells the kwarg
        # for this JAX version): after the all_gather every device
        # holds the same [replica_n] vector, but varying-mesh-axis
        # inference can't prove replica-invariance statically.
        return shard_map(kernel, mesh=self.mesh,
                         in_specs=(P(SLICE_AXIS),),
                         out_specs=P(), **UNCHECKED)(rows)

    def replicas_consistent(self, rows):
        """Host-side check: True when all replica copies digest equal."""
        d = np.asarray(self.replica_digest(rows))
        return bool((d == d[0]).all())
