"""JAX version-skew shims for the parallel tier.

``shard_map`` moved from ``jax.experimental.shard_map`` to ``jax``
proper, and its replication-check kwarg was renamed along the way
(``check_rep`` → ``check_vma``). Both suites (single-process mesh and
multi-host) must collect and pass on whatever JAX the image pins, so
the ONE copy of that dance lives here: import ``shard_map`` from this
module and splat ``UNCHECKED`` where a kernel's output replication
can't be proven statically (e.g. an all_gather the varying-mesh-axis
inference can't see through).
"""
import inspect

try:  # JAX >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - version-dependent
    from jax.experimental.shard_map import shard_map

# The kwarg that disables static replication checking, under whatever
# name this JAX spells it. Empty if the signature exposes neither
# (inspection failure included): the call then runs fully checked,
# which is correct — just stricter.
UNCHECKED = {}
try:
    _params = inspect.signature(shard_map).parameters
    for _name in ("check_vma", "check_rep"):
        if _name in _params:
            UNCHECKED = {_name: False}
            break
except (TypeError, ValueError):  # pragma: no cover - exotic builds
    pass
