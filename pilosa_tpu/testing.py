"""Public test harness (ref: the reference's importable ``test/``
package, SURVEY layer X3 — test/holder.go, test/cluster.go,
test/pilosa.go).

Gives downstream users the same fixtures the reference ships:
temp-dir-backed storage objects with ``reopen()`` for persistence
tests, fake clusters with deterministic placement hashers, and real
in-process multi-node server clusters.

    from pilosa_tpu.testing import TestHolder, ServerCluster

    with TestHolder() as h:
        idx = h.create_index("i")
        ...
        h.reopen()          # persistence round-trip

    with ServerCluster(3, replica_n=2) as servers:
        ...                 # three real HTTP servers, static membership
"""
import shutil
import socket
import tempfile

from pilosa_tpu.cluster.cluster import (  # noqa: F401 — re-exported seams
    ConstHasher,
    ModHasher,
    new_test_cluster,
)
from pilosa_tpu.storage.fragment import Fragment
from pilosa_tpu.storage.holder import Holder


def free_ports(n):
    """OS-assigned ports for in-process servers (ref: test/pilosa.go:66)."""
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("localhost", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


class TestHolder(Holder):
    """Holder on a fresh temp dir with ``reopen()``
    (ref: test/holder.go:26-120)."""

    def __init__(self, path=None):
        self._tmp = None
        if path is None:
            self._tmp = tempfile.mkdtemp(prefix="pilosa-tpu-test-")
            path = self._tmp
        super().__init__(path)
        try:
            self.open()
        except BaseException:
            if self._tmp:
                shutil.rmtree(self._tmp, ignore_errors=True)
            raise

    def reopen(self):
        """Close and reopen from disk — the persistence test seam."""
        self.close()
        super().open()
        return self

    def cleanup(self):
        self.close()
        if self._tmp:
            shutil.rmtree(self._tmp, ignore_errors=True)
            self._tmp = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.cleanup()


class TestFragment(Fragment):
    """Fragment on a temp file with ``reopen()``
    (ref: test/fragment.go)."""

    def __init__(self, index="i", frame="f", view="standard", slice_num=0,
                 **kwargs):
        self._tmp = tempfile.mkdtemp(prefix="pilosa-tpu-frag-")
        super().__init__(f"{self._tmp}/fragment", index, frame, view,
                         slice_num, **kwargs)
        try:
            self.open()
        except BaseException:
            shutil.rmtree(self._tmp, ignore_errors=True)
            raise

    def reopen(self):
        self.close()
        super().open()
        return self

    def cleanup(self):
        self.close()
        if self._tmp:
            shutil.rmtree(self._tmp, ignore_errors=True)
            self._tmp = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.cleanup()


class ServerCluster:
    """N real servers in one process joined by static membership
    (ref: test.NewServerCluster test/pilosa.go:41-63)."""

    def __init__(self, n, replica_n=1, anti_entropy_interval=0,
                 polling_interval=0, base_path=None, **server_kwargs):
        from pilosa_tpu.server.server import Server

        self._tmp = None
        if base_path is None:
            self._tmp = tempfile.mkdtemp(prefix="pilosa-tpu-cluster-")
            base_path = self._tmp
        # free_ports is a TOCTOU window (probe sockets close before the
        # servers bind) — redraw and retry on a stolen port, and never
        # leak already-opened servers on failure.
        last_err = None
        for attempt in range(3):
            ports = free_ports(n)
            self.hosts = [f"localhost:{p}" for p in ports]
            self.servers = []
            try:
                for i in range(n):
                    self.servers.append(
                        Server(f"{base_path}/node{i}-{attempt}",
                               bind=self.hosts[i],
                               cluster_hosts=self.hosts,
                               replica_n=replica_n,
                               anti_entropy_interval=anti_entropy_interval,
                               polling_interval=polling_interval,
                               **server_kwargs).open())
                return
            except OSError as e:
                last_err = e
                for srv in self.servers:
                    srv.close()
            except BaseException:
                for srv in self.servers:
                    srv.close()
                if self._tmp:
                    shutil.rmtree(self._tmp, ignore_errors=True)
                raise
        if self._tmp:
            shutil.rmtree(self._tmp, ignore_errors=True)
        raise last_err

    def __getitem__(self, i):
        return self.servers[i]

    def __iter__(self):
        return iter(self.servers)

    def __len__(self):
        return len(self.servers)

    def close(self):
        for s in self.servers:
            s.close()
        if self._tmp:
            shutil.rmtree(self._tmp, ignore_errors=True)
            self._tmp = None

    def __enter__(self):
        return self.servers

    def __exit__(self, *exc):
        self.close()


def must_parse(pql):
    """Parse PQL or raise (ref: test/executor.go:49 MustParse)."""
    from pilosa_tpu.pql import parse

    return parse(pql)
