"""Heat-driven autopilot — a closed-loop controller that operates the
cluster itself (ROADMAP open item 3).

Every prior tier assumed a human in the loop: the observatory (PR 13)
measures slice heat and SLO burn, the flight recorder + replica
vitals (PR 16) journal transitions and detect degraded replicas, the
rebalancer (PR 10) can move any slice safely — but an operator reads
``/debug/heatmap`` and POSTs ``/cluster/resize``. This module closes
the loop: it SENSES through the existing observe surfaces and ACTS
through the existing safe actuators, never inventing a new mutation
path of its own. Three control loops, each independently gated:

- **placement** — cluster-merged decayed slice heat (heatmap fan-out)
  plus per-replica vitals/healthScore yield a per-host effective load
  (a degraded host has half the capacity its heat share implies).
  When the hottest host exceeds ``heat_imbalance`` times the mean,
  the planner searches host-order permutations of the pinned
  generation (the jump hash is order-sensitive, so reordering IS the
  placement lever) and drives ``rebalancer.resize`` toward the best
  one. Per-slice widen/narrow replication targets ride along as plan
  evidence and are realized in memory by the tiering loop.
- **memory** — pre-stage hot slices by refreshing their fragments'
  LRU stamps (the governor then never picks them as victims) and
  demote the coldest resident fragments *before* the governor is
  forced to evict: above ``memory_headroom`` of budget, a bounded
  batch of cold fragments is unloaded to the durable tier.
- **slo** — page/ticket burn advisories (observe/slo.py) and
  ``replica.degraded`` watchdog verdicts become bounded actions: one
  admission-gate tighten step per episode, widened back on recovery;
  degraded hosts feed the placement loop's capacity weighting.

Safety is structural, not aspirational: every decision journals into
the flight recorder (``autopilot.plan/apply/abort/cooldown``) with
its sensor evidence inline; every action passes a per-loop min-dwell
AND a windowed action budget (a failed action RELEASES its budget
token — failures must not starve the recovery that fixes them); a
dry-run surface (``POST /cluster/autopilot/plan``) returns the plan
without executing; and the kill switch (``disable()``, config reload,
or server close) aborts mid-flight work cleanly — the rebalancer's
own abort path guarantees placement is never left mid-transition.

Hot-path cost when disabled: zero — the NOP tier is never spawned as
a monitor and the handler reads one ``enabled`` attribute.
"""
import collections
import threading
import time

from pilosa_tpu import faults
from pilosa_tpu import lockcheck

LOOPS = ("placement", "memory", "slo")

PLAN_HISTORY = 8     # last plans kept for /debug/autopilot
PRESTAGE_TOP = 8     # hot slices pinned into the LRU per action
DEMOTE_BATCH = 8     # cold fragments demoted per action (bounded)
MIN_HEALTH = 0.25    # capacity floor for health-weighted load
RELIEF = 0.9         # a permutation must cut imbalance >= 10%
EVIDENCE_SLICES = 3  # top slices inlined into journal evidence
SCRAPE_TIMEOUT = 2.0  # per-peer heatmap scrape budget (seconds)


class AutopilotDisabled(RuntimeError):
    """Raised inside apply when the kill switch flips mid-flight."""


class Autopilot:
    """The enabled controller tier. Sensors and actuators are
    attributes installed by the server's wiring block (None = that
    surface is absent and the loop that needs it stands down)."""

    enabled = True

    def __init__(self, local_host=None, interval=5.0, dry_run=False,
                 placement_loop=True, memory_loop=True, slo_loop=True,
                 min_dwell=60.0, max_actions_per_window=2,
                 window=300.0, heat_imbalance=1.5,
                 memory_headroom=0.85, clock=time.monotonic):
        self.local_host = local_host
        self.interval = float(interval)
        self.dry_run = bool(dry_run)
        self.placement_loop = bool(placement_loop)
        self.memory_loop = bool(memory_loop)
        self.slo_loop = bool(slo_loop)
        self.min_dwell = float(min_dwell)
        self.max_actions_per_window = int(max_actions_per_window)
        self.window = float(window)
        self.heat_imbalance = float(heat_imbalance)
        self.memory_headroom = float(memory_headroom)
        self._clock = clock
        # Sensor / actuator sockets, server-installed.
        self.cluster = None      # cluster.Cluster (topology + hasher)
        self.rebalancer = None   # the only placement actuator
        self.client = None       # InternalClient (heatmap scrape legs)
        self.qos = None          # admission-gate step actuator
        self.vitals = None       # replica vitals (health weighting)
        self.slo = None          # SLO tracker (burn advisories)
        self.governor = None     # host-memory governor (tiering)
        self.heat_fn = None      # () -> local heatmap snapshot
        # Flight recorder (observe.events), server-installed; None
        # when off. Emits happen OUTSIDE _mu — events is a leaf.
        self.events = None
        self._mu = lockcheck.register("autopilot.Autopilot._mu",
                                      threading.Lock())
        # Kill switch: Event, not a flag under _mu — apply checks it
        # mid-flight without taking the controller lock.
        self._stop = threading.Event()
        self._actions = collections.deque()   # token timestamps in window
        self._last_action = {}                # loop -> last applied ts
        self._last_hot = frozenset()          # last pre-staged hot set
        self._plans = collections.deque(maxlen=PLAN_HISTORY)
        self._last_plan = None
        self.plans_total = 0
        self.plan_errors_total = 0
        self.aborts_total = 0
        self.cooldown_blocked_total = 0
        self.actions_total = {loop: 0 for loop in LOOPS}

    # ------------------------------------------------------------ journal

    def _emit(self, kind, **fields):
        ev = self.events
        if ev is not None:
            ev.emit(kind, **fields)

    # ------------------------------------------------------------ sensors

    def _sense_heat(self):
        """Cluster-merged decayed slice heat: the local table plus
        every peer's /debug/heatmap JSON. Breaker-open peers are
        skipped and per-peer scrape failures degrade the merge to the
        reachable views — the controller plans on what it can see."""
        from pilosa_tpu.observe import heatmap as heatmap_mod
        local = (self.heat_fn() if self.heat_fn is not None
                 else heatmap_mod.ACTIVE.snapshot())
        host = self.local_host or ""
        per_node = {host: local}
        errors = {}
        cluster, client = self.cluster, self.client
        if cluster is not None and client is not None:
            brk = getattr(client, "breakers", None)
            for node in cluster.nodes:
                if node.host == host:
                    continue
                if brk is not None and brk.is_open(node.host):
                    errors[node.host] = "breaker open"
                    continue
                try:
                    per_node[node.host] = client.heatmap_json(
                        node, timeout=SCRAPE_TIMEOUT)
                except Exception as e:
                    errors[node.host] = str(e) or type(e).__name__
        merged = heatmap_mod.merge_snapshots(per_node)
        merged["errors"] = errors
        return merged

    def sense(self):
        """One consistent sensor sweep: merged heat, per-peer health,
        SLO advisories, governor pressure — the evidence every plan
        journals."""
        vitals, slo, gov = self.vitals, self.slo, self.governor
        mem = None
        if gov is not None:
            p = gov.pressure()
            mem = {"pressure": None if p is None else round(p, 4),
                   "residentBytes": gov.resident_bytes(),
                   "budgetBytes": gov.budget or 0}
        return {
            "heat": self._sense_heat(),
            "health": (vitals.health_by_peer()
                       if vitals is not None else {}),
            "advisories": slo.advisories() if slo is not None else {},
            "memory": mem,
        }

    # ----------------------------------------------------------- planners

    def _host_loads(self, hosts, slices, health):
        """Per-host EFFECTIVE heat load under a candidate ordered host
        list: primary-owner heat divided by healthScore capacity (a
        degraded peer at 0.5 carries its heat as double load)."""
        from pilosa_tpu.cluster.placement import PlacementMap
        cluster = self.cluster
        loads = {h: 0.0 for h in hosts}
        for ent in slices:
            pid = cluster.partition(ent["index"], ent["slice"])
            owners = PlacementMap.preview_owners(
                hosts, pid, cluster.replica_n, cluster.hasher)
            if owners:
                loads[owners[0]] += ent.get("heat") or 0.0
        out = {}
        for h in hosts:
            score = (health.get(h) or {}).get("healthScore", 1.0)
            out[h] = loads[h] / max(MIN_HEALTH, score)
        return out

    def _replication_targets(self, slices, n_hosts):
        """Advisory widen/narrow targets journaled as plan evidence:
        hot slices want replica_n+1 (realized in memory by the tiering
        loop's pre-stage), the cold tail of the top-K wants 1."""
        cluster = self.cluster
        base = cluster.replica_n if cluster is not None else 1
        hot = slices[:EVIDENCE_SLICES]
        cold = slices[PRESTAGE_TOP:][-EVIDENCE_SLICES:]
        return {
            "widen": [{"slice": f'{e["index"]}/{e["slice"]}',
                       "target": min(base + 1, n_hosts)} for e in hot],
            "narrow": [{"slice": f'{e["index"]}/{e["slice"]}',
                        "target": 1} for e in cold],
        }

    def _plan_placement(self, sensed):
        cluster, reb = self.cluster, self.rebalancer
        if cluster is None or reb is None or reb.is_running():
            return None
        pl = cluster.placement
        if pl.active and pl.phase != "stable":
            return None  # never stack onto an in-flight resize
        hosts = (list(pl.current_hosts()) if pl.active
                 else [n.host for n in cluster.nodes])
        slices = sensed["heat"].get("slices") or []
        if len(hosts) < 2 or not slices:
            return None
        health = sensed["health"]
        cur = self._host_loads(hosts, slices, health)
        mean = sum(cur.values()) / len(cur)
        if mean <= 0:
            return None
        imbalance = max(cur.values()) / mean
        if imbalance < self.heat_imbalance:
            return None
        # The placement lever is the generation's host ORDER (jump
        # hash walks it): search all single swaps for the best relief.
        best, best_score = None, imbalance
        for i in range(len(hosts)):
            for j in range(i + 1, len(hosts)):
                cand = list(hosts)
                cand[i], cand[j] = cand[j], cand[i]
                loads = self._host_loads(cand, slices, health)
                score = max(loads.values()) / mean
                if score < best_score - 1e-9:
                    best, best_score = cand, score
        if best is None or best_score > imbalance * RELIEF:
            return None
        degraded = sorted(h for h, st in health.items()
                          if st.get("degraded"))
        return {
            "loop": "placement", "kind": "rebalance", "hosts": best,
            "evidence": {
                "imbalance": round(imbalance, 3),
                "projected": round(best_score, 3),
                "hottestHost": max(cur, key=cur.get),
                "loads": {h: round(v, 3) for h, v in cur.items()},
                "degraded": degraded,
                "topSlices": slices[:EVIDENCE_SLICES],
                "replication": self._replication_targets(
                    slices, len(hosts)),
            },
        }

    def _plan_memory(self, sensed):
        gov = self.governor
        if gov is None:
            return None
        slices = sensed["heat"].get("slices") or []
        hot = frozenset((e["index"], e["slice"])
                        for e in slices[:PRESTAGE_TOP])
        mem = sensed.get("memory") or {}
        pressure = mem.get("pressure")
        demote = []
        if pressure is not None and pressure >= self.memory_headroom:
            demote = [f"{f.index}/{f.frame}/{f.view}/{f.slice}"
                      for f in gov.coldest(DEMOTE_BATCH, hot=hot)]
        prestage = hot if hot != self._last_hot else frozenset()
        if not demote and not prestage:
            return None
        return {
            "loop": "memory", "kind": "tier",
            "prestage": sorted(f"{i}/{s}" for i, s in prestage),
            "demote": demote,
            "evidence": {"pressure": pressure,
                         "residentBytes": mem.get("residentBytes"),
                         "budgetBytes": mem.get("budgetBytes"),
                         "hotSlices": len(hot)},
            "_hot": hot,
        }

    def _plan_slo(self, sensed):
        qos = self.qos
        if qos is None or not getattr(qos, "enabled", False):
            return None
        adv = sensed.get("advisories") or {}
        worst = "ok"
        for level in adv.values():
            if level == "page":
                worst = "page"
                break
            if level == "ticket":
                worst = "ticket"
        degraded = sorted(h for h, st in sensed["health"].items()
                          if st.get("degraded"))
        direction = 0
        if worst in ("page", "ticket") or degraded:
            direction = -1
        elif qos.gate.max_concurrent < qos.base_concurrency:
            direction = 1   # recovery: widen back toward the baseline
        if direction == 0:
            return None
        new = qos.preview_concurrency(direction)
        if new is None:
            return None  # already at the bound for that direction
        kind = "qos_tighten" if direction < 0 else "qos_widen"
        return {
            "loop": "slo", "kind": kind, "direction": direction,
            "maxConcurrent": new,
            "evidence": {"advisories": adv, "degraded": degraded,
                         "current": qos.gate.max_concurrent,
                         "baseline": qos.base_concurrency},
        }

    # --------------------------------------------------------------- plan

    def plan(self):
        """Compute the action plan from the current sensors WITHOUT
        executing it — the ``POST /cluster/autopilot/plan`` dry-run
        surface; ``tick()`` runs the same plan and then applies it.
        Plans with actions journal ``autopilot.plan`` with evidence;
        empty plans only count (a 5s cadence would flood the journal
        otherwise)."""
        if faults.ACTIVE.enabled:
            faults.ACTIVE.fire("autopilot.plan.error")
        sensed = self.sense()
        actions = []
        for on, planner in ((self.placement_loop, self._plan_placement),
                            (self.memory_loop, self._plan_memory),
                            (self.slo_loop, self._plan_slo)):
            if on:
                action = planner(sensed)
                if action is not None:
                    actions.append(action)
        now = self._clock()
        plan = {
            "ts": time.time(),
            "dryRun": self.dry_run,
            "actions": [{k: v for k, v in a.items()
                         if not k.startswith("_")} for a in actions],
            "budgetRemaining": self._budget_remaining(now),
            "sensors": {
                "advisories": sensed["advisories"],
                "memory": sensed["memory"],
                "heatErrors": sensed["heat"].get("errors") or {},
                "topSlices": (sensed["heat"].get("slices")
                              or [])[:EVIDENCE_SLICES],
            },
        }
        plan["_actions"] = actions   # internal: carries _hot etc.
        with self._mu:
            self.plans_total += 1
            self._last_plan = {k: v for k, v in plan.items()
                               if not k.startswith("_")}
            if actions:
                self._plans.append(self._last_plan)
        if actions:
            self._emit("autopilot.plan", actions=len(actions),
                       kinds=[a["kind"] for a in actions],
                       dryRun=self.dry_run,
                       evidence=[a["evidence"] for a in actions])
        return plan

    # -------------------------------------------------------------- apply

    def _budget_remaining(self, now):
        with self._mu:
            self._prune_locked(now)
            return max(0,
                       self.max_actions_per_window - len(self._actions))

    def _prune_locked(self, now):
        while self._actions and now - self._actions[0] > self.window:
            self._actions.popleft()

    def _gate(self, loop, now):
        """Take a cooldown token for one action, or return the reason
        it is blocked. Caller releases the token on failure."""
        with self._mu:
            if self._stop.is_set():
                return "autopilot disabled"
            self._prune_locked(now)
            last = self._last_action.get(loop)
            if last is not None and now - last < self.min_dwell:
                return (f"dwell: {self.min_dwell - (now - last):.1f}s "
                        f"remaining for loop {loop}")
            if len(self._actions) >= self.max_actions_per_window:
                return (f"action budget exhausted "
                        f"({self.max_actions_per_window} per "
                        f"{self.window:.0f}s window)")
            self._actions.append(now)
            self._last_action[loop] = now
            return None

    def _release(self, loop, now, prev_last):
        """A failed/aborted action must not consume budget: give the
        token back and restore the loop's dwell clock."""
        with self._mu:
            if now in self._actions:
                self._actions.remove(now)
            if self._last_action.get(loop) == now:
                if prev_last is None:
                    del self._last_action[loop]
                else:
                    self._last_action[loop] = prev_last

    def _actuate(self, action):
        """Dispatch one gated action to its actuator. Runs with NO
        controller lock held — the placement leg is a fan-out RPC."""
        loop = action["loop"]
        if loop == "placement":
            if lockcheck.ACTIVE.enabled:
                lockcheck.ACTIVE.io_point("autopilot.apply")
            return self.rebalancer.resize(action["hosts"],
                                          reason="autopilot")
        if loop == "memory":
            return self._apply_tier(action)
        if loop == "slo":
            new = self.qos.step_concurrency(action["direction"])
            if new is None:
                raise RuntimeError("admission gate moved under the "
                                   "plan: step no longer applies")
            return {"maxConcurrent": new}
        raise RuntimeError(f"unknown loop {loop!r}")

    def _apply_tier(self, action):
        gov = self.governor
        hot = action.get("_hot", frozenset())
        demoted = 0
        # Re-resolve victims at apply time (plan evidence may be
        # seconds old); the hot exclusion keeps pre-staged slices
        # safe. A lock-contended fragment is skipped, exactly like
        # the governor's own sweep.
        if action.get("demote"):
            for frag in gov.coldest(DEMOTE_BATCH, hot=hot):
                if frag.unload(blocking=False):
                    demoted += 1
        touched = 0
        for frag in gov.resident_fragments():
            if (frag.index, frag.slice) in hot:
                gov.touch(frag)
                touched += 1
        self._last_hot = hot
        return {"demoted": demoted, "prestaged": touched}

    def apply(self, plan):
        """Execute a plan's actions under the hysteresis gates.
        Blocked actions journal ``autopilot.cooldown``; failures (or a
        mid-flight kill switch) journal ``autopilot.abort`` and release
        their budget token. Returns per-action outcomes."""
        out = []
        for action in plan.get("_actions") or plan.get("actions") or []:
            out.append(self._apply_one(action))
        return out

    def _apply_one(self, action):
        loop, kind = action["loop"], action["kind"]
        now = self._clock()
        with self._mu:
            prev_last = self._last_action.get(loop)
        reason = self._gate(loop, now)
        if reason is not None:
            with self._mu:
                self.cooldown_blocked_total += 1
            self._emit("autopilot.cooldown", loop=loop, action=kind,
                       reason=reason)
            return {"loop": loop, "kind": kind, "applied": False,
                    "reason": reason}
        try:
            if faults.ACTIVE.enabled:
                faults.ACTIVE.fire("autopilot.apply.slow")
            if self._stop.is_set():
                raise AutopilotDisabled("autopilot disabled mid-flight")
            result = self._actuate(action)
        except Exception as e:
            self._release(loop, now, prev_last)
            with self._mu:
                self.aborts_total += 1
            why = str(e) or type(e).__name__
            self._emit("autopilot.abort", loop=loop, action=kind,
                       reason=why)
            return {"loop": loop, "kind": kind, "applied": False,
                    "aborted": True, "reason": why}
        with self._mu:
            self.actions_total[loop] += 1
        self._emit("autopilot.apply", loop=loop, action=kind,
                   result=result, evidence=action.get("evidence"))
        return {"loop": loop, "kind": kind, "applied": True,
                "result": result}

    # --------------------------------------------------------------- loop

    def tick(self):
        """One control pass — the server monitor's entry point. A plan
        failure (including an armed ``autopilot.plan.error`` failpoint)
        journals ``autopilot.abort`` and stands down until the next
        tick; it never takes a budget token."""
        if self._stop.is_set():
            return
        try:
            plan = self.plan()
        except Exception as e:
            with self._mu:
                self.plan_errors_total += 1
                self.aborts_total += 1
            self._emit("autopilot.abort", loop="plan", action="plan",
                       reason=str(e) or type(e).__name__)
            return
        if self.dry_run or not plan["_actions"]:
            return
        self.apply(plan)

    def disable(self):
        """The kill switch: stop planning, and any apply in flight
        aborts at its next checkpoint (journaled, token released)."""
        self._stop.set()

    def close(self):
        self.disable()

    # ----------------------------------------------------------- surfaces

    def snapshot(self):
        """Rich JSON for GET /debug/autopilot: loop state, hysteresis,
        action budget, last plans."""
        now = self._clock()
        with self._mu:
            self._prune_locked(now)
            used = len(self._actions)
            last_action = dict(self._last_action)
            plans = list(self._plans)
            last_plan = self._last_plan
            counters = {
                "plansTotal": self.plans_total,
                "planErrorsTotal": self.plan_errors_total,
                "actionsTotal": dict(self.actions_total),
                "abortsTotal": self.aborts_total,
                "cooldownBlockedTotal": self.cooldown_blocked_total,
            }
        loops = {}
        for loop, on in (("placement", self.placement_loop),
                         ("memory", self.memory_loop),
                         ("slo", self.slo_loop)):
            last = last_action.get(loop)
            loops[loop] = {
                "enabled": on,
                "lastActionAgeSeconds": (None if last is None
                                         else round(now - last, 1)),
                "dwellRemainingSeconds": (
                    0.0 if last is None
                    else round(max(0.0, self.min_dwell - (now - last)),
                               1)),
            }
        return {
            "enabled": True,
            "killed": self._stop.is_set(),
            "dryRun": self.dry_run,
            "intervalSeconds": self.interval,
            "loops": loops,
            "hysteresis": {
                "minDwellSeconds": self.min_dwell,
                "windowSeconds": self.window,
                "maxActionsPerWindow": self.max_actions_per_window,
                "heatImbalance": self.heat_imbalance,
                "memoryHeadroom": self.memory_headroom,
            },
            "budget": {
                "used": used,
                "remaining": max(0, self.max_actions_per_window - used),
            },
            "counters": counters,
            "lastPlan": last_plan,
            "plans": plans,
        }

    def metrics(self):
        """Flat dict for the ``pilosa_autopilot_*`` exposition group."""
        now = self._clock()
        with self._mu:
            self._prune_locked(now)
            out = {
                "plans_total": self.plans_total,
                "plan_errors_total": self.plan_errors_total,
                "aborts_total": self.aborts_total,
                "cooldown_blocked_total": self.cooldown_blocked_total,
                "budget_remaining": max(
                    0, self.max_actions_per_window - len(self._actions)),
                "dry_run": int(self.dry_run),
                "actions": dict(self.actions_total),
            }
        actions = out.pop("actions")
        for loop, on in (("placement", self.placement_loop),
                         ("memory", self.memory_loop),
                         ("slo", self.slo_loop)):
            out[f"actions_total;loop:{loop}"] = actions[loop]
            out[f"loop_enabled;loop:{loop}"] = int(on)
        return out


_NOP_PLAN = {"enabled": False, "actions": []}


class NopAutopilot:
    """Disabled tier: the handler reads one attribute; no monitor is
    ever spawned."""

    enabled = False
    interval = 0.0
    dry_run = False
    events = None

    def plan(self):
        return _NOP_PLAN

    def tick(self):
        pass

    def disable(self):
        pass

    def close(self):
        pass

    def snapshot(self):
        return {"enabled": False}

    def metrics(self):
        return {}


NOP = NopAutopilot()
