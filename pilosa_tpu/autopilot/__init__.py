"""Heat-driven autopilot: the closed-loop controller tier (see
controller.py for the design)."""
from pilosa_tpu.autopilot.controller import (  # noqa: F401
    NOP,
    Autopilot,
    AutopilotDisabled,
    NopAutopilot,
)
