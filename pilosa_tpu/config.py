"""Config: TOML file ⟵ env (PILOSA_*) ⟵ CLI flags (ref: config.go:44-130,
cmd/root.go:60-107 setAllConfig)."""
import os

try:
    import tomllib  # Python 3.11+
except ModuleNotFoundError:  # pragma: no cover - interpreter-dependent
    try:
        import tomli as tomllib  # the PyPI backport, same API
    except ModuleNotFoundError:
        from pilosa_tpu.utils import minitoml as tomllib

DEFAULT_PORT = 10101        # ref: config.go:17-32
DEFAULT_BIND = f"localhost:{DEFAULT_PORT}"

# Reject request bodies larger than this with 413 before buffering
# (server/handler.py make_http_server). A few MiB comfortably covers
# the largest legitimate import batch (MaxWritesPerRequest bits) while
# bounding what one connection can pin; fragment restore
# (POST /fragment/data, multi-GB backup tars) is exempt from the cap.
DEFAULT_MAX_BODY_SIZE = 8 << 20


class Config:
    def __init__(self):
        self.data_dir = "~/.pilosa"
        self.bind = DEFAULT_BIND
        self.max_writes_per_request = 5000
        self.log_path = ""
        # Host-byte budget for resident fragment matrices; 0 =
        # unlimited. (TPU-build extension: the reference's mmap lets
        # the OS bound RSS by page eviction; the dense-matrix design
        # needs an explicit cap — storage/memgov.py.)
        self.host_bytes = 0
        self.cluster = {
            "replicas": 1,
            "type": "static",
            "hosts": [],
            "poll-interval": 60,
            "long-query-time": 60,
            # Distributed mutation-epoch freshness bound (seconds):
            # how stale a peer's last-observed epoch counter may be
            # before a cached replay must probe it (cluster/epochs.py).
            # 0 = one membership heartbeat interval. This is the
            # documented worst-case staleness of a warm replay against
            # a write this node never relayed; unknown/unprobeable
            # peers always mean cold, never stale.
            "epoch-probe-ttl": 0,
            # Elastic-topology rebalancer (cluster/rebalancer.py):
            # concurrent fragment streams per resize, bytes/sec pacing
            # across all streams (0 = unpaced), and how long a LEAVING
            # node's shutdown waits for its handoff to finish.
            "rebalance-stream-concurrency": 2,
            "rebalance-bandwidth": 0,
            "rebalance-drain-timeout": 30.0,
            # Tail-tolerant reads (cluster/hedge.py; defaults mirror
            # hedge.DEFAULTS). hedge-reads arms deadline-budgeted
            # hedged fan-out; replica-routing scores every slice leg's
            # owner by live replica vitals instead of first-healthy.
            # Hedges draw from a token bucket refilled hedge-ratio
            # per primary leg (capped at hedge-burst) — the ~15%
            # extra-backend-load metastability bound. The hedge timer
            # is max(hedge-delay-ms, predicted latency ×
            # hedge-delay-factor) clamped to hedge-headroom of the
            # remaining deadline; at most hedge-max-per-request
            # hedges per request.
            "hedge-reads": False,
            "replica-routing": False,
            "hedge-ratio": 0.10,
            "hedge-burst": 8.0,
            "hedge-delay-ms": 30.0,
            "hedge-delay-factor": 1.5,
            "hedge-headroom": 0.5,
            "hedge-max-per-request": 4,
        }
        self.anti_entropy = {"interval": 600}
        self.tls = {                # ref: config.go TLS section
            "certificate": "",
            "key": "",
            "skip-verify": False,
        }
        self.metric = {
            "service": "expvar",
            "host": "127.0.0.1:8125",
            "poll-interval": 10,
            "diagnostics": False,  # phone-home is opt-in here, unlike ref
        }
        # Runtime telemetry (stats.py histograms, process collector,
        # /cluster/metrics aggregation). Histograms default ON — an
        # observation is a bisect + three integer adds; turning them
        # off restores the single-nop-attribute-read hot path.
        self.metrics = {
            "histograms": True,
            "histogram-buckets": [],   # seconds; [] = built-in defaults
            "collector-interval": 10,  # process telemetry; 0 = off
            "cluster-aggregation": True,
        }
        # "" / "text" = plain logging; "json" = structured records
        # with trace_id/span_id stamped from the active trace context
        # (logfmt.py).
        self.log_format = ""
        self.trace = {
            # Distributed query tracing (tracing.py). Off by default:
            # the nop tracer keeps the hot path allocation-free.
            "enabled": False,
            "slow-threshold": 0.25,   # seconds; slower queries are
            "ring-size": 128,         # retained in the slow-query ring
            "slow-ring-size": 64,
        }
        self.max_body_size = DEFAULT_MAX_BODY_SIZE
        # Graceful-drain budget: how long close()/SIGTERM waits for
        # in-flight queries after flipping the node to LEAVING.
        self.drain_timeout = 5.0
        self.faults = {
            # Deterministic fault injection (faults.py). Off by
            # default; enabling also unlocks POST /debug/faults.
            "enabled": False,
            "spec": "",   # e.g. "fragment.append.fsync=error(ENOSPC)"
        }
        self.storage = {
            # Compressed device-resident containers (ops/containers.py):
            # per-row-block array/run formats chosen from density
            # stats, with the dense path as the hot-block fallback.
            # Default ON; off = every block dense = the pre-container
            # behavior, bit-identical results either way.
            "container-formats": True,
        }
        self.executor = {
            # Epoch-validated slice-plan cache (plancache.py): LRU
            # entry budget for memoized slice universes, batched
            # dispatch plans, prelude layouts, and owner-host sets.
            # 0 disables the cache (every query re-walks its slices);
            # the default matches plancache.DEFAULT_ENTRIES.
            "plan-cache-entries": 512,
            # Cross-query micro-batching tick (executor coalescer):
            # how long a tick leader holds its accumulation window
            # open for more arrivals (microseconds; 0 = dispatch
            # immediately — batching still grows with load because
            # arrivals park while a tick runs), how many requests one
            # tick admits (QoS priority order decides who when it
            # truncates), whether all-compressed plans fuse as
            # container lanes (false = the pre-PR decline: compressed
            # concurrency serves serially), and the per-group HBM
            # budget for densifying DEEP all-compressed trees (each
            # densified block ticks container_conversions_total).
            "coalesce-max-wait-us": 0,
            "coalesce-max-group": 64,
            "coalesce-compressed": True,
            "coalesce-densify-bytes": 64 << 20,
        }
        # Adaptive cost-based query planner (planner.py): selectivity
        # reordering of commutative Intersect/Union chains, static
        # short-circuiting of provably-empty subtrees, and learned
        # execution-tier selection from the cost model's per-tier
        # estimates. Default ON; off = the written operand order and
        # the fixed tier-consultation chain, byte-identical results
        # either way. explore-stride: every Nth warm use of a plan
        # serves the static tier and records, so a mispredicted
        # override self-corrects (0 = never explore).
        self.planner = {
            "enabled": True,
            "reorder": True,
            "short-circuit": True,
            "tier-select": True,
            "explore-stride": 64,
        }
        self.ingest = {
            # Streaming bulk-ingest pipeline (ingest/pipeline.py):
            # POST /index/<i>/ingest with device-side pack/classify.
            # Default ON; disabling answers 501 on the route.
            "enabled": True,
            # Per-request bit/value budget — bounds what one request
            # pins in host memory and how long one admission slot is
            # held; far above the legacy max-writes-per-request.
            "max-batch-bits": 8_000_000,
        }
        # Workload observatory (observe/): kernel-cost attribution +
        # slice/row heatmaps. Always-on by default — the measured
        # overhead gate is `make obscheck` (≤ 2% on warm engine QPS);
        # disabling restores the one-nop-attribute-read hot path.
        self.observe = {
            "enabled": True,
            # 1-in-N kernel dispatches block_until_ready so TRUE
            # device time is sampled without stalling async dispatch
            # pipelining on the other N-1. 0 = never block (enqueue
            # time only).
            "kernel-sample-rate": 0,
            "heatmap-half-life": 300.0,  # seconds; heat decay rate
            "heatmap-top-k": 20,         # bounded /metrics exposition
        }
        # Continuous profiler (observe/profiler.py): always-on
        # wall-clock stack sampler over sys._current_frames with
        # subsystem attribution, served at /debug/profile. sample-hz
        # defaults to a prime so the sampler cannot phase-lock with
        # periodic work; 0 disables (the one-nop-attribute-read tier).
        self.profile = {
            "sample-hz": 19.0,
            # Where POST /debug/profile/device writes jax.profiler
            # traces when the request doesn't name a directory.
            "device-trace-dir": "",
        }
        # SLO tracker (observe/slo.py): per-QoS-priority latency/
        # availability objectives with 5m/1h burn rates. Off by
        # default (objectives are deployment policy, not a library
        # default); [slo.objectives.<priority>] tables declare them.
        self.slo = {
            "enabled": False,
            "objectives": {},
        }
        # Collective data plane (cluster/meshplane.py): within a
        # mesh peer group (one JAX process group sharing one device
        # set) multi-node queries compile to one shard_map + psum
        # program instead of HTTP fan-out. Off by default — it is a
        # topology claim (the group's nodes really do share devices),
        # not a tuning knob; HTTP remains the universal path.
        self.mesh = {
            "enabled": False,
            "group": "local",
            "stack-bytes": 1 << 30,  # staged sharded-stack LRU budget
        }
        self.qos = {
            # QoS & admission control (qos.py). Off by default: the
            # nop gate keeps the hot path lock- and allocation-free.
            "enabled": False,
            "max-concurrent": 64,      # admission gate capacity
            "queue-length": 128,       # bounded priority wait queue
            "queue-timeout": 1.0,      # max seconds queued before shed
            "default-deadline": 0.0,   # seconds; 0 = unbounded
            "client-qps": 0.0,         # default per-client rate; 0 = off
            "client-burst": 0.0,       # 0 = 2 * qps (floor 1 token)
            "quotas": {},              # client id -> qps override
            "breaker-threshold": 5,    # consecutive transport failures
            "breaker-cooldown": 10.0,  # seconds before a half-open probe
        }
        # Heat-driven autopilot (autopilot/controller.py): the
        # closed-loop controller. Off by default — operating the
        # cluster autonomously is deployment policy, not a library
        # default; `enabled = false` is also the kill switch.
        self.autopilot = {
            "enabled": False,
            "dry-run": False,            # plan + journal, never act
            "interval": 5.0,             # seconds between control passes
            "placement": True,           # heat-weighted placement loop
            "memory": True,              # pre-stage/demote tiering loop
            "slo": True,                 # SLO-burn responder loop
            "min-dwell": 60.0,           # seconds between same-loop actions
            "max-actions-per-window": 2,  # windowed action budget
            "window": 300.0,             # budget window seconds
            "heat-imbalance": 1.5,       # hottest-host/mean trigger ratio
            "memory-headroom": 0.85,     # governor pressure demote trigger
        }

    KNOWN_KEYS = {
        "data-dir", "bind", "max-writes-per-request", "log-path",
        "log-format", "host-bytes", "max-body-size", "drain-timeout",
        "cluster", "anti-entropy", "metric", "metrics", "tls", "trace",
        "qos", "faults", "executor", "storage", "ingest", "observe",
        "profile", "slo", "mesh", "autopilot", "planner",
    }

    @classmethod
    def load(cls, path=None, env=None, overrides=None):
        cfg = cls()
        if path:
            with open(path, "rb") as f:
                data = tomllib.load(f)
            unknown = set(data) - cls.KNOWN_KEYS
            if unknown:
                raise ValueError(
                    f"invalid config option(s): {sorted(unknown)}")
            cfg._apply(data)
        cfg._apply_env(env if env is not None else os.environ)
        if overrides:
            cfg._apply(overrides)
        cfg.validate()
        return cfg

    def _apply(self, data):
        if "data-dir" in data:
            self.data_dir = data["data-dir"]
        if "bind" in data:
            self.bind = data["bind"]
        if "max-writes-per-request" in data:
            self.max_writes_per_request = int(data["max-writes-per-request"])
        if "log-path" in data:
            self.log_path = data["log-path"]
        if "log-format" in data:
            self.log_format = data["log-format"]
        if "host-bytes" in data:
            self.host_bytes = int(data["host-bytes"])
        if "max-body-size" in data:
            self.max_body_size = int(data["max-body-size"])
        if "drain-timeout" in data:
            self.drain_timeout = float(data["drain-timeout"])
        for section in ("cluster", "anti-entropy", "metric", "metrics",
                        "tls", "trace", "qos", "faults", "executor",
                        "storage", "ingest", "observe", "profile",
                        "slo", "mesh", "autopilot", "planner"):
            if section in data:
                target = {"cluster": self.cluster,
                          "anti-entropy": self.anti_entropy,
                          "metric": self.metric,
                          "metrics": self.metrics,
                          "tls": self.tls,
                          "trace": self.trace,
                          "qos": self.qos,
                          "faults": self.faults,
                          "executor": self.executor,
                          "storage": self.storage,
                          "ingest": self.ingest,
                          "observe": self.observe,
                          "profile": self.profile,
                          "slo": self.slo,
                          "mesh": self.mesh,
                          "autopilot": self.autopilot,
                          "planner": self.planner}[section]
                target.update(data[section])

    def _apply_env(self, env):
        """PILOSA_* variables override file values (ref: cmd/root.go:73-90)."""
        if env.get("PILOSA_DATA_DIR"):
            self.data_dir = env["PILOSA_DATA_DIR"]
        if env.get("PILOSA_BIND"):
            self.bind = env["PILOSA_BIND"]
        if env.get("PILOSA_TPU_HOST_BYTES"):
            self.host_bytes = int(env["PILOSA_TPU_HOST_BYTES"])
        if env.get("PILOSA_CLUSTER_HOSTS"):
            self.cluster["hosts"] = [
                h.strip() for h in env["PILOSA_CLUSTER_HOSTS"].split(",") if h]
        if env.get("PILOSA_CLUSTER_REPLICAS"):
            self.cluster["replicas"] = int(env["PILOSA_CLUSTER_REPLICAS"])
        if env.get("PILOSA_EPOCH_PROBE_TTL"):
            self.cluster["epoch-probe-ttl"] = float(
                env["PILOSA_EPOCH_PROBE_TTL"])
        if env.get("PILOSA_REBALANCE_STREAM_CONCURRENCY"):
            self.cluster["rebalance-stream-concurrency"] = int(
                env["PILOSA_REBALANCE_STREAM_CONCURRENCY"])
        if env.get("PILOSA_REBALANCE_BANDWIDTH"):
            self.cluster["rebalance-bandwidth"] = int(
                env["PILOSA_REBALANCE_BANDWIDTH"])
        if env.get("PILOSA_REBALANCE_DRAIN_TIMEOUT"):
            self.cluster["rebalance-drain-timeout"] = float(
                env["PILOSA_REBALANCE_DRAIN_TIMEOUT"])
        # PILOSA_HEDGE_* (tail-tolerant reads): parsed by the hedge
        # module's OWN parser so config/env/server agree on one
        # grammar; malformed numeric values keep the defaults.
        from pilosa_tpu.cluster import hedge as _hedge

        self.cluster.update(_hedge.env_config(env))
        if env.get("PILOSA_METRIC_SERVICE"):
            self.metric["service"] = env["PILOSA_METRIC_SERVICE"]
        if env.get("PILOSA_TLS_CERTIFICATE"):
            self.tls["certificate"] = env["PILOSA_TLS_CERTIFICATE"]
        if env.get("PILOSA_TLS_KEY"):
            self.tls["key"] = env["PILOSA_TLS_KEY"]
        if env.get("PILOSA_TLS_SKIP_VERIFY"):
            self.tls["skip-verify"] = env[
                "PILOSA_TLS_SKIP_VERIFY"].lower() in ("1", "true", "yes")
        if env.get("PILOSA_TRACE_ENABLED"):
            self.trace["enabled"] = env[
                "PILOSA_TRACE_ENABLED"].lower() in ("1", "true", "yes")
        if env.get("PILOSA_TRACE_SLOW_THRESHOLD"):
            self.trace["slow-threshold"] = float(
                env["PILOSA_TRACE_SLOW_THRESHOLD"])
        if env.get("PILOSA_MAX_BODY_SIZE"):
            self.max_body_size = int(env["PILOSA_MAX_BODY_SIZE"])
        if env.get("PILOSA_QOS_ENABLED"):
            self.qos["enabled"] = env[
                "PILOSA_QOS_ENABLED"].lower() in ("1", "true", "yes")
        if env.get("PILOSA_QOS_MAX_CONCURRENT"):
            self.qos["max-concurrent"] = int(
                env["PILOSA_QOS_MAX_CONCURRENT"])
        if env.get("PILOSA_QOS_CLIENT_QPS"):
            self.qos["client-qps"] = float(env["PILOSA_QOS_CLIENT_QPS"])
        if env.get("PILOSA_QOS_DEFAULT_DEADLINE"):
            self.qos["default-deadline"] = float(
                env["PILOSA_QOS_DEFAULT_DEADLINE"])
        if env.get("PILOSA_PLAN_CACHE_ENTRIES"):
            # plancache.py reads this env itself for bare Executor
            # construction (tests, embedding); mirrored here so the
            # config surface reports the truth. Malformed values keep
            # the default and negatives clamp to 0 (off), matching
            # PlanCache's own parse — the one knob must not no-op on
            # one path and crash on the other.
            try:
                self.executor["plan-cache-entries"] = max(
                    0, int(env["PILOSA_PLAN_CACHE_ENTRIES"]))
            except ValueError:
                pass
        if env.get("PILOSA_COALESCE_MAX_WAIT_US"):
            # The executor reads these envs itself for bare
            # construction (tests, embedding); mirrored here so the
            # config surface reports the truth. Malformed values keep
            # the default (the PILOSA_PLAN_CACHE_ENTRIES discipline).
            try:
                self.executor["coalesce-max-wait-us"] = max(
                    0, int(env["PILOSA_COALESCE_MAX_WAIT_US"]))
            except ValueError:
                pass
        if env.get("PILOSA_COALESCE_MAX_GROUP"):
            try:
                self.executor["coalesce-max-group"] = max(
                    1, int(env["PILOSA_COALESCE_MAX_GROUP"]))
            except ValueError:
                pass
        if env.get("PILOSA_COALESCE_COMPRESSED"):
            # The executor's own parse accepts anything not in the
            # falsey set — same rule here so the two cannot drift.
            self.executor["coalesce-compressed"] = env[
                "PILOSA_COALESCE_COMPRESSED"].lower() not in (
                    "0", "false", "no", "off")
        if env.get("PILOSA_COALESCE_DENSIFY_BYTES"):
            try:
                self.executor["coalesce-densify-bytes"] = max(
                    0, int(env["PILOSA_COALESCE_DENSIFY_BYTES"]))
            except ValueError:
                pass
        # The planner reads these envs itself for bare Executor
        # construction (tests, embedding); mirrored here so the config
        # surface reports the truth — the planner's own parse accepts
        # anything not in the falsey set, same rule here.
        for var, key in (("PILOSA_PLANNER_ENABLED", "enabled"),
                         ("PILOSA_PLANNER_REORDER", "reorder"),
                         ("PILOSA_PLANNER_SHORT_CIRCUIT", "short-circuit"),
                         ("PILOSA_PLANNER_TIER_SELECT", "tier-select")):
            if env.get(var):
                self.planner[key] = env[var].lower() not in (
                    "0", "false", "no", "off")
        if env.get("PILOSA_PLANNER_EXPLORE_STRIDE"):
            try:
                self.planner["explore-stride"] = max(
                    0, int(env["PILOSA_PLANNER_EXPLORE_STRIDE"]))
            except ValueError:
                pass
        if env.get("PILOSA_INGEST_ENABLED"):
            self.ingest["enabled"] = env[
                "PILOSA_INGEST_ENABLED"].lower() in ("1", "true", "yes")
        if env.get("PILOSA_INGEST_MAX_BATCH_BITS"):
            # Malformed values keep the default rather than crash the
            # boot (the PILOSA_PLAN_CACHE_ENTRIES discipline).
            try:
                self.ingest["max-batch-bits"] = int(
                    env["PILOSA_INGEST_MAX_BATCH_BITS"])
            except ValueError:
                pass
        if env.get("PILOSA_CONTAINER_FORMATS"):
            # The containers module reads this env itself at import
            # (bare fragments/executors honor it); mirrored here via
            # the module's OWN parser so the config surface reports
            # the truth and the two rules cannot drift.
            from pilosa_tpu.ops import containers as containers_mod

            self.storage["container-formats"] = containers_mod.\
                parse_enabled(env["PILOSA_CONTAINER_FORMATS"])
        if env.get("PILOSA_OBSERVE_ENABLED"):
            self.observe["enabled"] = env[
                "PILOSA_OBSERVE_ENABLED"].lower() in ("1", "true", "yes")
        if env.get("PILOSA_OBSERVE_KERNEL_SAMPLE_RATE"):
            # Malformed values keep the default rather than crash the
            # boot (the PILOSA_PLAN_CACHE_ENTRIES discipline).
            try:
                self.observe["kernel-sample-rate"] = max(
                    0, int(env["PILOSA_OBSERVE_KERNEL_SAMPLE_RATE"]))
            except ValueError:
                pass
        if env.get("PILOSA_OBSERVE_HEATMAP_HALF_LIFE"):
            try:
                self.observe["heatmap-half-life"] = float(
                    env["PILOSA_OBSERVE_HEATMAP_HALF_LIFE"])
            except ValueError:
                pass
        if env.get("PILOSA_OBSERVE_HEATMAP_TOP_K"):
            try:
                self.observe["heatmap-top-k"] = max(
                    1, int(env["PILOSA_OBSERVE_HEATMAP_TOP_K"]))
            except ValueError:
                pass
        # Flight recorder + replica vitals: absent keys follow the
        # observatory master switch (server resolves the default), so
        # the env vars only materialize a key when set.
        if env.get("PILOSA_OBSERVE_EVENTS"):
            self.observe["events"] = env[
                "PILOSA_OBSERVE_EVENTS"].lower() in ("1", "true", "yes")
        if env.get("PILOSA_OBSERVE_VITALS"):
            self.observe["vitals"] = env[
                "PILOSA_OBSERVE_VITALS"].lower() in ("1", "true", "yes")
        if env.get("PILOSA_PROFILE_SAMPLE_HZ"):
            # Malformed values keep the default rather than crash the
            # boot (the PILOSA_PLAN_CACHE_ENTRIES discipline).
            try:
                self.profile["sample-hz"] = max(
                    0.0, float(env["PILOSA_PROFILE_SAMPLE_HZ"]))
            except ValueError:
                pass
        if env.get("PILOSA_PROFILE_DEVICE_TRACE_DIR"):
            self.profile["device-trace-dir"] = env[
                "PILOSA_PROFILE_DEVICE_TRACE_DIR"].strip()
        if env.get("PILOSA_SLO_ENABLED"):
            self.slo["enabled"] = env[
                "PILOSA_SLO_ENABLED"].lower() in ("1", "true", "yes")
        if env.get("PILOSA_SLO_OBJECTIVES"):
            # Compact spec grammar (prio=<n>ms@<percent>, comma
            # separated) parsed by the slo module's OWN parser so the
            # env surface and the tracker cannot drift; a malformed
            # spec fails the boot like a typo'd failpoint does.
            # Declaring objectives implies enabling the tracker —
            # UNLESS PILOSA_SLO_ENABLED explicitly said no (a
            # fleet-wide objectives var must stay overridable per
            # host); server.py's direct-construction path applies the
            # same rule.
            from pilosa_tpu.observe import slo as slo_mod

            objectives = slo_mod.parse_objectives(
                env["PILOSA_SLO_OBJECTIVES"])
            if not env.get("PILOSA_SLO_ENABLED"):
                self.slo["enabled"] = True
            self.slo["objectives"] = {
                prio: {"latency-ms": obj["latency"] * 1e3,
                       "target": obj["target"] * 100.0,
                       "availability": obj["availability"] * 100.0}
                for prio, obj in objectives.items()}
        if env.get("PILOSA_MESH_ENABLED"):
            self.mesh["enabled"] = env[
                "PILOSA_MESH_ENABLED"].lower() in ("1", "true", "yes")
        if env.get("PILOSA_MESH_GROUP"):
            self.mesh["group"] = env["PILOSA_MESH_GROUP"].strip()
        if env.get("PILOSA_MESH_STACK_BYTES"):
            # Malformed values keep the default rather than crash the
            # boot (the PILOSA_PLAN_CACHE_ENTRIES discipline).
            try:
                self.mesh["stack-bytes"] = int(
                    env["PILOSA_MESH_STACK_BYTES"])
            except ValueError:
                pass
        if env.get("PILOSA_AUTOPILOT_ENABLED"):
            self.autopilot["enabled"] = env[
                "PILOSA_AUTOPILOT_ENABLED"].lower() in ("1", "true",
                                                        "yes")
        if env.get("PILOSA_AUTOPILOT_DRY_RUN"):
            self.autopilot["dry-run"] = env[
                "PILOSA_AUTOPILOT_DRY_RUN"].lower() in ("1", "true",
                                                        "yes")
        if env.get("PILOSA_AUTOPILOT_INTERVAL"):
            # Malformed values keep the default rather than crash the
            # boot (the PILOSA_PLAN_CACHE_ENTRIES discipline).
            try:
                self.autopilot["interval"] = float(
                    env["PILOSA_AUTOPILOT_INTERVAL"])
            except ValueError:
                pass
        if env.get("PILOSA_AUTOPILOT_MIN_DWELL"):
            try:
                self.autopilot["min-dwell"] = float(
                    env["PILOSA_AUTOPILOT_MIN_DWELL"])
            except ValueError:
                pass
        if env.get("PILOSA_AUTOPILOT_MAX_ACTIONS_PER_WINDOW"):
            try:
                self.autopilot["max-actions-per-window"] = int(
                    env["PILOSA_AUTOPILOT_MAX_ACTIONS_PER_WINDOW"])
            except ValueError:
                pass
        if env.get("PILOSA_AUTOPILOT_WINDOW"):
            try:
                self.autopilot["window"] = float(
                    env["PILOSA_AUTOPILOT_WINDOW"])
            except ValueError:
                pass
        if env.get("PILOSA_AUTOPILOT_HEAT_IMBALANCE"):
            try:
                self.autopilot["heat-imbalance"] = float(
                    env["PILOSA_AUTOPILOT_HEAT_IMBALANCE"])
            except ValueError:
                pass
        if env.get("PILOSA_DRAIN_TIMEOUT"):
            self.drain_timeout = float(env["PILOSA_DRAIN_TIMEOUT"])
        if env.get("PILOSA_LOG_FORMAT"):
            self.log_format = env["PILOSA_LOG_FORMAT"].strip().lower()
        if env.get("PILOSA_METRICS_HISTOGRAMS"):
            self.metrics["histograms"] = env[
                "PILOSA_METRICS_HISTOGRAMS"].lower() in ("1", "true",
                                                         "yes")
        if env.get("PILOSA_METRICS_COLLECTOR_INTERVAL"):
            self.metrics["collector-interval"] = int(
                env["PILOSA_METRICS_COLLECTOR_INTERVAL"])
        if env.get("PILOSA_METRICS_CLUSTER_AGGREGATION"):
            self.metrics["cluster-aggregation"] = env[
                "PILOSA_METRICS_CLUSTER_AGGREGATION"].lower() in (
                    "1", "true", "yes")
        spec = env.get("PILOSA_FAULTS", "")
        if spec and spec.lower() not in ("0", "false", "no", "off"):
            # The faults module reads this env itself at import (so
            # bare fragments/clients see it); mirrored here so the
            # config surface reports the truth.
            self.faults["enabled"] = True
            if spec.lower() not in ("1", "true", "yes"):
                self.faults["spec"] = spec

    def validate(self):
        if self.cluster.get("type") not in ("static", "http", "gossip"):
            raise ValueError(
                f"invalid cluster type: {self.cluster.get('type')}")
        if self.host_bytes < 0:
            raise ValueError(
                f"host-bytes must be >= 0 (0 = unlimited): "
                f"{self.host_bytes}")
        if float(self.cluster.get("epoch-probe-ttl", 0)) < 0:
            raise ValueError(
                f"cluster epoch-probe-ttl must be >= 0 (0 = one "
                f"heartbeat interval): {self.cluster['epoch-probe-ttl']}")
        if int(self.cluster.get("rebalance-stream-concurrency", 1)) < 1:
            raise ValueError(
                f"cluster rebalance-stream-concurrency must be >= 1: "
                f"{self.cluster['rebalance-stream-concurrency']}")
        if int(self.cluster.get("rebalance-bandwidth", 0)) < 0:
            raise ValueError(
                f"cluster rebalance-bandwidth must be >= 0 "
                f"(0 = unpaced): {self.cluster['rebalance-bandwidth']}")
        if float(self.cluster.get("rebalance-drain-timeout", 0)) < 0:
            raise ValueError(
                f"cluster rebalance-drain-timeout must be >= 0: "
                f"{self.cluster['rebalance-drain-timeout']}")
        ratio = float(self.cluster.get("hedge-ratio", 0.1))
        if not 0.0 < ratio <= 1.0:
            raise ValueError(
                f"cluster hedge-ratio must be in (0, 1]: {ratio}")
        if float(self.cluster.get("hedge-burst", 1)) < 1:
            raise ValueError(
                f"cluster hedge-burst must be >= 1: "
                f"{self.cluster['hedge-burst']}")
        if float(self.cluster.get("hedge-delay-ms", 0)) < 0:
            raise ValueError(
                f"cluster hedge-delay-ms must be >= 0: "
                f"{self.cluster['hedge-delay-ms']}")
        if float(self.cluster.get("hedge-delay-factor", 0)) < 0:
            raise ValueError(
                f"cluster hedge-delay-factor must be >= 0: "
                f"{self.cluster['hedge-delay-factor']}")
        headroom = float(self.cluster.get("hedge-headroom", 0.5))
        if not 0.0 < headroom <= 1.0:
            raise ValueError(
                f"cluster hedge-headroom must be in (0, 1]: {headroom}")
        if int(self.cluster.get("hedge-max-per-request", 1)) < 1:
            raise ValueError(
                f"cluster hedge-max-per-request must be >= 1: "
                f"{self.cluster['hedge-max-per-request']}")
        if float(self.trace["slow-threshold"]) < 0:
            raise ValueError(
                f"trace slow-threshold must be >= 0: "
                f"{self.trace['slow-threshold']}")
        if int(self.trace["ring-size"]) < 1 \
                or int(self.trace["slow-ring-size"]) < 1:
            raise ValueError("trace ring sizes must be >= 1")
        if self.max_body_size < 0:
            raise ValueError(
                f"max-body-size must be >= 0 (0 = unlimited): "
                f"{self.max_body_size}")
        if float(self.drain_timeout) < 0:
            raise ValueError(
                f"drain-timeout must be >= 0 (0 = close immediately): "
                f"{self.drain_timeout}")
        if self.log_format not in ("", "text", "json"):
            raise ValueError(
                f'log-format must be "text" or "json": '
                f"{self.log_format!r}")
        m = self.metrics
        if int(m["collector-interval"]) < 0:
            raise ValueError(
                f"metrics collector-interval must be >= 0 (0 = off): "
                f"{m['collector-interval']}")
        buckets = m.get("histogram-buckets") or []
        prev = 0.0
        for b in buckets:
            try:
                val = float(b)
            except (TypeError, ValueError):
                raise ValueError(
                    f"metrics histogram-buckets must be numbers: {b!r}")
            if val <= prev:
                # Strictly increasing positives: cumulative bucket
                # exposition is meaningless otherwise, and a zero or
                # repeated bound would emit duplicate le= series.
                raise ValueError(
                    "metrics histogram-buckets must be strictly "
                    f"increasing positive seconds: {buckets}")
            prev = val
        if self.faults.get("spec"):
            # Parse at startup so a typo'd failpoint fails the boot,
            # not the first fire.
            from pilosa_tpu import faults as faults_mod

            try:
                faults_mod.parse_spec(self.faults["spec"])
            except ValueError as e:
                raise ValueError(f"faults spec: {e}")
        if not isinstance(self.storage.get("container-formats", True),
                          bool):
            raise ValueError(
                f"storage container-formats must be a boolean: "
                f"{self.storage['container-formats']!r}")
        if int(self.executor.get("plan-cache-entries", 0)) < 0:
            raise ValueError(
                f"executor plan-cache-entries must be >= 0 (0 = off): "
                f"{self.executor['plan-cache-entries']}")
        if int(self.executor.get("coalesce-max-wait-us", 0)) < 0:
            raise ValueError(
                f"executor coalesce-max-wait-us must be >= 0 (0 = "
                f"dispatch immediately): "
                f"{self.executor['coalesce-max-wait-us']}")
        if int(self.executor.get("coalesce-max-group", 1)) < 1:
            raise ValueError(
                f"executor coalesce-max-group must be >= 1: "
                f"{self.executor['coalesce-max-group']}")
        if not isinstance(self.executor.get("coalesce-compressed", True),
                          bool):
            raise ValueError(
                f"executor coalesce-compressed must be a boolean: "
                f"{self.executor['coalesce-compressed']!r}")
        if int(self.executor.get("coalesce-densify-bytes", 0)) < 0:
            raise ValueError(
                f"executor coalesce-densify-bytes must be >= 0 (0 = "
                f"never densify): "
                f"{self.executor['coalesce-densify-bytes']}")
        for key in ("enabled", "reorder", "short-circuit",
                    "tier-select"):
            if not isinstance(self.planner.get(key, True), bool):
                raise ValueError(
                    f"planner {key} must be a boolean: "
                    f"{self.planner[key]!r}")
        if int(self.planner.get("explore-stride", 0)) < 0:
            raise ValueError(
                f"planner explore-stride must be >= 0 (0 = never "
                f"explore): {self.planner['explore-stride']}")
        if not isinstance(self.ingest.get("enabled", True), bool):
            raise ValueError(
                f"ingest enabled must be a boolean: "
                f"{self.ingest['enabled']!r}")
        if int(self.ingest.get("max-batch-bits", 1)) < 1:
            raise ValueError(
                f"ingest max-batch-bits must be >= 1: "
                f"{self.ingest['max-batch-bits']}")
        o = self.observe
        if not isinstance(o.get("enabled", True), bool):
            raise ValueError(
                f"observe enabled must be a boolean: {o['enabled']!r}")
        if int(o.get("kernel-sample-rate", 0)) < 0:
            raise ValueError(
                f"observe kernel-sample-rate must be >= 0 (0 = never "
                f"block): {o['kernel-sample-rate']}")
        if float(o.get("heatmap-half-life", 1)) <= 0:
            raise ValueError(
                f"observe heatmap-half-life must be > 0 seconds: "
                f"{o['heatmap-half-life']}")
        if int(o.get("heatmap-top-k", 1)) < 1:
            raise ValueError(
                f"observe heatmap-top-k must be >= 1: "
                f"{o['heatmap-top-k']}")
        for key in ("events", "vitals"):
            if key in o and not isinstance(o[key], bool):
                raise ValueError(
                    f"observe {key} must be a boolean: {o[key]!r}")
        if int(o.get("events-ring", 1)) < 1:
            raise ValueError(
                f"observe events-ring must be >= 1: {o['events-ring']}")
        if float(o.get("vitals-window", 1)) <= 0:
            raise ValueError(
                f"observe vitals-window must be > 0 seconds: "
                f"{o['vitals-window']}")
        if float(o.get("watchdog-factor", 2)) <= 1:
            raise ValueError(
                f"observe watchdog-factor must be > 1: "
                f"{o['watchdog-factor']}")
        if float(o.get("watchdog-min-ms", 0)) < 0:
            raise ValueError(
                f"observe watchdog-min-ms must be >= 0: "
                f"{o['watchdog-min-ms']}")
        if float(self.profile.get("sample-hz", 0)) < 0:
            raise ValueError(
                f"profile sample-hz must be >= 0 (0 = off): "
                f"{self.profile['sample-hz']}")
        if not isinstance(self.profile.get("device-trace-dir", ""),
                          str):
            raise ValueError(
                f"profile device-trace-dir must be a string: "
                f"{self.profile['device-trace-dir']!r}")
        if not isinstance(self.slo.get("enabled", False), bool):
            raise ValueError(
                f"slo enabled must be a boolean: "
                f"{self.slo['enabled']!r}")
        if self.slo.get("objectives"):
            # Normalized at startup so a typo'd objective fails the
            # boot, not the first burn-rate computation.
            from pilosa_tpu.observe import slo as slo_mod

            try:
                slo_mod.normalize_objectives(self.slo["objectives"])
            except (TypeError, ValueError) as e:
                raise ValueError(f"slo objectives: {e}")
        if not isinstance(self.mesh.get("enabled", False), bool):
            raise ValueError(
                f"mesh enabled must be a boolean: "
                f"{self.mesh['enabled']!r}")
        if not str(self.mesh.get("group", "local")):
            raise ValueError("mesh group must be a non-empty string")
        if int(self.mesh.get("stack-bytes", 1)) < 1:
            raise ValueError(
                f"mesh stack-bytes must be >= 1: "
                f"{self.mesh['stack-bytes']}")
        q = self.qos
        if int(q["max-concurrent"]) < 1:
            raise ValueError(
                f"qos max-concurrent must be >= 1: {q['max-concurrent']}")
        if int(q["queue-length"]) < 0:
            raise ValueError(
                f"qos queue-length must be >= 0: {q['queue-length']}")
        for key in ("queue-timeout", "default-deadline", "client-qps",
                    "client-burst", "breaker-cooldown"):
            if float(q[key]) < 0:
                raise ValueError(f"qos {key} must be >= 0: {q[key]}")
        if int(q["breaker-threshold"]) < 1:
            raise ValueError(
                f"qos breaker-threshold must be >= 1: "
                f"{q['breaker-threshold']}")
        ap = self.autopilot
        for key in ("enabled", "dry-run", "placement", "memory", "slo"):
            if not isinstance(ap.get(key, False), bool):
                raise ValueError(
                    f"autopilot {key} must be a boolean: {ap[key]!r}")
        if float(ap.get("interval", 1)) <= 0:
            raise ValueError(
                f"autopilot interval must be > 0 seconds: "
                f"{ap['interval']}")
        for key in ("min-dwell", "window"):
            if float(ap.get(key, 0)) < 0:
                raise ValueError(
                    f"autopilot {key} must be >= 0 seconds: {ap[key]}")
        if int(ap.get("max-actions-per-window", 1)) < 1:
            raise ValueError(
                f"autopilot max-actions-per-window must be >= 1: "
                f"{ap['max-actions-per-window']}")
        if float(ap.get("heat-imbalance", 1)) < 1:
            raise ValueError(
                f"autopilot heat-imbalance must be >= 1 (1 = any "
                f"skew triggers): {ap['heat-imbalance']}")
        if not 0 < float(ap.get("memory-headroom", 0.5)) <= 1:
            raise ValueError(
                f"autopilot memory-headroom must be in (0, 1]: "
                f"{ap['memory-headroom']}")
        for client, qps in (q.get("quotas") or {}).items():
            # Validated at startup like every other qos key — a bad
            # override must not surface as per-request errors, and a
            # negative one would silently mean UNLIMITED (qps <= 0 is
            # the documented off switch) for the one client the
            # operator meant to restrict.
            try:
                val = float(qps)
            except (TypeError, ValueError):
                raise ValueError(
                    f"qos quota for {client!r} must be a number: "
                    f"{qps!r}")
            if val < 0:
                raise ValueError(
                    f"qos quota for {client!r} must be >= 0 "
                    f"(0 = unlimited): {qps}")
        return self

    def to_toml(self):
        """(ref: ctl/generate_config.go:39-44)."""
        hosts = ", ".join(f'"{h}"' for h in (self.cluster["hosts"]
                                             or [self.bind]))
        buckets = ", ".join(
            str(float(b)) for b in self.metrics["histogram-buckets"])
        return f"""data-dir = "{self.data_dir}"
bind = "{self.bind}"
max-writes-per-request = {self.max_writes_per_request}
host-bytes = {self.host_bytes}
max-body-size = {self.max_body_size}
drain-timeout = {self.drain_timeout}
log-format = "{self.log_format}"

[cluster]
  poll-interval = {self.cluster['poll-interval']}
  replicas = {self.cluster['replicas']}
  hosts = [{hosts}]
  long-query-time = {self.cluster['long-query-time']}
  type = "{self.cluster['type']}"
  epoch-probe-ttl = {self.cluster['epoch-probe-ttl']}
  rebalance-stream-concurrency = {self.cluster['rebalance-stream-concurrency']}
  rebalance-bandwidth = {self.cluster['rebalance-bandwidth']}
  rebalance-drain-timeout = {self.cluster['rebalance-drain-timeout']}
  hedge-reads = {str(self.cluster['hedge-reads']).lower()}
  replica-routing = {str(self.cluster['replica-routing']).lower()}
  hedge-ratio = {self.cluster['hedge-ratio']}
  hedge-burst = {self.cluster['hedge-burst']}
  hedge-delay-ms = {self.cluster['hedge-delay-ms']}
  hedge-delay-factor = {self.cluster['hedge-delay-factor']}
  hedge-headroom = {self.cluster['hedge-headroom']}
  hedge-max-per-request = {self.cluster['hedge-max-per-request']}

[anti-entropy]
  interval = {self.anti_entropy['interval']}

[tls]
  certificate = "{self.tls['certificate']}"
  key = "{self.tls['key']}"
  skip-verify = {str(self.tls['skip-verify']).lower()}

[metric]
  service = "{self.metric['service']}"
  host = "{self.metric['host']}"
  poll-interval = {self.metric['poll-interval']}
  diagnostics = {str(self.metric['diagnostics']).lower()}

[metrics]
  histograms = {str(self.metrics['histograms']).lower()}
  histogram-buckets = [{buckets}]
  collector-interval = {self.metrics['collector-interval']}
  cluster-aggregation = {str(self.metrics['cluster-aggregation']).lower()}

[executor]
  plan-cache-entries = {self.executor['plan-cache-entries']}
  coalesce-max-wait-us = {self.executor['coalesce-max-wait-us']}
  coalesce-max-group = {self.executor['coalesce-max-group']}
  coalesce-compressed = {str(self.executor['coalesce-compressed']).lower()}
  coalesce-densify-bytes = {self.executor['coalesce-densify-bytes']}

[planner]
  enabled = {str(self.planner['enabled']).lower()}
  reorder = {str(self.planner['reorder']).lower()}
  short-circuit = {str(self.planner['short-circuit']).lower()}
  tier-select = {str(self.planner['tier-select']).lower()}
  explore-stride = {self.planner['explore-stride']}

[storage]
  container-formats = {str(self.storage['container-formats']).lower()}

[ingest]
  enabled = {str(self.ingest['enabled']).lower()}
  max-batch-bits = {self.ingest['max-batch-bits']}

[observe]
  enabled = {str(self.observe['enabled']).lower()}
  kernel-sample-rate = {self.observe['kernel-sample-rate']}
  heatmap-half-life = {self.observe['heatmap-half-life']}
  heatmap-top-k = {self.observe['heatmap-top-k']}

[profile]
  sample-hz = {self.profile['sample-hz']}
  device-trace-dir = "{self.profile['device-trace-dir']}"

[mesh]
  enabled = {str(self.mesh['enabled']).lower()}
  group = "{self.mesh['group']}"
  stack-bytes = {self.mesh['stack-bytes']}

[slo]
  enabled = {str(self.slo['enabled']).lower()}
""" + "".join(
            f"""
  [slo.objectives.{prio}]
    latency-ms = {float(obj['latency-ms'])}
    target = {float(obj.get('target', 99.9))}
    availability = {float(obj.get('availability',
                                  obj.get('target', 99.9)))}
"""
            for prio, obj in sorted(
                (self.slo.get("objectives") or {}).items())) + f"""
[trace]
  enabled = {str(self.trace['enabled']).lower()}
  slow-threshold = {self.trace['slow-threshold']}
  ring-size = {self.trace['ring-size']}
  slow-ring-size = {self.trace['slow-ring-size']}

[qos]
  enabled = {str(self.qos['enabled']).lower()}
  max-concurrent = {self.qos['max-concurrent']}
  queue-length = {self.qos['queue-length']}
  queue-timeout = {self.qos['queue-timeout']}
  default-deadline = {self.qos['default-deadline']}
  client-qps = {self.qos['client-qps']}
  client-burst = {self.qos['client-burst']}
  breaker-threshold = {self.qos['breaker-threshold']}
  breaker-cooldown = {self.qos['breaker-cooldown']}
""" + (("\n  [qos.quotas]\n" + "".join(
            f'  "{k}" = {float(v)}\n'
            for k, v in sorted(self.qos.get("quotas", {}).items())))
       if self.qos.get("quotas") else "") + f"""
[autopilot]
  enabled = {str(self.autopilot['enabled']).lower()}
  dry-run = {str(self.autopilot['dry-run']).lower()}
  interval = {self.autopilot['interval']}
  placement = {str(self.autopilot['placement']).lower()}
  memory = {str(self.autopilot['memory']).lower()}
  slo = {str(self.autopilot['slo']).lower()}
  min-dwell = {self.autopilot['min-dwell']}
  max-actions-per-window = {self.autopilot['max-actions-per-window']}
  window = {self.autopilot['window']}
  heat-imbalance = {self.autopilot['heat-imbalance']}
  memory-headroom = {self.autopilot['memory-headroom']}

[faults]
  enabled = {str(self.faults['enabled']).lower()}
  spec = "{self.faults['spec']}"
"""
