"""User-facing client library + PQL ORM.

The reference ecosystem ships client libraries (go-pilosa /
python-pilosa, docs/client-libraries.md) with a small ORM: ``Client``,
``Schema`` → ``Index`` → ``Frame`` objects whose methods build PQL
calls, and typed query responses. This module is the equivalent for
pilosa-tpu, speaking the same HTTP+JSON API (handler.py route table).

    from pilosa_tpu.client import Client

    client = Client("http://localhost:10101")
    schema = client.schema()
    repo = schema.index("repository")
    stargazer = repo.frame("stargazer")
    client.sync_schema(schema)

    client.query(stargazer.setbit(14, 100))
    resp = client.query(stargazer.bitmap(14))
    print(resp.result.bitmap.bits)
"""
import json

from pilosa_tpu import errors as perr
from pilosa_tpu.utils.uri import URI


class PilosaError(perr.PilosaError):
    """Client-side error (subclasses the package error root so a bare
    ``except pilosa_tpu.errors.PilosaError`` also catches it)."""


# --------------------------------------------------------------------- PQL

def _fmt(value):
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, str):
        return json.dumps(value)
    if isinstance(value, (list, tuple)):
        return "[" + ",".join(_fmt(v) for v in value) + "]"
    return str(value)


class PQLQuery:
    """A single PQL call bound to an index."""

    def __init__(self, pql, index):
        self.pql = pql
        self.index = index

    def serialize(self):
        return self.pql


class PQLBatchQuery:
    def __init__(self, index, queries=()):
        self.index = index
        self.queries = list(queries)

    def add(self, query):
        self.queries.append(query)
        return self

    def serialize(self):
        return "".join(q.serialize() for q in self.queries)


def _call(name, index, *positional, **kwargs):
    args = list(positional)
    for k, v in kwargs.items():
        if v is not None:
            args.append(f"{k}={_fmt(v)}")
    return PQLQuery(f"{name}({', '.join(args)})", index)


class Index:
    """(ref: python-pilosa Index — PQL builders for index-level calls)."""

    def __init__(self, name, column_label="columnID", time_quantum=""):
        self.name = name
        self.column_label = column_label
        self.time_quantum = time_quantum
        self._frames = {}

    def frame(self, name, **options):
        if name not in self._frames:
            self._frames[name] = Frame(self, name, **options)
        return self._frames[name]

    def frames(self):
        return dict(self._frames)

    def raw_query(self, pql):
        return PQLQuery(pql, self)

    def batch_query(self, *queries):
        return PQLBatchQuery(self, queries)

    def _bitmap_op(self, name, bitmaps):
        return PQLQuery(
            f"{name}({', '.join(b.serialize() for b in bitmaps)})", self)

    def union(self, *bitmaps):
        return self._bitmap_op("Union", bitmaps)

    def intersect(self, *bitmaps):
        if not bitmaps:
            raise PilosaError("Intersect requires at least one bitmap")
        return self._bitmap_op("Intersect", bitmaps)

    def difference(self, *bitmaps):
        if not bitmaps:
            raise PilosaError("Difference requires at least one bitmap")
        return self._bitmap_op("Difference", bitmaps)

    def xor(self, *bitmaps):
        if len(bitmaps) < 2:
            raise PilosaError("Xor requires at least two bitmaps")
        return self._bitmap_op("Xor", bitmaps)

    def count(self, bitmap):
        return PQLQuery(f"Count({bitmap.serialize()})", self)

    def set_column_attrs(self, column_id, attrs):
        pairs = ", ".join(f"{k}={_fmt(v)}" for k, v in sorted(attrs.items()))
        return PQLQuery(
            f"SetColumnAttrs({self.column_label}={column_id}, {pairs})",
            self)


class Frame:
    """(ref: python-pilosa Frame — PQL builders for frame-level calls)."""

    def __init__(self, index, name, row_label="rowID", inverse_enabled=False,
                 range_enabled=False, cache_type="", cache_size=0,
                 time_quantum="", fields=None):
        self.index = index
        self.name = name
        self.row_label = row_label
        self.inverse_enabled = inverse_enabled
        self.range_enabled = range_enabled
        self.cache_type = cache_type
        self.cache_size = cache_size
        self.time_quantum = time_quantum
        self.fields = fields or []

    def _options(self):
        opts = {}
        if self.row_label != "rowID":
            opts["rowLabel"] = self.row_label
        if self.inverse_enabled:
            opts["inverseEnabled"] = True
        if self.range_enabled:
            opts["rangeEnabled"] = True
        if self.cache_type:
            opts["cacheType"] = self.cache_type
        if self.cache_size:
            opts["cacheSize"] = self.cache_size
        if self.time_quantum:
            opts["timeQuantum"] = self.time_quantum
        if self.fields:
            opts["fields"] = self.fields
        return opts

    def bitmap(self, row_id):
        return _call("Bitmap", self.index,
                     f"{self.row_label}={row_id}", frame=self.name)

    def inverse_bitmap(self, column_id):
        return _call("Bitmap", self.index,
                     f"{self.index.column_label}={column_id}",
                     frame=self.name)

    def setbit(self, row_id, column_id, timestamp=None):
        if hasattr(timestamp, "strftime"):  # datetime → server TIME_FORMAT
            timestamp = timestamp.strftime("%Y-%m-%dT%H:%M")
        return _call("SetBit", self.index, f"{self.row_label}={row_id}",
                     f"{self.index.column_label}={column_id}",
                     frame=self.name, timestamp=timestamp)

    def clearbit(self, row_id, column_id):
        return _call("ClearBit", self.index, f"{self.row_label}={row_id}",
                     f"{self.index.column_label}={column_id}",
                     frame=self.name)

    def topn(self, n, bitmap=None, field=None, *values):
        args = [f"frame={_fmt(self.name)}", f"n={n}"]
        if bitmap is not None:
            args.insert(0, bitmap.serialize())
        if field is not None:
            args.append(f"field={_fmt(field)}")
            args.append(f"filters={_fmt(list(values))}")
        return PQLQuery(f"TopN({', '.join(args)})", self.index)

    def range(self, row_id, start, end):
        return _call(
            "Range", self.index, f"{self.row_label}={row_id}",
            frame=self.name, start=start.strftime("%Y-%m-%dT%H:%M"),
            end=end.strftime("%Y-%m-%dT%H:%M"))

    def set_row_attrs(self, row_id, attrs):
        pairs = ", ".join(f"{k}={_fmt(v)}" for k, v in sorted(attrs.items()))
        return PQLQuery(
            f"SetRowAttrs({self.row_label}={row_id}, "
            f"frame={_fmt(self.name)}, {pairs})", self.index)

    def set_field_value(self, column_id, field, value):
        return _call("SetFieldValue", self.index,
                     f"{self.index.column_label}={column_id}",
                     frame=self.name, **{field: value})

    def sum(self, bitmap=None, field=None):
        args = []
        if bitmap is not None:
            args.append(bitmap.serialize())
        args.append(f"frame={_fmt(self.name)}")
        args.append(f"field={_fmt(field)}")
        return PQLQuery(f"Sum({', '.join(args)})", self.index)

    def field(self, name):
        return FieldRange(self, name)


class FieldRange:
    """BSI comparison builders: frame.field("x") > 5 → Range query
    (ref: python-pilosa RangeField)."""

    def __init__(self, frame, name):
        self.frame = frame
        self.name = name

    def _cmp(self, op, value):
        return PQLQuery(
            f"Range(frame={_fmt(self.frame.name)}, "
            f"{self.name} {op} {_fmt(value)})", self.frame.index)

    def __lt__(self, other):
        return self._cmp("<", other)

    def __le__(self, other):
        return self._cmp("<=", other)

    def __gt__(self, other):
        return self._cmp(">", other)

    def __ge__(self, other):
        return self._cmp(">=", other)

    def equals(self, other):
        return self._cmp("==", other)

    def not_equals(self, other):
        return self._cmp("!=", other)

    def between(self, lo, hi):
        return self._cmp("><", [lo, hi])


class Schema:
    def __init__(self):
        self._indexes = {}

    def index(self, name, **options):
        if name not in self._indexes:
            self._indexes[name] = Index(name, **options)
        return self._indexes[name]

    def indexes(self):
        return dict(self._indexes)


# ------------------------------------------------------------------ results

class BitmapResult:
    def __init__(self, d):
        d = d or {}
        self.bits = d.get("bits", [])
        self.attributes = d.get("attrs", {})


class CountResultItem:
    def __init__(self, d):
        self.id = d.get("id", d.get("key", 0))
        self.count = d.get("count", 0)

    def __repr__(self):
        return f"CountResultItem(id={self.id}, count={self.count})"


class QueryResult:
    def __init__(self, raw):
        self.raw = raw
        self.bitmap = BitmapResult(raw if isinstance(raw, dict) else None)
        self.count_items = ([CountResultItem(i) for i in raw]
                            if isinstance(raw, list) else [])
        self.count = raw if isinstance(raw, (int, bool)) else 0
        if isinstance(raw, dict) and "sum" in raw:
            self.sum = raw["sum"]
            self.sum_count = raw.get("count", 0)
        else:
            self.sum = 0
            self.sum_count = 0
        self.changed = raw if isinstance(raw, bool) else False


class QueryResponse:
    def __init__(self, body):
        self.results = [QueryResult(r) for r in body.get("results", [])]
        self.column_attrs = body.get("columnAttrs", [])

    @property
    def result(self):
        return self.results[0] if self.results else None


# ------------------------------------------------------------------- client

class Client:
    """HTTP client for a pilosa-tpu cluster
    (ref: python-pilosa Client; our wire = handler.py routes)."""

    def __init__(self, address="http://localhost:10101", timeout=30,
                 skip_verify=False):
        from pilosa_tpu.cluster.client import InternalClient

        u = URI.parse(address)
        self.base = u.normalize()
        # All HTTP plumbing (urlopen, TLS skip-verify context, status
        # mapping) lives in InternalClient — one implementation.
        self._ic = InternalClient(timeout=timeout, skip_verify=skip_verify)

    # -- plumbing

    def _http(self, method, path, body=None, content_type="application/json"):
        from pilosa_tpu.cluster.client import ClientError

        try:
            status, data, _ = self._ic._do(
                method, self.base + path, body, content_type=content_type)
        except ClientError as e:
            raise PilosaError(str(e)) from e
        return status, data

    def _json(self, method, path, payload=None):
        body = (json.dumps(payload).encode()
                if payload is not None else None)
        status, data = self._http(method, path, body)
        parsed = {}
        if data:
            try:
                parsed = json.loads(data)
            except ValueError:
                parsed = {"error": data.decode(errors="replace")}
        if status >= 400:
            # Raise the BARE server message ("index already exists"),
            # matching python-pilosa's contract — deliberately not
            # InternalClient._json, whose errors carry method/url/status.
            raise PilosaError(parsed.get("error", f"status {status}"))
        return parsed

    # -- queries

    def query(self, query, exclude_attrs=False, exclude_bits=False):
        qs = []
        if exclude_attrs:
            qs.append("excludeAttrs=true")
        if exclude_bits:
            qs.append("excludeBits=true")
        suffix = ("?" + "&".join(qs)) if qs else ""
        status, data = self._http(
            "POST", f"/index/{query.index.name}/query{suffix}",
            query.serialize().encode(), content_type="text/plain")
        parsed = json.loads(data) if data else {}
        if status >= 400 or "error" in parsed:
            raise PilosaError(parsed.get("error", f"status {status}"))
        return QueryResponse(parsed)

    # -- schema

    def schema(self):
        schema = Schema()
        for idx in self._json("GET", "/schema").get("indexes") or []:
            index = schema.index(idx["name"])
            for fr in idx.get("frames") or []:
                index.frame(fr["name"])
        return schema

    def sync_schema(self, schema):
        """Create every index/frame in ``schema`` that the server lacks,
        and add server-side ones into ``schema``
        (ref: python-pilosa Client.sync_schema)."""
        server = self.schema()
        for name, index in schema.indexes().items():
            self.ensure_index(index)
            for frame in index.frames().values():
                self.ensure_frame(frame)
        for name, index in server.indexes().items():
            local = schema.index(name)
            for fname in index.frames():
                local.frame(fname)

    def create_index(self, index):
        opts = {}
        if index.column_label != "columnID":
            opts["columnLabel"] = index.column_label
        if index.time_quantum:
            opts["timeQuantum"] = index.time_quantum
        self._json("POST", f"/index/{index.name}", {"options": opts})

    def ensure_index(self, index):
        try:
            self.create_index(index)
        except PilosaError as e:
            if "exists" not in str(e):
                raise

    def create_frame(self, frame):
        self._json("POST", f"/index/{frame.index.name}/frame/{frame.name}",
                   {"options": frame._options()})

    def ensure_frame(self, frame):
        try:
            self.create_frame(frame)
        except PilosaError as e:
            if "exists" not in str(e):
                raise

    def delete_index(self, index):
        self._json("DELETE", f"/index/{index.name}")

    def delete_frame(self, frame):
        self._json("DELETE", f"/index/{frame.index.name}/frame/{frame.name}")

    def status(self):
        return self._json("GET", "/status")
