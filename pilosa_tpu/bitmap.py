"""Cross-slice result bitmap (ref: bitmap.go:28-155).

The reference's ``pilosa.Bitmap`` is a list of per-slice roaring
segments merged via aligned iterators. Here a segment is a **device
array** — ``uint32[32768]`` in HBM — so binary ops between result
bitmaps stay on the TPU (fused bitwise kernels) and counts are device
popcounts; bits only come back to the host when a caller asks for
column ids (serialization) or a host-side filter view.

Format-polymorphic segments: a segment may also be a compressed
``ops.containers.Container`` (array/run/dense — it carries a ``fmt``
descriptor and a host-known cardinality), served by the fragment tier.
All algebra routes through ``bitops.dispatch_pair`` /
``bitops.dispatch_count``, so compressed operands take their
registered kernels (count-only paths never materialize a dense
intermediate) and any uncovered pair densifies and falls back —
bit-exact by construction. Material boundaries (``columns``,
``host_words``, stack merging) densify via ``bitops.densify``.
"""
import numpy as np
import jax.numpy as jnp

from pilosa_tpu import SLICE_WIDTH, WORDS_PER_SLICE
from pilosa_tpu.ops import bitops


def _seg_count(seg):
    """Cardinality of one segment: host-known for containers (every
    format carries its count — zero device work), device popcount for
    raw dense arrays."""
    cnt = getattr(seg, "count", None)
    if cnt is not None:
        return int(cnt)
    return int(bitops.count(seg))


class Bitmap:
    def __init__(self, attrs=None):
        self._segments = {}  # slice -> uint32[WORDS_PER_SLICE] (device/host)
        self.attrs = attrs or {}
        self._count = None   # cached count (ref: bitmap.go:205-238)
        self._stack = None   # deferred (stack, slice_list, counts)

    @property
    def segments(self):
        """slice -> words map; materializes a deferred stack first.

        A batched materialization (executor._batched_bitmap) produces
        the whole result as ONE ``uint32[n_slices, W]`` device stack.
        Slicing it into per-slice device arrays eagerly costs one
        dispatch (and, sharded, one cross-device gather) per slice —
        measured 0.3-0.7× the serial path. Deferring until a caller
        actually touches segment words turns that into a single bulk
        host fetch, and count-only consumers never fetch at all."""
        if self._stack is not None:
            stack, slice_list, counts, word_base = self._stack
            host = np.asarray(stack)  # one transfer/gather for the lot
            self._stack = None  # only after the fetch succeeded
            narrow = host.shape[1] < WORDS_PER_SLICE
            for i, s in enumerate(slice_list):
                if counts[i]:
                    if narrow:
                        # Window-width batched result: rebase to the
                        # full slice so segment algebra stays aligned.
                        seg = np.zeros(WORDS_PER_SLICE, dtype=host.dtype)
                        seg[word_base : word_base + host.shape[1]] = (
                            host[i])
                    else:
                        seg = host[i]
                    mine = self._segments.get(s)
                    if mine is not None:
                        seg = np.bitwise_or(
                            np.asarray(bitops.densify(mine)), seg)
                    self._segments[s] = seg
        return self._segments

    @segments.setter
    def segments(self, value):
        self._segments = value
        self._stack = None
        self.invalidate_count()

    def defer_stack(self, stack, slice_list, counts, word_base=0):
        """Adopt a batched result stack without slicing it (rows with
        zero counts are dropped at materialization time). ``word_base``
        is the column-window offset (uint32 words) of a narrower-than-
        slice stack; materialization rebases rows to full width."""
        if self._stack is not None or self._segments:
            # Merging into existing content: materialize the old stack
            # first, then stage the new one.
            _ = self.segments
        self._stack = (stack, list(slice_list), np.asarray(counts),
                       int(word_base))
        self.invalidate_count()

    # ------------------------------------------------------ construction

    @classmethod
    def from_device(cls, slice_num, words32):
        bm = cls()
        bm.segments[slice_num] = words32
        return bm

    @classmethod
    def from_host_words(cls, slice_num, words64):
        bm = cls()
        bm.segments[slice_num] = jnp.asarray(
            np.ascontiguousarray(words64).view(np.uint32))
        return bm

    @classmethod
    def from_columns(cls, columns):
        """Build from absolute column ids (wire format: uint64 list,
        internal/public.proto Bitmap.Bits)."""
        bm = cls()
        columns = np.asarray(sorted(columns), dtype=np.uint64)
        if len(columns) == 0:
            return bm
        slices = (columns // SLICE_WIDTH).astype(np.int64)
        for s in np.unique(slices):
            cols = (columns[slices == s] % SLICE_WIDTH).astype(np.int64)
            bits = np.zeros(SLICE_WIDTH, dtype=np.uint8)
            bits[cols] = 1
            words = np.packbits(bits, bitorder="little").view(np.uint32)
            bm.segments[int(s)] = jnp.asarray(words)
        return bm

    # ------------------------------------------------------------- algebra
    # Aligned segment-wise ops (ref: mergeSegmentIterator bitmap.go:426-461);
    # a missing segment is all-zeros.

    def intersect(self, other):
        out = Bitmap()
        for k in set(self.segments) & set(other.segments):
            out.segments[k] = bitops.dispatch_pair(
                "and", self.segments[k], other.segments[k])
        return out

    def union(self, other):
        out = Bitmap()
        for k in set(self.segments) | set(other.segments):
            a, b = self.segments.get(k), other.segments.get(k)
            if a is None:
                out.segments[k] = b
            elif b is None:
                out.segments[k] = a
            else:
                out.segments[k] = bitops.dispatch_pair("or", a, b)
        return out

    def difference(self, other):
        out = Bitmap()
        for k, a in self.segments.items():
            b = other.segments.get(k)
            out.segments[k] = (a if b is None
                               else bitops.dispatch_pair("andnot", a, b))
        return out

    def xor(self, other):
        out = Bitmap()
        for k in set(self.segments) | set(other.segments):
            a, b = self.segments.get(k), other.segments.get(k)
            if a is None:
                out.segments[k] = b
            elif b is None:
                out.segments[k] = a
            else:
                out.segments[k] = bitops.dispatch_pair("xor", a, b)
        return out

    def intersection_count(self, other):
        """Count-only fast path — never materializes (ref: bitmap.go:139)."""
        return self.op_count("and", other)

    def op_count(self, op, other):
        """|self OP other| without materializing the result bitmap:
        per-slice counts via ``bitops.dispatch_count`` (compressed
        operands run their registered count kernels — the analog of
        the reference's intersectionCount* fast paths,
        roaring.go:1811-1923), with absent segments resolved by the
        op's identity (missing = all-zeros): ``and`` skips them,
        ``or``/``xor`` count the present side, ``andnot`` counts an
        unopposed left side."""
        total = 0
        mine, theirs = self.segments, other.segments
        if op == "and":
            for k in set(mine) & set(theirs):
                total += int(bitops.dispatch_count("and", mine[k],
                                                   theirs[k]))
            return total
        if op == "andnot":
            for k, a in mine.items():
                b = theirs.get(k)
                total += (_seg_count(a) if b is None
                          else int(bitops.dispatch_count("andnot", a, b)))
            return total
        for k in set(mine) | set(theirs):  # or / xor
            a, b = mine.get(k), theirs.get(k)
            if a is None:
                total += _seg_count(b)
            elif b is None:
                total += _seg_count(a)
            else:
                total += int(bitops.dispatch_count(op, a, b))
        return total

    # ------------------------------------------------------------- readers

    def merge(self, other):
        """Disjoint-slice merge for map/reduce (ref: Bitmap.Merge).
        ``other`` is left intact (as in the reference)."""
        if (not self._segments and self._stack is None
                and other._stack is not None):
            # Empty target adopts the other's deferred stack unfetched —
            # a shared reference, so both bitmaps stay independently
            # materializable; only other's EAGER segments remain to
            # merge below.
            self._stack = other._stack
            eager = other._segments
        else:
            eager = other.segments  # materializes other's stack if any
        for k, words in eager.items():
            mine = self.segments.get(k)
            self.segments[k] = (words if mine is None
                                else bitops.dispatch_pair("or", mine,
                                                          words))
        self.invalidate_count()
        return self

    def count(self):
        if self._count is None:
            if self._stack is not None and not self._segments:
                self._count = int(self._stack[2].sum())
            else:
                self._count = sum(_seg_count(w)
                                  for w in self.segments.values())
        return self._count

    def invalidate_count(self):
        self._count = None

    def columns(self):
        """Absolute column ids, ascending (wire serialization)."""
        out = []
        for k in sorted(self.segments):
            words = np.asarray(bitops.densify(self.segments[k]))
            bits = np.flatnonzero(
                np.unpackbits(words.view(np.uint8), bitorder="little"))
            out.append(bits.astype(np.uint64) + np.uint64(k) * SLICE_WIDTH)
        if not out:
            return np.empty(0, dtype=np.uint64)
        return np.concatenate(out)

    def host_words(self, slice_num):
        """uint64[WORDS64] host view of one segment."""
        seg = self.segments.get(slice_num)
        if seg is None:
            return np.zeros(SLICE_WIDTH // 64, dtype=np.uint64)
        return np.ascontiguousarray(
            np.asarray(bitops.densify(seg))).view(np.uint64)

    def __eq__(self, other):
        if not isinstance(other, Bitmap):
            return NotImplemented
        return np.array_equal(self.columns(), other.columns())
