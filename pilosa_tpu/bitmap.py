"""Cross-slice result bitmap (ref: bitmap.go:28-155).

The reference's ``pilosa.Bitmap`` is a list of per-slice roaring
segments merged via aligned iterators. Here a segment is a **device
array** — ``uint32[32768]`` in HBM — so binary ops between result
bitmaps stay on the TPU (fused bitwise kernels) and counts are device
popcounts; bits only come back to the host when a caller asks for
column ids (serialization) or a host-side filter view.
"""
import numpy as np
import jax.numpy as jnp

from pilosa_tpu import SLICE_WIDTH, WORDS_PER_SLICE
from pilosa_tpu.ops import bitops


class Bitmap:
    def __init__(self, attrs=None):
        self.segments = {}   # slice -> jnp.uint32[WORDS_PER_SLICE]
        self.attrs = attrs or {}
        self._count = None   # cached count (ref: bitmap.go:205-238)

    # ------------------------------------------------------ construction

    @classmethod
    def from_device(cls, slice_num, words32):
        bm = cls()
        bm.segments[slice_num] = words32
        return bm

    @classmethod
    def from_host_words(cls, slice_num, words64):
        bm = cls()
        bm.segments[slice_num] = jnp.asarray(
            np.ascontiguousarray(words64).view(np.uint32))
        return bm

    @classmethod
    def from_columns(cls, columns):
        """Build from absolute column ids (wire format: uint64 list,
        internal/public.proto Bitmap.Bits)."""
        bm = cls()
        columns = np.asarray(sorted(columns), dtype=np.uint64)
        if len(columns) == 0:
            return bm
        slices = (columns // SLICE_WIDTH).astype(np.int64)
        for s in np.unique(slices):
            cols = (columns[slices == s] % SLICE_WIDTH).astype(np.int64)
            bits = np.zeros(SLICE_WIDTH, dtype=np.uint8)
            bits[cols] = 1
            words = np.packbits(bits, bitorder="little").view(np.uint32)
            bm.segments[int(s)] = jnp.asarray(words)
        return bm

    # ------------------------------------------------------------- algebra
    # Aligned segment-wise ops (ref: mergeSegmentIterator bitmap.go:426-461);
    # a missing segment is all-zeros.

    def intersect(self, other):
        out = Bitmap()
        for k in set(self.segments) & set(other.segments):
            out.segments[k] = bitops.bitmap_and(self.segments[k],
                                                other.segments[k])
        return out

    def union(self, other):
        out = Bitmap()
        for k in set(self.segments) | set(other.segments):
            a, b = self.segments.get(k), other.segments.get(k)
            if a is None:
                out.segments[k] = b
            elif b is None:
                out.segments[k] = a
            else:
                out.segments[k] = bitops.bitmap_or(a, b)
        return out

    def difference(self, other):
        out = Bitmap()
        for k, a in self.segments.items():
            b = other.segments.get(k)
            out.segments[k] = a if b is None else bitops.bitmap_andnot(a, b)
        return out

    def xor(self, other):
        out = Bitmap()
        for k in set(self.segments) | set(other.segments):
            a, b = self.segments.get(k), other.segments.get(k)
            if a is None:
                out.segments[k] = b
            elif b is None:
                out.segments[k] = a
            else:
                out.segments[k] = bitops.bitmap_xor(a, b)
        return out

    def intersection_count(self, other):
        """Count-only fast path — never materializes (ref: bitmap.go:139)."""
        total = 0
        for k in set(self.segments) & set(other.segments):
            total += int(bitops.count_and(self.segments[k], other.segments[k]))
        return total

    # ------------------------------------------------------------- readers

    def merge(self, other):
        """Disjoint-slice merge for map/reduce (ref: Bitmap.Merge)."""
        for k, words in other.segments.items():
            mine = self.segments.get(k)
            self.segments[k] = words if mine is None else bitops.bitmap_or(
                mine, words)
        self.invalidate_count()
        return self

    def count(self):
        if self._count is None:
            self._count = sum(
                int(bitops.count(w)) for w in self.segments.values())
        return self._count

    def invalidate_count(self):
        self._count = None

    def columns(self):
        """Absolute column ids, ascending (wire serialization)."""
        out = []
        for k in sorted(self.segments):
            words = np.asarray(self.segments[k])
            bits = np.flatnonzero(
                np.unpackbits(words.view(np.uint8), bitorder="little"))
            out.append(bits.astype(np.uint64) + np.uint64(k) * SLICE_WIDTH)
        if not out:
            return np.empty(0, dtype=np.uint64)
        return np.concatenate(out)

    def host_words(self, slice_num):
        """uint64[WORDS64] host view of one segment."""
        seg = self.segments.get(slice_num)
        if seg is None:
            return np.zeros(SLICE_WIDTH // 64, dtype=np.uint64)
        return np.ascontiguousarray(np.asarray(seg)).view(np.uint64)

    def __eq__(self, other):
        if not isinstance(other, Bitmap):
            return NotImplemented
        return np.array_equal(self.columns(), other.columns())
