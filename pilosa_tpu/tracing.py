"""End-to-end distributed query tracing.

The reference's only ops surfaces are expvar counters and statsd
timings (stats.go:34-252) — aggregates that can say a query WAS slow
but never WHERE the time went (parse, plan, per-slice kernel execute,
XLA compile, remote fan-out, reduce). This module adds spans:

- ``Span``/``Trace``: monotonic timings, tags, parent links. Finished
  traces land in a bounded in-memory ring; traces slower than a
  configurable threshold additionally land in a dedicated slow-query
  ring and increment ``pilosa_slow_queries_total`` plus cumulative
  latency buckets on the stats client (rendered on ``/metrics``).
- Trace-context propagation: the coordinator stamps
  ``X-Pilosa-Trace-Id``/``X-Pilosa-Span-Id`` on internal fan-out
  requests (cluster/client.py); the remote handler adopts them so the
  remote node's spans carry the same trace id and a parent link into
  the coordinator's fan-out span. ``stitch()`` reassembles the pieces
  (one ``to_dict()`` payload per node) into a single tree.
- A module-level ACTIVE-SPAN slot (thread-local): instrumentation
  points anywhere in the codebase call ``tracing.span(name, **tags)``,
  which is a shared no-op context manager unless a trace is active on
  the calling thread — the NopStatsClient pattern, so disabled tracing
  costs one call + attribute read per instrumentation point (per-slice
  hot loops hoist even that behind an ``active_span()`` check).

Roots are opened by whoever owns a Tracer (the HTTP handler, tests);
everything below nests automatically. Fan-out threads adopt their
parent explicitly via ``child_of`` (thread-locals don't cross
``threading.Thread``).
"""
import os
import threading
import time
from collections import deque

from pilosa_tpu import lockcheck

TRACE_HEADER = "X-Pilosa-Trace-Id"
SPAN_HEADER = "X-Pilosa-Span-Id"

DEFAULT_SLOW_THRESHOLD = 0.25   # seconds
DEFAULT_RING_SIZE = 128
DEFAULT_SLOW_RING_SIZE = 64

# Cumulative histogram bucket bounds (seconds) for the /metrics
# latency exposition. The +Inf bucket is emitted explicitly —
# histogram_quantile() returns NaN without it.
LATENCY_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, float("inf"))

_ACTIVE = threading.local()


def _new_id():
    return os.urandom(8).hex()


def active_span():
    """The span currently active on this thread, or None."""
    return getattr(_ACTIVE, "span", None)


class _NopCM:
    """Shared, stateless no-op span: ``with`` it from any thread."""

    __slots__ = ()
    tags = None  # sentinel — instrumentation must not write into it

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def tag(self, **kw):
        pass


NOP_SPAN = _NopCM()


def span(name, **tags):
    """Child span of the thread's active span; a shared no-op when no
    trace is active (the common, disabled-tracing case)."""
    parent = getattr(_ACTIVE, "span", None)
    if parent is None:
        return NOP_SPAN
    return Span(parent.trace, name, parent_id=parent.span_id, tags=tags)


def child_of(parent, name, **tags):
    """Explicit-parent span for work handed to another thread (the
    executor's fan-out): capture ``active_span()`` before spawning,
    open the child inside the thread."""
    if parent is None or parent is NOP_SPAN:
        return NOP_SPAN
    return Span(parent.trace, name, parent_id=parent.span_id, tags=tags)


def trace_headers():
    """Outbound propagation headers for the active trace context, or
    None when no trace is active."""
    sp = getattr(_ACTIVE, "span", None)
    if sp is None:
        return None
    return {TRACE_HEADER: sp.trace.trace_id, SPAN_HEADER: sp.span_id}


class Span:
    """One timed operation. A context manager: entering activates it on
    the current thread, exiting records duration, appends it to its
    trace, and restores the previous active span."""

    __slots__ = ("trace", "name", "span_id", "parent_id", "tags",
                 "start", "duration", "_t0", "_prev")

    def __init__(self, trace, name, parent_id=None, tags=None):
        self.trace = trace
        self.name = name
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.tags = dict(tags) if tags else {}
        self.start = None
        self.duration = None
        self._t0 = None
        self._prev = None

    def tag(self, **kw):
        self.tags.update(kw)

    def __enter__(self):
        self._prev = getattr(_ACTIVE, "span", None)
        _ACTIVE.span = self
        self._t0 = time.perf_counter()
        # Wall-clock anchor derived from the trace's epoch pair so all
        # of one process's spans share a consistent clock.
        self.start = self.trace.epoch0 + (self._t0 - self.trace.perf0)
        return self

    def __exit__(self, exc_type, exc, tb):
        self.duration = time.perf_counter() - self._t0
        if exc is not None:
            self.tags["error"] = f"{type(exc).__name__}: {exc}"[:200]
        self.trace.add(self)
        _ACTIVE.span = self._prev
        if self is self.trace.root:
            self.trace.tracer._finish(self.trace)
        return False

    def to_dict(self):
        return {
            "name": self.name,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "start": self.start,
            "durationMs": (round(self.duration * 1000, 3)
                           if self.duration is not None else None),
            "tags": dict(self.tags),
        }


class Trace:
    """A collection of spans sharing one trace id. Spans append on
    exit (children exit before parents), so the list is complete when
    the root exits."""

    def __init__(self, tracer, trace_id=None):
        self.tracer = tracer
        self.trace_id = trace_id or _new_id()
        self.epoch0 = time.time()
        self.perf0 = time.perf_counter()
        self.spans = []
        # NOT lockcheck-registered: a Trace is per-request — registering
        # would grow the checker's instance registry on every query
        # (lockcheck instruments long-lived locks only).
        self._mu = threading.Lock()
        self.root = None
        self.dropped = 0  # folded into the tracer's total at finish

    def add(self, sp):
        with self._mu:
            if len(self.spans) < self.tracer.max_spans:
                self.spans.append(sp)
            else:
                self.dropped += 1

    def to_dict(self):
        with self._mu:
            spans = [s.to_dict() for s in self.spans]
        out = {
            "traceId": self.trace_id,
            "durationMs": (round(self.root.duration * 1000, 3)
                           if self.root and self.root.duration is not None
                           else None),
            "spans": spans,
            "roots": _build_tree(spans),
        }
        # Per-query resource counts (querystats.py), attached by the
        # handler after the root closes — rendered next to the span
        # tree in ?profile=true responses and the slow-query ring.
        resources = getattr(self, "resources", None)
        if resources:
            out["resources"] = resources
        # Top profiler stacks sampled during this trace's window
        # (observe/profiler.py), attached by the tracer when a slow
        # trace lands in the ring — the "what was the process doing
        # while this was slow" answer, inline with the trace.
        profile = getattr(self, "profile", None)
        if profile:
            out["profile"] = profile
        return out


def _build_tree(span_dicts):
    """Nest flat span dicts by parent links. Spans whose parent is not
    in the set (trace roots; remote fragments whose parent lives on
    the coordinator) become roots, ordered by start time."""
    nodes = {}
    for s in span_dicts:
        n = dict(s)
        n["children"] = []
        nodes[s["spanId"]] = n
    roots = []
    for n in nodes.values():
        parent = nodes.get(n["parentId"]) if n["parentId"] else None
        if parent is not None and parent is not n:
            parent["children"].append(n)
        else:
            roots.append(n)
    key = lambda n: n["start"] or 0  # noqa: E731
    for n in nodes.values():
        n["children"].sort(key=key)
    roots.sort(key=key)
    return roots


def stitch(trace_dicts):
    """Merge ``Trace.to_dict()`` payloads — typically one per cluster
    node, gathered from each node's ``/debug/traces`` — into one span
    tree. All payloads must share one trace id (propagated via
    ``X-Pilosa-Trace-Id``); remote roots resolve under the
    coordinator's fan-out span through their propagated parent id."""
    if not trace_dicts:
        return None
    tids = {t["traceId"] for t in trace_dicts}
    if len(tids) != 1:
        raise ValueError(f"cannot stitch distinct trace ids: {sorted(tids)}")
    spans, seen = [], set()
    for t in trace_dicts:
        for s in t["spans"]:
            if s["spanId"] not in seen:
                seen.add(s["spanId"])
                spans.append(s)
    durations = [t["durationMs"] for t in trace_dicts
                 if t.get("durationMs") is not None]
    return {
        "traceId": tids.pop(),
        "durationMs": max(durations) if durations else None,
        "spans": spans,
        "roots": _build_tree(spans),
    }


class Tracer:
    """Recording tracer: bounded ring of recent traces, slow-query
    ring, and (optionally) slow-query / latency-bucket counters on a
    stats client so ``/metrics`` exposes them."""

    enabled = True

    def __init__(self, ring_size=DEFAULT_RING_SIZE,
                 slow_threshold=DEFAULT_SLOW_THRESHOLD,
                 slow_ring_size=DEFAULT_SLOW_RING_SIZE,
                 stats=None, max_spans=4096):
        self.slow_threshold = slow_threshold
        self.max_spans = max_spans
        self._ring = deque(maxlen=max(int(ring_size), 1))
        self._slow_ring = deque(maxlen=max(int(slow_ring_size), 1))
        self._latencies = deque(maxlen=512)
        self._mu = lockcheck.register("tracing.Tracer._mu",
                                      threading.Lock())
        self._finished = 0
        self._slow = 0
        self._dropped = 0
        self.stats = stats
        # Pre-tagged bucket clients: with_tags per finish would allocate
        # a client per bucket per query.
        self._buckets = ([(le, stats.with_tags(
                              "le:+Inf" if le == float("inf")
                              else f"le:{le}"))
                          for le in LATENCY_BUCKETS] if stats else [])

    # ------------------------------------------------------------ record

    def start(self, name, trace_id=None, parent_id=None, **tags):
        """Open a root span (a new trace). ``trace_id``/``parent_id``
        from propagated headers stitch this trace under a remote
        parent."""
        trace = Trace(self, trace_id=trace_id)
        root = Span(trace, name, parent_id=parent_id, tags=tags)
        trace.root = root
        return root

    def span(self, name, **tags):
        """Child of the thread's active span, or a fresh root when no
        trace is active (direct executor use in tests)."""
        parent = getattr(_ACTIVE, "span", None)
        if parent is not None:
            return Span(parent.trace, name, parent_id=parent.span_id,
                        tags=tags)
        return self.start(name, **tags)

    def _finish(self, trace):
        dur = trace.root.duration
        slow = dur is not None and dur >= self.slow_threshold
        with self._mu:
            self._ring.append(trace)
            self._finished += 1
            self._dropped += trace.dropped
            if dur is not None:
                self._latencies.append(dur)
            if slow:
                self._slow += 1
                self._slow_ring.append(trace)
        if slow:
            # Slow-query linkage: stamp the trace with the top stacks
            # the continuous profiler sampled during its window.
            # Lazy import (tracing must not import observe at module
            # load); one `.enabled` attribute read when disabled.
            from pilosa_tpu.observe import profiler as profiler_mod

            prof = profiler_mod.ACTIVE
            if prof.enabled:
                # Anchor on the ROOT SPAN's own clock, not trace.perf0:
                # the trace is constructed before the root enters, so
                # a perf0-based window ends early and drops samples
                # taken in the query's final microseconds.
                t0 = (trace.root._t0 if trace.root._t0 is not None
                      else trace.perf0)
                trace.profile = prof.window_top(t0, t0 + dur, k=5)
        st = self.stats
        if st is not None and dur is not None:
            if slow:
                st.count("slow_queries_total", 1)
            st.count("query_latency_seconds_count", 1)
            st.count("query_latency_seconds_sum", dur)
            for le, client in self._buckets:
                if dur <= le:
                    client.count("query_latency_seconds_bucket", 1)

    # ------------------------------------------------------------- read

    def recent(self, n=32, slow=False, trace_id=None):
        """Newest-first trace dicts from the requested ring."""
        with self._mu:
            ring = list(self._slow_ring if slow else self._ring)
        out = []
        for trace in reversed(ring):
            if trace_id and trace.trace_id != trace_id:
                continue
            out.append(trace.to_dict())
            if len(out) >= n:
                break
        return out

    def ring_len(self, slow=False):
        with self._mu:
            return len(self._slow_ring if slow else self._ring)

    def summary(self):
        """Compact stats for diagnostics reports: totals plus p50/p99
        over the recent-latency window."""
        with self._mu:
            lats = sorted(self._latencies)
            out = {"traces": self._finished, "slowQueries": self._slow,
                   "droppedSpans": self._dropped}
        if lats:
            out["p50Ms"] = round(lats[len(lats) // 2] * 1000, 3)
            out["p99Ms"] = round(
                lats[min(len(lats) - 1, (len(lats) * 99) // 100)] * 1000, 3)
        return out


class NopTracer:
    """Disabled tracing: every surface answers, nothing records —
    the ``NopStatsClient`` pattern."""

    enabled = False
    slow_threshold = DEFAULT_SLOW_THRESHOLD

    def start(self, name, trace_id=None, parent_id=None, **tags):
        return NOP_SPAN

    def span(self, name, **tags):
        return NOP_SPAN

    def recent(self, n=32, slow=False, trace_id=None):
        return []

    def ring_len(self, slow=False):
        return 0

    def summary(self):
        return {}


NOP = NopTracer()
