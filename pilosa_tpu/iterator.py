"""(rowID, columnID) stream iterators (ref: iterator.go:24-194).

Used by export, block sync, and merge logic. The reference defines an
``Iterator`` protocol {Seek, Next, Peek} over ascending (row, column)
pairs plus Buf/Limit/Slice wrappers; kept here for API parity and host
pipelines that want streaming rather than whole-array extraction.
"""
import numpy as np

from pilosa_tpu import SLICE_WIDTH

EOF = (None, None)


class SliceIterator:
    """Iterate parallel rowIDs/columnIDs arrays (ref: iterator.go
    SliceIterator)."""

    def __init__(self, row_ids, column_ids):
        if len(row_ids) != len(column_ids):
            raise ValueError("mismatched row/column id lengths")
        order = np.lexsort((np.asarray(column_ids), np.asarray(row_ids)))
        self.rows = np.asarray(row_ids)[order]
        self.cols = np.asarray(column_ids)[order]
        self.i = 0

    def seek(self, row_id, column_id):
        self.i = 0
        while self.i < len(self.rows) and (
                (self.rows[self.i], self.cols[self.i]) < (row_id, column_id)):
            self.i += 1

    def peek(self):
        if self.i >= len(self.rows):
            return EOF
        return int(self.rows[self.i]), int(self.cols[self.i])

    def next(self):
        pair = self.peek()
        if pair is not EOF:
            self.i += 1
        return pair

    def __iter__(self):
        while True:
            pair = self.next()
            if pair is EOF:
                return
            yield pair


class FragmentIterator:
    """Stream a fragment's pairs in ascending position order — the
    roaring-iterator analog (ref: Fragment storage iteration via
    roaring.Iterator, roaring.go:834-998)."""

    def __init__(self, fragment):
        self.fragment = fragment
        self._row_ids = fragment.rows()
        self._row_idx = 0
        self._bits = None
        self._bit_idx = 0

    def _load_row(self):
        from pilosa_tpu import native

        while self._row_idx < len(self._row_ids):
            row_id = self._row_ids[self._row_idx]
            words = self.fragment.row_words(row_id)
            if native.available():
                bits = native.extract_positions(words)
            else:
                bits = np.flatnonzero(np.unpackbits(
                    words.view(np.uint8), bitorder="little")).astype(np.uint64)
            if len(bits):
                self._bits = bits
                self._bit_idx = 0
                return row_id
            self._row_idx += 1
        return None

    def seek(self, row_id, column_id=0):
        self._row_idx = 0
        while (self._row_idx < len(self._row_ids)
               and self._row_ids[self._row_idx] < row_id):
            self._row_idx += 1
        self._bits = None
        self._seek_col = column_id if (
            self._row_idx < len(self._row_ids)
            and self._row_ids[self._row_idx] == row_id) else 0

    def next(self):
        seek_col = getattr(self, "_seek_col", 0)
        while True:
            if self._bits is None:
                row_id = self._load_row()
                if row_id is None:
                    return EOF
            row_id = self._row_ids[self._row_idx]
            while self._bit_idx < len(self._bits):
                col = int(self._bits[self._bit_idx])
                self._bit_idx += 1
                if col >= seek_col:
                    self._seek_col = 0
                    return row_id, col
            self._bits = None
            self._row_idx += 1
            seek_col = 0

    def __iter__(self):
        while True:
            pair = self.next()
            if pair is EOF:
                return
            yield pair


class LimitIterator:
    """Stop at (maxRowID, maxColumnID) exclusive upper bound
    (ref: iterator.go LimitIterator)."""

    def __init__(self, itr, max_row_id, max_column_id=SLICE_WIDTH):
        self.itr = itr
        self.max_row_id = max_row_id
        self.max_column_id = max_column_id
        self._done = False

    def seek(self, row_id, column_id=0):
        self.itr.seek(row_id, column_id)
        self._done = False

    def next(self):
        if self._done:
            return EOF
        pair = self.itr.next()
        if pair is EOF:
            return EOF
        row, col = pair
        if row >= self.max_row_id or col >= self.max_column_id:
            self._done = True
            return EOF
        return pair

    def __iter__(self):
        while True:
            pair = self.next()
            if pair is EOF:
                return
            yield pair


class BufIterator:
    """One-pair pushback buffer (ref: iterator.go BufIterator) —
    the primitive the consensus merge walks with."""

    def __init__(self, itr):
        self.itr = itr
        self._buf = None

    def seek(self, row_id, column_id=0):
        self.itr.seek(row_id, column_id)
        self._buf = None

    def peek(self):
        if self._buf is None:
            self._buf = self.itr.next()
        return self._buf

    def next(self):
        pair = self.peek()
        self._buf = None
        return pair

    def unread(self, pair):
        if self._buf is not None:
            raise ValueError("unread buffer full")
        self._buf = pair

    def __iter__(self):
        while True:
            pair = self.next()
            if pair is EOF:
                return
            yield pair
