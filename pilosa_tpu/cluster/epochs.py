"""Distributed mutation epochs — the cluster-wide validity protocol
behind every warm fast path.

A single node already has a complete warm story: the process-local
per-index mutation epoch (storage/fragment.py) keys the master
response replay, the executor's result/prelude memos, and the worker
response caches, and every local write bumps it BEFORE the write's
HTTP response — so epoch equality is a sufficient condition for cache
validity, checked in O(1). On a cluster that counter sees only this
node's writes, which is why rounds 1-5 gated every warm tier to
``len(cluster.nodes) <= 1``.

This module extends the epoch to a per-index **epoch vector**
(node host → counter) so the same equality check validates across
nodes:

- **Piggyback.** Every internal RPC response and every membership
  heartbeat carries the sender's current counters in ONE header pair
  (``X-Pilosa-Epochs``) / one status field. The internal client feeds
  each observation into this registry in-line, so a coordinator that
  fans a write out to a replica learns the replica's bumped counter
  from the write's own response — read-your-writes through any
  coordinator that served or relayed the write is strict, with zero
  extra round trips.
- **Probes.** Cross-coordinator visibility (a write this node never
  saw) is closed by cheap parallel epoch probes
  (``GET /internal/epochs``) issued before a cached replay whenever a
  needed peer's last observation is older than ``ttl`` (default: one
  heartbeat interval). The TTL is therefore the documented staleness
  bound: a remote-only write becomes visible to this node's caches at
  most ``ttl`` seconds after it lands.
- **Cold, never stale.** An unknown peer, a stale observation that a
  probe could not refresh, or a dropped propagation (the
  ``client.epoch.stale`` failpoint) makes ``token()`` return ``None``
  — and every cache tier treats ``None`` as "do not replay, do not
  store". Degradation is always to the full fan-out path.

A validity token is the tuple ``((host, counter), ...)`` over the
nodes owning the queried slices, sorted by host. Tokens compare by
equality only — the per-node counters are monotone within a process
lifetime, and a peer restart (counter reset) changes the token, which
invalidates; it can never accidentally re-validate an entry because
the stored token embeds the exact counter it was minted against.

Per-index scoping rides along: the wire format carries one counter
per index (the peer's scoped ``mutation_epoch(index)``) plus a ``*``
process total used for indexes the peer had not created when it
published — so a write-heavy index on one node doesn't flush another
node's caches for unrelated indexes.
"""
import os
import threading
import time
import urllib.parse

from pilosa_tpu import faults
from pilosa_tpu.storage import fragment as _frag
from pilosa_tpu import lockcheck

# The ONE piggyback header pair every internal RPC response carries on
# a multi-node cluster: "host;idx=ctr,idx=ctr,...".
EPOCH_HEADER = "X-Pilosa-Epochs"

# Per-process boot nonce, shipped with every counter set (key "!") and
# folded into validity tokens: counters are process-local and restart
# at 0, so without it a restarted peer whose counter climbs back to a
# stored token's value could re-validate a pre-restart cache entry —
# silently missing every write of the new incarnation. An int so it
# rides the same k=int(v) wire coercion as the counters.
INCARNATION_KEY = "!"
_BOOT_NONCE = int.from_bytes(os.urandom(8), "little")

# With no explicit [cluster] epoch-probe-ttl, freshness follows the
# membership heartbeat interval (HTTPNodeSet default) — heartbeats
# already refresh every peer's counters continuously, so the serving
# path almost never has to probe.
DEFAULT_PROBE_TTL = 5.0

# The aggregate wire key for "any index I didn't list": the process
# epoch total. Index names are URL-quoted on the wire, so a literal
# "*" index can never collide ("*" survives quote() but an index named
# "*" would be rejected upstream; the quoting keeps ;,= unambiguous).
TOTAL_KEY = "*"


def local_epochs(holder):
    """This node's current per-index counters + process total + boot
    nonce, the payload of every piggyback/probe/heartbeat."""
    out = {}
    for name in list(holder.indexes):
        out[name] = _frag.mutation_epoch(name)
    out[TOTAL_KEY] = _frag.epoch_total()
    out[INCARNATION_KEY] = _BOOT_NONCE
    return out


def encode_epochs(host, epochs):
    parts = ",".join(
        f"{urllib.parse.quote(str(k), safe='*')}={int(v)}"
        for k, v in sorted(epochs.items()))
    return f"{urllib.parse.quote(host, safe=':')};{parts}"


def decode_epochs(value):
    """-> (host, {index: counter}); raises ValueError on garbage."""
    head, _, rest = value.partition(";")
    host = urllib.parse.unquote(head)
    if not host:
        raise ValueError("epoch header missing host")
    epochs = {}
    for item in rest.split(","):
        if not item:
            continue
        k, sep, v = item.partition("=")
        if not sep:
            raise ValueError(f"bad epoch entry: {item!r}")
        epochs[urllib.parse.unquote(k)] = int(v)
    return host, epochs


class ClusterEpochs:
    """Per-process epoch-vector registry (one per multi-node Server).

    Thread-safe; the hot paths (header memo, token assembly) are a few
    dict reads under a short lock. Single-node servers never construct
    one — callers hold ``None`` and skip every hook with one attribute
    read, the nop-tracer discipline."""

    enabled = True
    HEADER = EPOCH_HEADER

    def __init__(self, local_host, holder, cluster=None, client=None,
                 ttl=DEFAULT_PROBE_TTL, probe_timeout=None, pool=None):
        self.local_host = local_host
        self.holder = holder
        self.cluster = cluster
        self.client = client
        self.ttl = float(ttl)
        # A probe bounds how long a cached replay can stall on a dead
        # peer: never longer than the staleness budget itself.
        self.probe_timeout = (probe_timeout if probe_timeout is not None
                              else min(1.0, self.ttl) or 1.0)
        # Failed probes back off for one TTL — a dead peer means COLD
        # for that window, not a connect-timeout per cached request.
        self.probe_backoff = self.ttl
        self._mu = lockcheck.register("epochs.ClusterEpochs._mu",
                                      threading.Lock())
        self._peers = {}      # host -> (epochs dict, monotonic seen_at)
        self._probe_at = {}   # host -> monotonic of last probe ATTEMPT
        self._version = 0     # bumps on every observed change
        self._hdr_memo = (None, None)
        self._publish = None  # publish_cluster_version hook (workers)
        self._pool = pool     # FanoutPool for parallel probes (lazy)
        self.counters = {"observations": 0, "changes": 0, "probes": 0,
                         "probe_failures": 0, "cold": 0, "tokens": 0}
        # Flight recorder (observe.events), server-installed; None
        # when off. Cold flips and probe failures are journal events.
        self.events = None
        self._published_cold = False

    # ---------------------------------------------------------- piggyback

    def header_value(self):
        """The encoded local vector for response piggyback, memoized
        on the process epoch total (steady state: one int compare)."""
        tot = _frag.epoch_total()
        memo = self._hdr_memo
        if memo[0] == tot:
            return memo[1]
        val = encode_epochs(self.local_host, local_epochs(self.holder))
        self._hdr_memo = (tot, val)
        return val

    def observe_header(self, value):
        try:
            host, epochs = decode_epochs(value)
        except (ValueError, TypeError):
            return
        self.observe(host, epochs)

    def observe(self, host, epochs):
        """Learn a peer's counters (from an RPC response header, a
        heartbeat, or a probe). The ``client.epoch.stale`` failpoint
        models a partition of the propagation plane: armed, the
        observation is dropped on the floor — caches then degrade to
        cold (token() -> None), never to stale."""
        if host == self.local_host or not isinstance(epochs, dict):
            return
        if faults.ACTIVE.enabled:
            try:
                if faults.ACTIVE.fire("client.epoch.stale"):
                    return
            except OSError:
                return  # error(...)-armed: same verdict, dropped
        try:
            epochs = {str(k): int(v) for k, v in epochs.items()}
        except (TypeError, ValueError):
            return
        with self._mu:
            self.counters["observations"] += 1
            cur = self._peers.get(host)
            changed = cur is None or cur[0] != epochs
            if changed:
                self._version += 1
                self.counters["changes"] += 1
            self._peers[host] = (epochs, time.monotonic())
            self._probe_at.pop(host, None)
            if changed and self._publish is not None:
                # Synchronous, and UNDER _mu: a relayed write's
                # response observation must reach the worker-published
                # counter before the relaying coordinator acks the
                # write (read-your-writes through this node's worker
                # caches), and publication must serialize with the
                # staleness monitor — a compute-then-publish race
                # could roll the published version BACK and
                # re-validate pre-write worker entries (stale replay).
                self._publish(self._version + 1)

    # ------------------------------------------------------------- tokens

    def _peer_counter_locked(self, host, index, now):
        """(incarnation, counter) for a FRESH peer entry, else None."""
        ent = self._peers.get(host)
        if ent is None or now - ent[1] > self.ttl:
            return None
        epochs = ent[0]
        ctr = epochs.get(index)
        if ctr is None:
            ctr = epochs.get(TOTAL_KEY)
        if ctr is None:
            return None
        return epochs.get(INCARNATION_KEY, 0), ctr

    def peer_fresh(self, host):
        """True when ``host`` is this node or its epoch entry is
        within TTL — the hedged-read staleness gate: a routed or
        hedged leg only targets replicas whose epoch plane is
        current, so a partitioned peer (entries aging out) drops out
        of the candidate set rather than serving a possibly-stale
        answer. Mirrors ``token()``'s freshness rule without the
        per-index counter math."""
        if host == self.local_host:
            return True
        now = time.monotonic()
        with self._mu:
            ent = self._peers.get(host)
        return ent is not None and now - ent[1] <= self.ttl

    def token(self, index, hosts):
        """Validity token over ``hosts`` (the owner set of the queried
        slices; the local host reads the live local counter). Each
        peer entry carries (host, incarnation, counter) so a restarted
        peer — counters reset to 0 — can never re-validate a
        pre-restart entry even if its new counter climbs back to the
        stored value. ``None`` when any peer is unknown or stale —
        cold, never stale."""
        now = time.monotonic()
        parts = []
        with self._mu:
            self.counters["tokens"] += 1
            for h in sorted(set(hosts)):
                if h == self.local_host:
                    continue
                ent = self._peer_counter_locked(h, index, now)
                if ent is None:
                    self.counters["cold"] += 1
                    return None
                parts.append((h, ent[0], ent[1]))
        parts.append((self.local_host, _BOOT_NONCE,
                      _frag.mutation_epoch(index)))
        parts.sort()
        return tuple(parts)

    def ensure_fresh(self, index, hosts):
        """token(), refreshing stale peers first with cheap parallel
        epoch probes (bounded by ``probe_timeout``; failed probes back
        off for one TTL). The replay-gate entry point: at most one
        probe round per peer per TTL, amortized over every cached
        replay inside the window."""
        tok = self.token(index, hosts)
        if tok is not None:
            return tok
        now = time.monotonic()
        stale = []
        with self._mu:
            for h in set(hosts):
                if h == self.local_host:
                    continue
                ent = self._peers.get(h)
                if ent is not None and now - ent[1] <= self.ttl:
                    continue
                if now - self._probe_at.get(h, -1e9) < self.probe_backoff:
                    continue  # recently probed and still cold: stay cold
                self._probe_at[h] = now
                stale.append(h)
        if stale:
            self._probe_hosts(stale)
        return self.token(index, hosts)

    def validate(self, index, stored):
        """Re-derive the current token for a STORED token's own host
        set (cache-hit validation: the entry remembers exactly which
        nodes it covered). Equal -> valid; None/unequal -> miss."""
        return self.ensure_fresh(index, [p[0] for p in stored])

    # ------------------------------------------------------------- probes

    def _probe_hosts(self, hosts):
        if self.client is None or self.cluster is None:
            return
        nodes = [n for h in hosts
                 for n in (self.cluster.node_by_host(h),) if n is not None]
        if not nodes:
            return

        def probe(node):
            with self._mu:
                self.counters["probes"] += 1
            try:
                out = self.client.epochs_fetch(
                    node, timeout=self.probe_timeout)
            except Exception:  # noqa: BLE001 — unprobeable means COLD
                with self._mu:
                    self.counters["probe_failures"] += 1
                ev = self.events
                if ev is not None:
                    ev.emit("epoch.probe_failed", peer=node.host)
                return
            eps = out.get("epochs")
            if isinstance(eps, dict):
                # Keyed by the MEMBERSHIP host we probed, not the
                # peer's self-reported bind (a ":0"-bound peer knows
                # itself by resolved port; token() looks up by the
                # cluster's node list).
                self.observe(node.host, eps)

        if len(nodes) == 1:
            probe(nodes[0])
            return
        pool = self._pool
        if pool is None:
            from pilosa_tpu.utils.fanpool import FanoutPool

            pool = self._pool = FanoutPool(max_idle=4)
        waits = [pool.run(lambda n=n: probe(n)) for n in nodes]
        for w in waits:
            w.wait()

    # ------------------------------------------------- worker publication

    def attach_worker_publisher(self, publish):
        """Wire the mmap word-1 publisher (fragment.
        publish_cluster_version) so worker response caches see vector
        movement: every observed change publishes ``version+1``;
        ``publish_for_workers`` flips to 0 (= cold) when any peer goes
        stale, so a partition degrades workers to relay, never to
        stale replay."""
        self._publish = publish
        self.publish_for_workers()

    def publish_for_workers(self, probe=False):
        if self._publish is None:
            return
        now = time.monotonic()
        stale = []
        with self._mu:
            for node in (self.cluster.nodes if self.cluster else ()):
                if node.host == self.local_host:
                    continue
                ent = self._peers.get(node.host)
                if ent is None or now - ent[1] > self.ttl:
                    stale.append(node.host)
        if stale and probe:
            self._probe_hosts(stale)
            now = time.monotonic()
            with self._mu:
                stale = [h for h in stale
                         if (self._peers.get(h) is None
                             or now - self._peers[h][1] > self.ttl)]
        flipped = None
        with self._mu:
            # UNDER _mu, like observe()'s publish: computing the
            # version outside the lock could interleave with a
            # concurrent observation and publish a STALE (smaller)
            # version over its newer one, re-validating pre-write
            # worker entries. Serialized, word 1 only ever moves
            # forward — or to 0 (cold), the intentional exception.
            self._publish(0 if stale else self._version + 1)
            cold = bool(stale)
            if cold != self._published_cold:
                self._published_cold = cold
                flipped = list(stale)
        if flipped is not None:
            ev = self.events
            if ev is not None:
                if flipped:
                    ev.emit("epoch.cold", stalePeers=flipped)
                else:
                    ev.emit("epoch.fresh")

    # -------------------------------------------------------------- intro

    def snapshot(self):
        now = time.monotonic()
        with self._mu:
            peers = {
                host: {"ageSeconds": round(now - at, 3),
                       "fresh": now - at <= self.ttl,
                       "epochs": dict(eps)}
                for host, (eps, at) in self._peers.items()}
            return {"enabled": True, "host": self.local_host,
                    "ttlSeconds": self.ttl,
                    "probeTimeout": self.probe_timeout,
                    "version": self._version,
                    "local": local_epochs(self.holder),
                    "peers": peers, "counters": dict(self.counters)}

    def metrics(self):
        """Flat dict for the /metrics ``pilosa_epoch_*`` group."""
        with self._mu:
            out = {f"{k}_total": v for k, v in self.counters.items()}
            out["version"] = self._version
            out["peers_known"] = len(self._peers)
            return out

    def close(self):
        if self._pool is not None:
            self._pool.close()
