"""Metadata broadcast plane (ref: broadcast.go).

Schema DDL and slice-creation messages replicate to every node. The
reference has SendSync (HTTP POST to every peer's /cluster/message) and
SendAsync (gossip). Without an on-device gossip analog, async sends use
a background thread pool over the same HTTP plane; membership is
delegated to a NodeSet (static here; the coordinator-based variant lives
with multi-host JAX runtime wiring).
"""
import threading

STATUS_INTERVAL = 60  # seconds, max-slice poll (ref: server.go:321 monitorMaxSlices)


class NopBroadcaster:
    """(ref: broadcast.go:70-100)."""

    def send_sync(self, msg):
        pass

    def send_async(self, msg):
        pass


class HTTPBroadcaster:
    """SendSync to every peer (ref: Server.SendSync server.go:444-465)."""

    def __init__(self, client, cluster, local_host):
        self.client = client
        self.cluster = cluster
        self.local_host = local_host

    def _peers(self):
        # Skip known-DOWN members: they are reconciled with a schema
        # push when membership sees them again (Server._on_peer_rejoin),
        # mirroring the reference's gossip state exchange on rejoin.
        nodes = (self.cluster.node_set.nodes()
                 if self.cluster.node_set is not None else self.cluster.nodes)
        return [n for n in nodes if n.host != self.local_host]

    def send_sync(self, msg):
        errors = []
        for node in self._peers():
            try:
                self.client.send_message(node, msg)
            except Exception as e:  # noqa: BLE001 — collect and report
                errors.append((node.host, str(e)))
        if errors:
            raise RuntimeError(f"broadcast errors: {errors}")

    def send_async(self, msg):
        def run(node):
            try:
                self.client.send_message(node, msg)
            except Exception:  # noqa: BLE001 — async best-effort like gossip
                pass

        for node in self._peers():
            threading.Thread(target=run, args=(node,), daemon=True).start()


class StaticNodeSet:
    """Static membership from config (ref: broadcast.go:39-61)."""

    def __init__(self, nodes=None):
        self._nodes = list(nodes or [])

    def open(self):
        return self

    def close(self):
        pass

    def nodes(self):
        return list(self._nodes)

    def join(self, nodes):
        for n in nodes:
            if n not in self._nodes:
                self._nodes.append(n)
