"""Metadata broadcast plane (ref: broadcast.go).

Schema DDL and slice-creation messages replicate to every node. The
reference has SendSync (HTTP POST to every peer's /cluster/message) and
SendAsync (gossip). Without an on-device gossip analog, async sends use
a background thread pool over the same HTTP plane; membership is
delegated to a NodeSet (static here; the coordinator-based variant lives
with multi-host JAX runtime wiring).
"""
import threading
from pilosa_tpu import lockcheck

STATUS_INTERVAL = 60  # seconds, max-slice poll (ref: server.go:321 monitorMaxSlices)


class NopBroadcaster:
    """(ref: broadcast.go:70-100)."""

    def send_sync(self, msg):
        pass

    def send_async(self, msg):
        pass


class HTTPBroadcaster:
    """SendSync to every peer (ref: Server.SendSync server.go:444-465).

    Async sends that fail (peer transiently unreachable but not yet
    marked DOWN) enter a bounded retry queue drained by a background
    thread — the HTTP-plane analog of memberlist's
    TransmitLimitedQueue re-gossiping undelivered broadcasts
    (gossip.go SendAsync → QueueBroadcast). Known-DOWN peers are still
    reconciled by the rejoin schema push instead, so the queue only
    covers the blip window before membership notices."""

    RETRY_INTERVAL = 5      # seconds between queue drains
    RETRY_MAX = 12          # attempts per message before giving up
    QUEUE_MAX = 1024        # bounded: DDL is low-rate; drop oldest

    def __init__(self, client, cluster, local_host):
        self.client = client
        self.cluster = cluster
        self.local_host = local_host
        self._retry = []     # [(coalesce_key, host, msg, attempts)]
        self._mu = lockcheck.register("broadcast.HTTPBroadcaster._mu",
                                      threading.Lock())
        self._closing = threading.Event()
        self._retry_thread = None

    def _peers(self):
        # Skip known-DOWN members: they are reconciled with a schema
        # push when membership sees them again (Server._on_peer_rejoin),
        # mirroring the reference's gossip state exchange on rejoin.
        nodes = (self.cluster.node_set.nodes()
                 if self.cluster.node_set is not None else self.cluster.nodes)
        return [n for n in nodes if n.host != self.local_host]

    def send_sync(self, msg):
        errors = []
        for node in self._peers():
            try:
                self.client.send_message(node, msg)
            except Exception as e:  # noqa: BLE001 — collect and report
                errors.append((node.host, str(e)))
        if errors:
            raise RuntimeError(f"broadcast errors: {errors}")

    # How long send_async waits for parallel deliveries before letting
    # the write proceed; stragglers keep running and self-enqueue.
    ASYNC_WAIT = 3.0

    def send_async(self, msg):
        """Best-effort delivery that never raises. Peers are posted in
        PARALLEL and the caller waits up to ASYNC_WAIT: healthy peers
        get the message before the write returns (so a client that
        writes through node A and immediately reads through node B
        sees its new slice), while a black-holed peer costs the write
        at most the bounded wait — its daemon thread finishes on its
        own and queues the message for retry on failure. The
        reference's SendAsync has the same at-least-eventually contract
        via gossip (broadcast.go:116)."""
        import time

        threads = []

        def run(node):
            try:
                self.client.send_message(node, msg, timeout=5)
            except Exception:  # noqa: BLE001 — queue for retry
                self._enqueue(node.host, msg)

        for node in self._peers():
            t = threading.Thread(target=run, args=(node,), daemon=True)
            t.start()
            threads.append(t)
        deadline = time.monotonic() + self.ASYNC_WAIT
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))

    # ----------------------------------------------------------- retry queue

    @staticmethod
    def _coalesce_key(host, msg):
        """Messages that supersede each other share a key: repeated
        create-slice for one (host, index, inverse) keeps only the max
        slice (set_remote_max_slice is a monotonic max), and re-sending
        the same DDL is idempotent — so a flapping peer's redundant
        retries can never crowd another host's sole pending message
        out of the bounded queue."""
        return (host, msg.get("type"), msg.get("index"), msg.get("frame"),
                msg.get("name"), msg.get("field"), msg.get("view"),
                msg.get("inverse"))

    def _enqueue(self, host, msg, attempts=0):
        key = self._coalesce_key(host, msg)
        with self._mu:
            for i, (k, _, m, att) in enumerate(self._retry):
                if k == key:
                    if (msg.get("type") == "create-slice"
                            and m.get("slice", 0) > msg.get("slice", 0)):
                        msg = m
                    self._retry[i] = (key, host, msg, min(att, attempts))
                    break
            else:
                if len(self._retry) >= self.QUEUE_MAX:
                    self._retry.pop(0)
                self._retry.append((key, host, msg, attempts))
            if self._retry_thread is None:
                self._retry_thread = threading.Thread(
                    target=self._retry_loop, daemon=True)
                self._retry_thread.start()

    def _drain_once(self):
        with self._mu:
            pending, self._retry = self._retry, []
        by_host = {n.host: n for n in self.cluster.nodes}
        for _, host, msg, attempts in pending:
            node = by_host.get(host)
            if node is None:
                continue  # peer left the cluster
            ns = self.cluster.node_set
            if ns is not None and hasattr(ns, "is_down") and ns.is_down(host):
                continue  # rejoin schema push owns reconciliation now
            try:
                self.client.send_message(node, msg)
            except Exception:  # noqa: BLE001 — still unreachable
                if attempts + 1 < self.RETRY_MAX:
                    self._enqueue(host, msg, attempts + 1)

    def _retry_loop(self):
        while not self._closing.wait(self.RETRY_INTERVAL):
            self._drain_once()

    def pending_retries(self):
        with self._mu:
            return len(self._retry)

    def close(self):
        self._closing.set()


class StaticNodeSet:
    """Static membership from config (ref: broadcast.go:39-61)."""

    def __init__(self, nodes=None):
        self._nodes = list(nodes or [])

    def open(self):
        return self

    def close(self):
        pass

    def nodes(self):
        return list(self._nodes)

    def join(self, nodes):
        for n in nodes:
            if n not in self._nodes:
                self._nodes.append(n)
