"""HTTP heartbeat membership — failure detection without gossip
(ref: gossip/gossip.go wraps memberlist SWIM; the TPU build replaces it
with a coordinator-friendly heartbeat NodeSet since there is no
on-device gossip analog; the polling fallback mirrors monitorMaxSlices
server.go:321-357).

SWIM-shaped, like memberlist, rather than everyone-probes-everyone:

- **Probe subsets.** Each round probes at most ``probe_subset`` peers
  drawn from a shuffled cycle (full coverage every ceil((n-1)/k)
  rounds), so cluster-wide probe traffic is O(N·k) per interval, not
  O(N²) — the same scaling memberlist gets from its random probe
  order (gossip.go:30-41 delegating to memberlist's probe loop).
- **Suspicion via indirect probes.** A peer that fails
  ``suspect_after`` consecutive direct probes is not declared DOWN
  outright: up to ``indirect_n`` other live peers are asked to probe
  it (GET /internal/probe on the helper, the analog of SWIM's
  indirect ping), and any success clears the suspicion — a partition
  between two nodes doesn't false-positive a healthy third-party-
  reachable peer.

DOWN peers drop from ``nodes()`` (which feeds Cluster.node_states and
the executor's failover remap). A recovered peer rejoins automatically
on its next successful probe and gets a schema push, the same
reconciliation the reference does via gossip state exchange
(LocalState/MergeRemoteState).
"""
import logging
import random
import threading
from pilosa_tpu import lockcheck

logger = logging.getLogger(__name__)


class HTTPNodeSet:
    def __init__(self, cluster, local_host, client, interval=5,
                 suspect_after=3, on_rejoin=None, probe_subset=3,
                 indirect_n=2, status_fn=None, merge_fn=None):
        self.cluster = cluster
        self.local_host = local_host
        self.client = client
        self.interval = interval
        self.suspect_after = suspect_after
        self.on_rejoin = on_rejoin
        self.probe_subset = probe_subset
        self.indirect_n = indirect_n
        # Heartbeat piggyback (memberlist LocalState/MergeRemoteState
        # analog): status_fn() -> compact NodeStatus sent with each
        # probe; merge_fn(peer_status) applies the reply. With these
        # wired, schema/max-slice convergence is continuous — the 60 s
        # poll becomes a backstop.
        self.status_fn = status_fn
        self.merge_fn = merge_fn
        self._hb_unsupported = set()  # hosts on pre-heartbeat builds
        self._hb_retry_rounds = 120   # re-try unsupported hosts (~10min)
        self._peer_digests = {}       # host -> last seen schemaDigest
        self._digest_pairs = {}       # host -> ((mine, theirs), count)
        self._rounds = 0
        self._failures = {}   # host -> consecutive failed probes
        self._down = set()
        self._cycle = []      # shuffled peer-host cycle for subsets
        self._mu = lockcheck.register("membership.HTTPNodeSet._mu",
                                      threading.Lock())
        self._closing = threading.Event()
        self._thread = None
        self._rng = random.Random()
        # Flight recorder (observe.events), server-installed; None
        # when off. Join/down/rejoin transitions are journal events.
        self.events = None

    # ---------------------------------------------------------- NodeSet API

    def open(self):
        self._thread = threading.Thread(target=self._probe_loop, daemon=True)
        self._thread.start()
        return self

    def close(self):
        self._closing.set()

    def nodes(self):
        """Live members (ref: GossipNodeSet.Nodes gossip.go:44-51)."""
        with self._mu:
            return [n for n in self.cluster.nodes if n.host not in self._down]

    def join(self, nodes):
        """Add peers to the live node list. With an ACTIVE placement
        (cluster/placement.py) a join grants RPC reachability only —
        slice ownership stays pinned to the committed generation until
        an operator resize (POST /cluster/resize) commits, so
        membership churn can no longer instantly reassign slices the
        new node does not hold."""
        for n in nodes:
            if self.cluster.node_by_host(n.host) is None:
                self.cluster.nodes.append(n)
                self.cluster.topology_version += 1
                ev = self.events
                if ev is not None:
                    ev.emit("membership.join", peer=n.host)

    def is_down(self, host):
        with self._mu:
            return host in self._down

    # -------------------------------------------------------------- probing

    def _peers(self):
        return [n for n in self.cluster.nodes if n.host != self.local_host]

    def _next_subset(self):
        """Next ≤ probe_subset peers from the shuffled cycle. DOWN
        peers are always included on top (cheap — they answer or
        time out — and rejoin detection must not wait a full cycle)."""
        peers = self._peers()
        by_host = {n.host: n for n in peers}
        with self._mu:
            self._cycle = [h for h in self._cycle if h in by_host]
            picked = []
            while len(picked) < min(self.probe_subset, len(by_host)):
                if not self._cycle:
                    hosts = list(by_host)
                    self._rng.shuffle(hosts)
                    self._cycle = hosts
                h = self._cycle.pop()
                if h not in picked:
                    picked.append(h)
            down = [h for h in self._down if h in by_host and h not in picked]
        return [by_host[h] for h in dict.fromkeys(picked + down)]

    def probe_once(self):
        self._rounds += 1
        if (self._hb_unsupported
                and self._rounds % self._hb_retry_rounds == 0):
            # Rolling upgrades: a host that once 404'd the heartbeat
            # endpoint may have been upgraded since — re-offer it
            # periodically so state exchange resumes without a
            # down/up transition.
            self._hb_unsupported.clear()
        for node in self._next_subset():
            self._probe_node(node)

    def _probe_node(self, node):
        ok = self._probe(node)
        if not ok:
            with self._mu:
                n = self._failures.get(node.host, 0) + 1
                self._failures[node.host] = n
                already_down = node.host in self._down
                suspect = n >= self.suspect_after and not already_down
            if suspect:
                # SWIM suspicion: ask other live peers before declaring
                # DOWN — any indirect success clears the failure count.
                if self._indirect_probe(node):
                    with self._mu:
                        self._failures[node.host] = 0
                    return
                with self._mu:
                    self._down.add(node.host)
                ev = self.events
                if ev is not None:
                    # Death declaration: direct probes exhausted AND
                    # indirect probes found nobody who can reach it.
                    ev.emit("membership.down", peer=node.host,
                            failures=n)
            return
        with self._mu:
            was_down = node.host in self._down
            self._failures[node.host] = 0
            self._down.discard(node.host)
        if was_down:
            ev = self.events
            if ev is not None:
                ev.emit("membership.rejoin", peer=node.host)
        if was_down and self.on_rejoin:
            try:
                self.on_rejoin(node)
            except Exception:  # noqa: BLE001 — reconciliation best-effort; pilint: disable=swallow
                pass

    def _indirect_probe(self, target):
        helpers = [n for n in self.nodes()
                   if n.host not in (self.local_host, target.host)]
        self._rng.shuffle(helpers)
        for helper in helpers[: self.indirect_n]:
            try:
                if self.client.indirect_probe(helper, target):
                    return True
            except Exception:  # noqa: BLE001 — helper itself may be sick; pilint: disable=swallow
                continue
        return False

    _DIGEST_DIVERGE_ROUNDS = 10

    def _note_digest_pair(self, host, mine, theirs):
        """Surface permanent schema divergence: the create-only merge
        cannot reconcile same-named objects with different OPTIONS, so
        two digests can stay stable-but-unequal forever — shipping the
        full schema both ways every probe with no visible sign. Warn
        once per stable pair."""
        if not mine or mine == theirs:
            self._digest_pairs.pop(host, None)
            return
        prev = self._digest_pairs.get(host)
        if prev and prev[0] == (mine, theirs):
            count = prev[1] + 1
            if count == self._DIGEST_DIVERGE_ROUNDS:
                logger.warning(
                    "schema digests with %s stable but unequal after "
                    "%d exchanges (%s vs %s): same-named objects "
                    "likely differ in options; full schema ships on "
                    "every probe until reconciled",
                    host, count, mine, theirs)
            self._digest_pairs[host] = ((mine, theirs), count)
        else:
            self._digest_pairs[host] = ((mine, theirs), 1)

    def _probe(self, node):
        # Via the internal client so TLS contexts (skip-verify clusters)
        # apply to health probes exactly as to data-plane requests.
        if (self.status_fn is not None
                and node.host not in self._hb_unsupported):
            # Build OUR status OUTSIDE the transport try: a local
            # status_fn failure must fall back to the plain probe, not
            # feed the failure detector as if the peer were down.
            status = None
            try:
                status = self.status_fn()
                # Steady state: the peer already has our schema
                # (digests match) — strip it so the probe stays
                # O(max-slice map) on the wire, not O(schema).
                if (status.get("schemaDigest")
                        and self._peer_digests.get(node.host)
                        == status.get("schemaDigest")):
                    status = {k: v for k, v in status.items()
                              if k != "schema"}
            except Exception:  # noqa: BLE001 — local fault only
                status = None
            if status is not None:
                try:
                    peer = self.client.heartbeat(
                        node, status, timeout=self.interval)
                except Exception:  # noqa: BLE001 — transport down
                    return False
                if peer is None:
                    # Pre-heartbeat peer: remember and use plain
                    # probes (one extra request this round only).
                    self._hb_unsupported.add(node.host)
                else:
                    if peer:
                        if peer.get("schemaDigest"):
                            self._peer_digests[node.host] = peer[
                                "schemaDigest"]
                            self._note_digest_pair(
                                node.host, status.get("schemaDigest"),
                                peer["schemaDigest"])
                        if self.merge_fn is not None:
                            try:
                                self.merge_fn(peer)
                            except Exception:  # noqa: BLE001 — merge; pilint: disable=swallow
                                pass  # is best-effort; liveness stands
                    return True
        return self.client.probe(node, timeout=self.interval)

    def _probe_loop(self):
        while not self._closing.wait(self.interval):
            try:
                self.probe_once()
            except Exception:  # noqa: BLE001 — detection must outlive; pilint: disable=swallow
                pass           # any single bad probe round
