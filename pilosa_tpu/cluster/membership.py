"""HTTP heartbeat membership — failure detection without gossip
(ref: gossip/gossip.go wraps memberlist SWIM; the TPU build replaces it
with a coordinator-friendly heartbeat NodeSet since there is no
on-device gossip analog; the polling fallback mirrors monitorMaxSlices
server.go:321-357).

Each node probes every peer's /id endpoint on an interval; peers that
miss ``suspect_after`` consecutive probes are marked DOWN and dropped
from ``nodes()`` (which feeds Cluster.node_states and the executor's
failover remap). A recovered peer rejoins automatically on its next
successful probe and gets a schema push, the same reconciliation the
reference does via gossip state exchange (LocalState/MergeRemoteState).
"""
import threading


class HTTPNodeSet:
    def __init__(self, cluster, local_host, client, interval=5,
                 suspect_after=3, on_rejoin=None):
        self.cluster = cluster
        self.local_host = local_host
        self.client = client
        self.interval = interval
        self.suspect_after = suspect_after
        self.on_rejoin = on_rejoin
        self._failures = {}   # host -> consecutive failed probes
        self._down = set()
        self._mu = threading.Lock()
        self._closing = threading.Event()
        self._thread = None

    # ---------------------------------------------------------- NodeSet API

    def open(self):
        self._thread = threading.Thread(target=self._probe_loop, daemon=True)
        self._thread.start()
        return self

    def close(self):
        self._closing.set()

    def nodes(self):
        """Live members (ref: GossipNodeSet.Nodes gossip.go:44-51)."""
        with self._mu:
            return [n for n in self.cluster.nodes if n.host not in self._down]

    def join(self, nodes):
        for n in nodes:
            if self.cluster.node_by_host(n.host) is None:
                self.cluster.nodes.append(n)

    def is_down(self, host):
        with self._mu:
            return host in self._down

    # -------------------------------------------------------------- probing

    def probe_once(self):
        for node in self.cluster.nodes:
            if node.host == self.local_host:
                continue
            ok = self._probe(node)
            with self._mu:
                if ok:
                    was_down = node.host in self._down
                    self._failures[node.host] = 0
                    self._down.discard(node.host)
                else:
                    n = self._failures.get(node.host, 0) + 1
                    self._failures[node.host] = n
                    was_down = False
                    if n >= self.suspect_after:
                        self._down.add(node.host)
            if ok and was_down and self.on_rejoin:
                try:
                    self.on_rejoin(node)
                except Exception:  # noqa: BLE001 — reconciliation best-effort
                    pass

    def _probe(self, node):
        import urllib.request

        try:
            with urllib.request.urlopen(
                    f"{node.uri()}/id", timeout=self.interval) as resp:
                return resp.status == 200
        except OSError:
            return False

    def _probe_loop(self):
        while not self._closing.wait(self.interval):
            self.probe_once()
