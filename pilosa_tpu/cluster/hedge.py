"""Tail-tolerant reads: replica-aware routing, deadline-budgeted
hedged fan-out, and the retry/hedge token budget (ROADMAP item 4).

Every fan-out leg used to go to the single *preferred* owner per
slice, so the cluster p99 was set by the slowest replica, not the
average — the classic tail-amplification problem ("The Tail at
Scale"). This module supplies the three mechanisms the executor's
fan-out rounds compose:

**Routing** (``rank``): for an owner replica set, order candidates by
a live score built from the PR 16 replica vitals — last closed-window
p99, error EWMA, in-flight count, degraded verdict — with the local
host nudged ahead when healthy and the owner-tuple position as the
deterministic tiebreak (two coordinators with the same vitals pick
the same owner; cold vitals degrade to exactly the legacy
preferred-owner order). Degraded peers always rank last.

**Hedging** (``plan_hedge`` / budget): when a leg's primary runs past
its predicted latency (cost-model estimate when available, else the
primary peer's p99, floored at ``delay_ms`` and clamped into the
remaining QoS deadline headroom), the same leg is issued to the next
epoch-valid replica; first response wins, the loser is cancelled
(accounting only — the wire RPC runs out, but its latency sample is
suppressed so a slow peer's losses can't poison its own watchdog
baseline).

**Budget** (metastability guard): hedges draw from a token bucket
whose ONLY refill is load-proportional — ``ratio`` tokens per primary
leg dispatched, capped at ``burst``. Total hedges are therefore
structurally bounded by ``ratio × primary_legs + burst`` over any
window: a slow cluster under overload deposits less (QoS sheds
primaries) and the saturation gate (``qos.saturated()``) stops
hedging outright, so hedges can never amplify an overload. Tokens are
consumed permanently — a cancelled or failed hedge "releases" only
its in-flight slot, never its token.

Suppression reasons (counted per-reason, surfaced in explain):
``no_candidates`` (no second epoch-valid replica), ``all_degraded``
(every alternate is watchdog-degraded — the leg runs un-hedged at
full deadline; journaled as a ``hedge.suppressed`` flight-recorder
event so operators see the degradation ladder engage), ``budget``
(bucket empty), ``qos_saturated`` (admission gate full), ``deadline``
(not enough headroom left to hedge usefully), and ``request_cap``
(per-request hedge cap reached).

Disabled — the default — the executor holds ``hedger = None`` and
every decision point costs one attribute read (the NopTracer /
NopQoS / NopFaults discipline); the preferred-owner path is
byte-identical to pre-hedging behavior.
"""
import os
import threading
import time

from pilosa_tpu import lockcheck

# Routing score weights (seconds-denominated): one unit of error EWMA
# costs like half a second of p99, one in-flight RPC like 2 ms, and
# the local host gets a 1 ms head start (local legs skip the wire).
ERR_PENALTY = 0.5
INFLIGHT_STEP = 0.002
LOCAL_BONUS = 0.001

# Vitals route-stats memo TTL: scoring runs per owner-tuple per
# fan-out pass — one vitals read per TTL serves them all.
STATS_TTL = 0.25

# Defaults for the [cluster] hedge knobs (config.py mirrors these).
DEFAULTS = {
    "hedge-reads": False,
    "replica-routing": False,
    "hedge-ratio": 0.10,
    "hedge-burst": 8.0,
    "hedge-delay-ms": 30.0,
    "hedge-delay-factor": 1.5,
    "hedge-headroom": 0.5,
    "hedge-max-per-request": 4,
}

SUPPRESS_REASONS = ("no_candidates", "all_degraded", "budget",
                    "qos_saturated", "deadline", "request_cap")


def env_config(env=None):
    """``PILOSA_HEDGE_*`` overrides as a config-key dict (the
    ``_apply_env`` discipline: a malformed value keeps the default
    rather than crashing the boot path)."""
    env = os.environ if env is None else env
    out = {}
    for var, key, cast in (
            ("PILOSA_HEDGE_READS", "hedge-reads", None),
            ("PILOSA_HEDGE_ROUTING", "replica-routing", None),
            ("PILOSA_HEDGE_RATIO", "hedge-ratio", float),
            ("PILOSA_HEDGE_BURST", "hedge-burst", float),
            ("PILOSA_HEDGE_DELAY_MS", "hedge-delay-ms", float),
            ("PILOSA_HEDGE_DELAY_FACTOR", "hedge-delay-factor", float),
            ("PILOSA_HEDGE_HEADROOM", "hedge-headroom", float),
            ("PILOSA_HEDGE_MAX_PER_REQUEST", "hedge-max-per-request",
             int),
    ):
        raw = env.get(var)
        if not raw:
            continue
        if cast is None:
            out[key] = raw.strip().lower() in ("1", "true", "yes")
            continue
        try:
            out[key] = cast(raw)
        except ValueError:
            pass
    return out


class HedgeBudget:
    """The process-wide hedge token bucket. Load-proportional refill
    is the whole point: ``deposit`` is called once per PRIMARY leg
    dispatched, adding ``ratio`` tokens (bucket capped at ``burst``),
    and ``try_take`` consumes a whole token per hedge — so over any
    window, hedged legs ≤ ratio × primary legs + burst. No timer
    refill: an idle or shedding cluster earns no hedges."""

    def __init__(self, ratio, burst):
        self.ratio = float(ratio)
        self.burst = float(burst)
        self._mu = lockcheck.register("hedge.HedgeBudget._mu",
                                      threading.Lock())
        self._tokens = self.burst   # full at boot: burst bounds it

    def deposit(self, legs=1):
        with self._mu:
            self._tokens = min(self.burst,
                               self._tokens + self.ratio * legs)

    def try_take(self):
        with self._mu:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def tokens(self):
        with self._mu:
            return self._tokens

    def drain(self):
        """Empty the bucket (tests/debug: prove zero-budget behavior
        without waiting out the burst)."""
        with self._mu:
            self._tokens = 0.0


class HedgeSession:
    """Per-request hedge cap, threaded explicitly through the fan-out
    (thread-locals don't cross pool threads — the querystats.scope
    discipline). Per-request object: plain lock, not lockcheck-
    registered (see tracing.Trace)."""

    __slots__ = ("_mu", "remaining", "hedged")

    def __init__(self, cap):
        self._mu = threading.Lock()
        self.remaining = int(cap)
        self.hedged = 0

    def try_take(self):
        with self._mu:
            if self.remaining <= 0:
                return False
            self.remaining -= 1
            self.hedged += 1
            return True

    def give_back(self):
        """Return a session slot taken speculatively (the process
        budget refused after the session said yes) — the session cap
        bounds hedges ISSUED, not attempts."""
        with self._mu:
            self.remaining += 1
            self.hedged -= 1


class CancelBox:
    """Loser-cancellation accounting for one in-flight leg. The wire
    RPC cannot be aborted mid-read (blocking http.client), so
    cancellation is an accounting verdict: the transport checks
    ``cancelled`` at completion and suppresses the latency/error
    sample (a loser leg on a degraded peer must NOT train that peer's
    watchdog baseline) while still decrementing in-flight gauges."""

    __slots__ = ("cancelled",)

    def __init__(self):
        self.cancelled = False


class Hedger:
    """The enabled hedging/routing tier: configuration, the process
    budget, the vitals-backed replica scorer, and every counter the
    ``pilosa_hedge_*`` metrics group exports. Server-wired refs
    (vitals / breakers / epochs / qos / events) default to None so a
    bare Hedger works in unit tests."""

    enabled = True

    def __init__(self, cfg=None, clock=time.monotonic):
        c = dict(DEFAULTS)
        c.update(cfg or {})
        self.reads = bool(c["hedge-reads"])
        self.routing = bool(c["replica-routing"])
        self.delay_s = float(c["hedge-delay-ms"]) / 1000.0
        self.delay_factor = float(c["hedge-delay-factor"])
        self.headroom = float(c["hedge-headroom"])
        self.max_per_request = int(c["hedge-max-per-request"])
        self.budget = HedgeBudget(c["hedge-ratio"], c["hedge-burst"])
        self.vitals = None       # observe.replica.ReplicaVitals
        self.breakers = None     # qos.PeerBreakers
        self.epochs = None       # cluster.epochs.ClusterEpochs
        self.qos = None          # qos.QoS (saturation gate)
        self.events = None       # flight recorder
        self.local_host = None
        self._clock = clock
        self._mu = lockcheck.register("hedge.Hedger._mu",
                                      threading.Lock())
        self._stats_memo = (-1e9, {})
        # Counters (all under _mu; inflight is the live hedge gauge).
        self.legs_primary = 0
        self.legs_hedge = 0
        self.armed = 0
        self.fired = 0
        self.won_primary = 0
        self.won_hedge = 0
        self.cancelled = 0
        self.errors = 0
        self.routed_non_preferred = 0
        self.inflight = 0
        self.suppressed = dict.fromkeys(SUPPRESS_REASONS, 0)

    # ------------------------------------------------------- routing

    def _route_stats(self):
        at, stats = self._stats_memo
        now = self._clock()
        if now - at <= STATS_TTL:
            return stats
        vt = self.vitals
        stats = (vt.route_stats() if vt is not None and vt.enabled
                 else {})
        self._stats_memo = (now, stats)   # atomic tuple swap — racy
        return stats                      # double-compute is benign

    def rank(self, hosts, local_host=None):
        """Order an owner tuple for serving: ``[(host, inputs)]``
        ascending by (degraded, score, owner-position). ``inputs`` is
        the score breakdown explain shows. Deterministic: equal scores
        (the cold-vitals case) preserve the owner-tuple order, i.e.
        exactly the legacy preferred-owner routing."""
        local_host = local_host if local_host is not None else self.local_host
        stats = self._route_stats()
        keyed = []
        for i, h in enumerate(hosts):
            st = stats.get(h) or {}
            p99 = st.get("p99") or 0.0
            err = st.get("errEwma") or 0.0
            infl = st.get("inflight") or 0
            degraded = bool(st.get("degraded"))
            score = p99 + ERR_PENALTY * err + INFLIGHT_STEP * infl
            if h == local_host:
                score -= LOCAL_BONUS
            keyed.append((1 if degraded else 0, score, i, h, {
                "host": h, "p99": round(p99, 6),
                "errEwma": round(err, 4), "inflight": infl,
                "degraded": degraded,
                "healthScore": st.get("healthScore"),
                "score": round(score, 6),
            }))
        keyed.sort(key=lambda t: (t[0], t[1], t[2]))
        return [(h, inputs) for _d, _s, _i, h, inputs in keyed]

    # ----------------------------------------------------- candidates

    def peer_serveable(self, host):
        """A host a hedge (or routed leg) may target: breaker closed,
        not LEAVING (callers pre-filter via the cluster candidate
        helper), epoch entry fresh. The local host always qualifies
        (its epochs are the live counters)."""
        if host == self.local_host:
            return True
        brk = self.breakers
        if brk is not None and host in brk.open_hosts():
            return False
        ep = self.epochs
        if ep is not None and not ep.peer_fresh(host):
            return False
        return True

    # -------------------------------------------------------- hedging

    def hedge_delay(self, primary_host, predicted_s, deadline):
        """Seconds to wait before hedging, or None when there is not
        enough deadline headroom for a hedge to finish (suppress with
        reason ``deadline``). The trigger is the cost model's
        prediction when the coordinator has one, else the primary
        peer's observed p99, scaled by ``delay_factor`` and floored at
        the configured minimum delay."""
        base = predicted_s
        if not base:
            st = self._route_stats().get(primary_host) or {}
            base = st.get("p99") or 0.0
        delay = max(self.delay_s, base * self.delay_factor)
        if deadline is not None:
            remaining = deadline - time.monotonic()
            budget = remaining * self.headroom
            if budget <= 0 or remaining <= self.delay_s:
                return None
            delay = min(delay, budget)
        return delay

    def admit_hedge(self, session):
        """(ok, reason): consume one session slot + one budget token.
        Checked in cheapest-first order; the session slot is returned
        when a later gate refuses."""
        if session is not None and not session.try_take():
            return False, "request_cap"
        q = self.qos
        if q is not None and q.saturated():
            if session is not None:
                session.give_back()
            return False, "qos_saturated"
        if not self.budget.try_take():
            if session is not None:
                session.give_back()
            return False, "budget"
        return True, None

    # ----------------------------------------------------- accounting

    def on_primary_legs(self, n):
        """n primary legs dispatched: count them and earn budget —
        the load-proportional refill."""
        with self._mu:
            self.legs_primary += n
        self.budget.deposit(n)

    def on_armed(self):
        with self._mu:
            self.armed += 1

    def on_fired(self):
        with self._mu:
            self.fired += 1
            self.legs_hedge += 1
            self.inflight += 1

    def on_settled(self, hedge_won, hedge_errored=False):
        """The race resolved: exactly one of primary/hedge won. The
        in-flight hedge gauge releases here — the budget token does
        not (consumed permanently; see module docstring)."""
        with self._mu:
            self.inflight = max(0, self.inflight - 1)
            if hedge_errored:
                self.errors += 1
            if hedge_won:
                self.won_hedge += 1
            else:
                self.won_primary += 1
                if not hedge_errored:
                    self.cancelled += 1

    def on_routed_non_preferred(self):
        with self._mu:
            self.routed_non_preferred += 1

    def suppress(self, reason, **fields):
        """Count a suppression; ``all_degraded`` — the degradation
        ladder's last rung — additionally journals a
        ``hedge.suppressed`` flight-recorder event."""
        with self._mu:
            self.suppressed[reason] = self.suppressed.get(reason, 0) + 1
        if reason == "all_degraded":
            ev = self.events
            if ev is not None:
                ev.emit("hedge.suppressed", reason=reason, **fields)
        return reason

    # ---------------------------------------------------------- reads

    def metrics(self):
        """Flat dict for the /metrics ``pilosa_hedge_*`` group."""
        with self._mu:
            out = {
                "legs_primary_total": self.legs_primary,
                "legs_hedge_total": self.legs_hedge,
                "armed_total": self.armed,
                "fired_total": self.fired,
                "won_primary_total": self.won_primary,
                "won_hedge_total": self.won_hedge,
                "cancelled_total": self.cancelled,
                "errors_total": self.errors,
                "routed_non_preferred_total": self.routed_non_preferred,
                "inflight": self.inflight,
            }
            for reason, n in self.suppressed.items():
                out[f"suppressed_total;reason:{reason}"] = n
        out["budget_tokens"] = round(self.budget.tokens(), 4)
        return out

    def snapshot(self):
        """Rich JSON for GET /debug/hedge."""
        with self._mu:
            supp = dict(self.suppressed)
            body = {
                "enabled": True, "reads": self.reads,
                "routing": self.routing,
                "delayMs": self.delay_s * 1000.0,
                "delayFactor": self.delay_factor,
                "headroom": self.headroom,
                "maxPerRequest": self.max_per_request,
                "legsPrimary": self.legs_primary,
                "legsHedge": self.legs_hedge,
                "armed": self.armed, "fired": self.fired,
                "wonPrimary": self.won_primary,
                "wonHedge": self.won_hedge,
                "cancelled": self.cancelled, "errors": self.errors,
                "routedNonPreferred": self.routed_non_preferred,
                "inflight": self.inflight,
            }
        body["suppressed"] = supp
        body["budget"] = {"ratio": self.budget.ratio,
                          "burst": self.budget.burst,
                          "tokens": round(self.budget.tokens(), 4)}
        return body

    def session(self):
        return HedgeSession(self.max_per_request)


class NopHedger:
    """Hedging/routing disabled: the executor's decision points guard
    on ``enabled`` (or hold None) and never call further."""

    enabled = False
    reads = False
    routing = False

    def metrics(self):
        return {}

    def snapshot(self):
        return {"enabled": False}


NOP = NopHedger()
