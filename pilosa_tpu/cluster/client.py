"""Internal HTTP client — the node-to-node data/query plane
(ref: client.go:46-1160 InternalHTTPClient).

Transport: a keep-alive connection pool with TCP_NODELAY, not
one-shot urllib requests. Every remote subquery, digest pre-check,
heartbeat, and sync block fetch used to pay TCP setup plus the
Nagle/delayed-ACK stall per call — the same ~40 ms tax round 4
evicted from the PUBLIC serving path, still sitting on the internal
plane (the reference's http.Client pools connections natively,
client.go:60-83). Pooled connections are checked out per request and
returned after the response is fully read; a stale keep-alive
(peer closed between requests) retries once on a fresh connection.
"""
import base64
import http.client
import json
import socket
import threading
import time
import urllib.parse

from pilosa_tpu import errors as perr
from pilosa_tpu import faults
from pilosa_tpu import lockcheck
from pilosa_tpu import qos
from pilosa_tpu import querystats
from pilosa_tpu import stats as stats_mod

# Internal-plane requests are stamped with the internal priority class
# so a peer's admission gate never parks coordinator fan-out (which
# already holds a slot for the originating user query) behind other
# user traffic — see qos.py.
_INTERNAL_HEADERS = {qos.PRIORITY_HEADER: "internal"}


def _b64(data):
    """Go marshals []byte as base64 in JSON (AttrBlock.Checksum)."""
    return base64.b64encode(data).decode()


def _decode_checksum(s):
    """Checksums are 8 bytes (xxhash64): base64 is 12 chars with '='
    padding, round-1's hex form is 16 hex chars — the shapes are
    disjoint, so both generations of peers parse correctly."""
    if len(s) == 16:
        try:
            return bytes.fromhex(s)
        except ValueError:
            pass
    return base64.b64decode(s)


class ClientError(Exception):
    """``status`` carries the HTTP status when one was received —
    callers must branch on it, never on substring-matching the
    message (which embeds the URL: a query for slice 404 would match
    a '404' text probe). ``timed_out`` marks a socket-timeout failure
    (deadline-budget callers convert it to DeadlineExceeded);
    ``breaker_open`` marks a request refused locally by an open peer
    circuit breaker — no bytes ever hit the wire."""

    def __init__(self, msg, status=None, timed_out=False,
                 breaker_open=False):
        super().__init__(msg)
        self.status = status
        self.timed_out = timed_out
        self.breaker_open = breaker_open


def _node_url(node, path, **params):
    base = node.uri() if hasattr(node, "uri") else str(node).rstrip("/")
    qs = urllib.parse.urlencode(
        {k: v for k, v in params.items() if v is not None})
    return f"{base}{path}" + (f"?{qs}" if qs else "")


class InternalClient:
    """JSON/protobuf client used by the executor's remote fan-out, the
    import path, anti-entropy sync, and backup/restore."""

    # Idle connections kept per (scheme, host) — enough for the
    # replica fan-out plus background monitors without hoarding fds
    # at membership scale.
    POOL_PER_HOST = 8

    def __init__(self, timeout=30, skip_verify=False, breakers=None):
        self.timeout = timeout
        # Distributed mutation-epoch registry (cluster/epochs.py),
        # wired by the server on multi-node deployments: every RPC
        # response's piggyback header feeds it in-line, so a write
        # fan-out's ack returns the replica's bumped epoch before the
        # coordinator acks its client. None = one attribute read.
        self.epochs = None
        # Per-peer circuit breakers (qos.PeerBreakers) — None (the
        # default) means no breaker accounting at all: one attribute
        # read on the request path, the nop-tracer discipline.
        self.breakers = breakers
        # TLS skip-verify for self-signed intra-cluster certs
        # (ref: client.go:60-75 InsecureSkipVerify, config.go TLS section).
        self._ssl_ctx = None
        if skip_verify:
            import ssl

            self._ssl_ctx = ssl.create_default_context()
            self._ssl_ctx.check_hostname = False
            self._ssl_ctx.verify_mode = ssl.CERT_NONE
        self._default_ssl_ctx = None  # built lazily, cached (CA load)
        self._pool_mu = lockcheck.register(
            "cluster.InternalClient._pool_mu", threading.Lock())
        self._pool = {}  # (scheme, netloc) -> [idle HTTPConnection]
        # Internal-plane request-latency histogram (stats.Histogram),
        # wired by the server; one attribute read when off.
        self.histogram = stats_mod.NOP_HISTOGRAM
        self._hist_peers = {}
        # Per-replica vitals (observe.replica.ReplicaVitals), wired by
        # the server; None when off — one attribute read on the hot
        # path, and no observe-package import from the client layer.
        self.vitals = None
        # Lazy fan-out pool for parallel replica posts (import_bits /
        # import_values): no threads until a multi-owner write.
        self._fan_pool = None

    def set_histogram(self, hist):
        """Install the ``client_request_seconds`` family; per-peer
        children are memoized off the hot path."""
        self.histogram = hist or stats_mod.NOP_HISTOGRAM
        self._hist_peers = {}

    def _peer_hist(self, netloc):
        child = self._hist_peers.get(netloc)
        if child is None:
            child = self._hist_peers[netloc] = self.histogram.with_tags(
                f"peer:{netloc}")
        return child

    # ------------------------------------------------------------- plumbing

    def _new_conn(self, scheme, netloc, timeout):
        if scheme == "https":
            ctx = self._ssl_ctx
            if ctx is None:
                if self._default_ssl_ctx is None:
                    import ssl

                    # Cached: create_default_context re-reads the CA
                    # bundle from disk on every call.
                    self._default_ssl_ctx = ssl.create_default_context()
                ctx = self._default_ssl_ctx
            conn = http.client.HTTPSConnection(netloc, timeout=timeout,
                                               context=ctx)
        else:
            conn = http.client.HTTPConnection(netloc, timeout=timeout)
        return conn

    def _checkout(self, key, timeout, fresh_only=False):
        """``fresh_only`` (the stale-keep-alive retry) flushes the
        host's idle list and dials anew: after a peer restart EVERY
        parked keep-alive to it is stale — popping another one would
        fail the retry spuriously."""
        conn = None
        if fresh_only:
            with self._pool_mu:
                stale = self._pool.pop(key, [])
            for c in stale:
                try:
                    c.close()
                except OSError:
                    pass
        else:
            with self._pool_mu:
                idle = self._pool.get(key)
                conn = idle.pop() if idle else None
        if conn is None:
            conn = self._new_conn(key[0], key[1], timeout)
        else:
            conn.timeout = timeout
            if conn.sock is not None:
                conn.sock.settimeout(timeout)
        return conn

    def _checkin(self, key, conn):
        with self._pool_mu:
            idle = self._pool.setdefault(key, [])
            if len(idle) < self.POOL_PER_HOST:
                idle.append(conn)
                return
        conn.close()

    def close(self):
        """Drop every idle pooled connection (tests, shutdown)."""
        with self._pool_mu:
            pools, self._pool = self._pool, {}
        for idle in pools.values():
            for conn in idle:
                try:
                    conn.close()
                except OSError:
                    pass
        if self._fan_pool is not None:
            self._fan_pool.close()

    def _do(self, method, url, body=None, content_type="application/json",
            accept=None, timeout=None, extra_headers=None,
            bypass_breaker=False, budget_timeout=False, cancel_box=None):
        if lockcheck.ACTIVE.enabled:
            # Any registered lock held across an internal-plane RPC
            # turns one slow peer into a node-wide convoy (and, for
            # cluster-visible locks, a distributed deadlock risk).
            lockcheck.ACTIVE.io_point("client.rpc")
        parsed = urllib.parse.urlsplit(url)
        key = (parsed.scheme or "http", parsed.netloc)
        path = parsed.path or "/"
        if parsed.query:
            path += "?" + parsed.query
        brk = self.breakers
        holds_probe = False
        if brk is not None and not bypass_breaker:
            verdict = brk.allow(parsed.netloc)
            if not verdict:
                # Fail fast: a peer with an open breaker already
                # proved dead a moment ago — don't pay connect/read
                # timeouts per call to rediscover it. Probes/
                # heartbeats (the failure detector, the recovery
                # path) bypass this gate.
                raise ClientError(
                    f"{method} {url}: circuit open: {parsed.netloc}",
                    breaker_open=True)
            holds_probe = verdict is brk.PROBE
        if faults.ACTIVE.enabled and not bypass_breaker:
            # Chaos points on the internal plane. Probes/heartbeats
            # (bypass_breaker) are exempt: they ARE the failure
            # detector, and injecting into them would collapse
            # membership instead of exercising the fan-out paths.
            faults.ACTIVE.fire("client.fanout.slow")  # delay action
            try:
                faults.ACTIVE.fire("client.fanout.error")
            except OSError as e:
                # Mirror a real transport failure exactly: breaker
                # accounting, then ClientError — so the executor's
                # failover and the breaker lifecycle are what the
                # injection tests, not a bespoke error path.
                if brk is not None:
                    brk.record_failure(parsed.netloc)
                raise ClientError(f"{method} {url}: {e}") from e
        headers = {}
        if body is not None:
            headers["Content-Type"] = content_type
        if accept:
            headers["Accept"] = accept
        if extra_headers:
            headers.update(extra_headers)
        t = timeout or self.timeout
        vt = self.vitals
        vtok = None
        if vt is not None:
            # In-flight counts up BEFORE the wire write so a hung peer
            # is visible before any sample completes; done() runs in
            # the finally so it comes back down on every exit.
            vtok = vt.begin(key[1], parsed.path or "/",
                            headers.get(qos.PRIORITY_HEADER, "internal"))
        ok = False
        t0 = time.perf_counter()
        try:
            out = self._do_wire(method, url, key, path, body, headers,
                                t, t0, brk, parsed, holds_probe,
                                bypass_breaker, budget_timeout)
            ok = True
            return out
        finally:
            if vtok is not None:
                # A hedged leg that LOST the race (cancel_box flipped
                # by the winner) still decrements in-flight but must
                # not record its latency/error sample: the loser is
                # slow by construction, and counting every lost race
                # would poison the peer's watchdog baseline
                # (cluster/hedge.py CancelBox).
                vt.done(vtok, time.perf_counter() - t0, ok,
                        record_sample=not (cancel_box is not None
                                           and cancel_box.cancelled))

    def _do_wire(self, method, url, key, path, body, headers, t, t0,
                 brk, parsed, holds_probe, bypass_breaker,
                 budget_timeout):
        # One retry: a pooled keep-alive the peer closed between
        # requests surfaces as BadStatusLine/ConnectionReset on FIRST
        # use — indistinguishable from a dead peer only after a fresh
        # connection also fails. TIMEOUTS never retry: the server may
        # still be executing the request, and re-sending would
        # duplicate a non-idempotent write while doubling the wait.
        for attempt in (0, 1):
            conn = self._checkout(key, t, fresh_only=attempt > 0)
            fresh = conn.sock is None
            try:
                if fresh:
                    conn.connect()
                    # The internal plane is request/response ping-pong:
                    # without NODELAY every request pays a Nagle/
                    # delayed-ACK stall (round 4's public-path lesson).
                    conn.sock.setsockopt(socket.IPPROTO_TCP,
                                         socket.TCP_NODELAY, 1)
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
                data = resp.read()  # fully drained: safe to reuse
                if (faults.ACTIVE.enabled and not bypass_breaker
                        and data
                        and faults.ACTIVE.fire("client.fanout.corrupt")):
                    # Garble the payload (length-preserving): decoders
                    # downstream fail, and the caller's failover /
                    # error handling — not a crash — must absorb it.
                    data = data[::-1]
                out = resp.status, data, dict(resp.headers)
            except socket.timeout as e:
                try:
                    conn.close()
                except OSError:
                    pass
                if brk is not None:
                    if budget_timeout:
                        # A DEADLINE-bounded timeout proves the
                        # request's budget spent, not the peer dead —
                        # it must not open the breaker against a
                        # healthy peer serving legitimately slow
                        # queries. It DOES release the half-open probe
                        # slot when THIS request holds it, or the peer
                        # would wedge in HALF_OPEN forever.
                        if holds_probe:
                            brk.abort_probe(parsed.netloc)
                    else:
                        brk.record_failure(parsed.netloc)
                if self.histogram.enabled:
                    # Failures must sample too: a timing-out peer's
                    # slowest requests are exactly what the per-peer
                    # latency histogram exists to expose.
                    self._peer_hist(key[1]).observe(
                        time.perf_counter() - t0)
                raise ClientError(f"{method} {url}: {e}",
                                  timed_out=True) from e
            except (http.client.HTTPException, OSError) as e:
                try:
                    conn.close()
                except OSError:
                    pass
                if attempt == 0 and not fresh:
                    continue  # stale keep-alive: retry on a fresh conn
                if brk is not None:
                    brk.record_failure(parsed.netloc)
                if self.histogram.enabled:
                    self._peer_hist(key[1]).observe(
                        time.perf_counter() - t0)
                raise ClientError(f"{method} {url}: {e}") from e
            if brk is not None:
                # Any response — even a 5xx — proves the peer's
                # transport alive; only connect/reset/timeout count
                # toward opening the breaker.
                brk.record_success(parsed.netloc)
            if resp.will_close:
                conn.close()
            else:
                self._checkin(key, conn)
            if self.histogram.enabled:
                self._peer_hist(key[1]).observe(
                    time.perf_counter() - t0)
            ep = self.epochs
            if ep is not None:
                hv = out[2].get(ep.HEADER)
                if hv:
                    ep.observe_header(hv)
            return out

    def _json(self, method, url, payload=None, timeout=None,
              extra_headers=None):
        body = json.dumps(payload).encode() if payload is not None else None
        status, data, _ = self._do(method, url, body, timeout=timeout,
                                   extra_headers=extra_headers)
        if status >= 400:
            try:
                msg = json.loads(data).get("error", data.decode())
            except ValueError:
                msg = data.decode()
            raise ClientError(f"{method} {url}: {status}: {msg}",
                              status=status)
        return json.loads(data) if data else {}

    # -------------------------------------------------------------- queries

    def execute_query(self, node, index, query, slices=None, remote=False,
                      exclude_attrs=False, exclude_bits=False,
                      trace_headers=None, deadline=None, cancel_box=None):
        """POST /index/{i}/query with protobuf body, Remote=true
        (ref: client.go:227-276). Returns decoded result list in
        executor-native types. ``trace_headers`` (an
        X-Pilosa-Trace-Id/X-Pilosa-Span-Id dict from
        tracing.trace_headers()) stitches the remote node's spans
        under the caller's trace. ``deadline`` (a ``time.monotonic()``
        instant) bounds the socket timeout to the REMAINING request
        budget and re-stamps the X-Pilosa-Deadline header (converted
        to wall-clock at this wire boundary) so the remote node
        enforces the same instant; an exhausted budget — before or
        during the round trip — raises DeadlineExceeded.
        ``cancel_box`` (hedge.CancelBox) marks this leg part of a
        hedged race: when the box is flipped before completion the
        leg's replica-vitals sample is suppressed (loser-cancellation
        accounting; the wire RPC itself runs out)."""
        from pilosa_tpu.bitmap import Bitmap
        from pilosa_tpu.server import wireproto

        extra = dict(_INTERNAL_HEADERS)
        if trace_headers:
            extra.update(trace_headers)
        # Per-query resource profiling: when this (fan-out) thread
        # carries an active accumulator, ask the remote node to count
        # its side and return the partial in a response footer header.
        qstats_acc = querystats.active()
        if qstats_acc is not None:
            extra[querystats.COLLECT_HEADER] = "1"
        timeout = None
        budget_bound = False
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise qos.DeadlineExceeded()
            budget_bound = remaining < self.timeout
            timeout = min(self.timeout, remaining)
            extra[qos.DEADLINE_HEADER] = \
                f"{qos.wall_deadline(deadline):.6f}"
        body = wireproto.encode_query_request(
            str(query), slices=slices, remote=remote,
            exclude_attrs=exclude_attrs, exclude_bits=exclude_bits)
        url = _node_url(node, f"/index/{index}/query")
        try:
            status, data, headers = self._do(
                "POST", url, body, content_type="application/x-protobuf",
                accept="application/x-protobuf", extra_headers=extra,
                timeout=timeout, budget_timeout=budget_bound,
                cancel_box=cancel_box)
        except ClientError as e:
            if e.timed_out and budget_bound:
                # The timeout WAS the remaining budget: the request's
                # time is spent, not the peer's health in question. (A
                # flat health-timeout with budget left stays a
                # ClientError so replica failover still applies.)
                raise qos.DeadlineExceeded() from e
            raise
        if status == 504 and deadline is not None:
            # The remote node's deadline enforcement fired — the
            # shared absolute deadline is expired for us too. (With no
            # local deadline a remote 504 stays a ClientError so the
            # executor's replica failover still applies.)
            raise qos.DeadlineExceeded()
        if headers.get("Content-Type") != "application/x-protobuf":
            # Generic error path (e.g. panic recovery) answers JSON; do
            # not feed it to the protobuf decoder.
            raise ClientError(f"POST {url}: {status}: {data.decode()[:200]}",
                              status=status)
        resp = wireproto.decode_query_response(data)
        if qstats_acc is not None:
            qstats_acc.add("fanoutCalls", 1)
            qstats_acc.merge(querystats.decode(
                headers.get(querystats.STATS_HEADER)))
        if resp["error"]:
            raise ClientError(resp["error"])
        if status >= 400:
            raise ClientError(f"POST {url}: {status}")

        out = []
        for r in resp["results"]:
            if isinstance(r, dict) and "bits" in r:
                bm = Bitmap.from_columns(r["bits"])
                bm.attrs = r.get("attrs", {})
                out.append(bm)
            else:
                out.append(r)
        return out

    # --------------------------------------------------------------- schema

    def schema(self, node):
        return self._json("GET", _node_url(node, "/schema"))["indexes"]

    def post_schema(self, node, indexes):
        self._json("POST", _node_url(node, "/schema"), {"indexes": indexes})

    def create_index(self, node, index, opts=None):
        url = _node_url(node, f"/index/{index}")
        status, data, _ = self._do("POST", url,
                                   json.dumps({"options": opts or {}}).encode())
        if status == 409:
            raise perr.ErrIndexExists()
        if status >= 400:
            raise ClientError(f"POST {url}: {status}: {data!r}")

    def ensure_index(self, node, index, opts=None):
        try:
            self.create_index(node, index, opts)
        except perr.ErrIndexExists:
            pass

    def create_frame(self, node, index, frame, opts=None):
        url = _node_url(node, f"/index/{index}/frame/{frame}")
        status, data, _ = self._do("POST", url,
                                   json.dumps({"options": opts or {}}).encode())
        if status == 409:
            raise perr.ErrFrameExists()
        if status >= 400:
            raise ClientError(f"POST {url}: {status}: {data!r}")

    def ensure_frame(self, node, index, frame, opts=None):
        try:
            self.create_frame(node, index, frame, opts)
        except perr.ErrFrameExists:
            pass

    def create_field(self, node, index, frame, field, min_val=0, max_val=0):
        url = _node_url(node, f"/index/{index}/frame/{frame}/field/{field}")
        status, data, _ = self._do(
            "POST", url,
            json.dumps({"type": "int", "min": min_val,
                        "max": max_val}).encode())
        if status == 409 or b"field already exists" in data:
            raise perr.ErrFieldExists()
        if status >= 400:
            raise ClientError(f"POST {url}: {status}: {data!r}")

    def ensure_field(self, node, index, frame, field, min_val=0, max_val=0):
        try:
            self.create_field(node, index, frame, field, min_val, max_val)
        except perr.ErrFieldExists:
            pass

    def max_slices(self, node, inverse=False):
        return {k: int(v) for k, v in self._json(
            "GET", _node_url(node, "/slices/max",
                             inverse="true" if inverse else None)
        )["maxSlices"].items()}

    def frame_views(self, node, index, frame):
        """(ref: FrameViews client.go — GET /index/{i}/frame/{f}/views)."""
        return self._json(
            "GET", _node_url(node, f"/index/{index}/frame/{frame}/views"),
        )["views"]

    def fragment_nodes(self, node, index, slice_num):
        return self._json("GET", _node_url(node, "/fragment/nodes",
                                           index=index, slice=slice_num))

    def status(self, node):
        return self._json("GET", _node_url(node, "/status"))["status"]

    def metrics_text(self, node, timeout=None):
        """One peer's /metrics exposition text — the /cluster/metrics
        scrape leg. Bypasses the circuit breaker entirely: a periodic
        scrape must neither consume the single half-open probe slot a
        real query deserves (allow() would, the moment the cooldown
        elapses) nor open a breaker on failure — scrape failures have
        their own accounting (the handler's scrape_errors series)."""
        url = _node_url(node, "/metrics")
        status, data, _ = self._do("GET", url, timeout=timeout,
                                   bypass_breaker=True)
        if status >= 400:
            raise ClientError(f"GET {url}: {status}", status=status)
        return data.decode()

    def events_json(self, node, timeout=None, **params):
        """One peer's /debug/events page — the merged-timeline scrape
        leg. Bypasses the breaker for the same reason metrics_text
        does: a debug scrape must not consume the half-open probe slot
        or open a breaker; fetch failures degrade per-peer in the
        merged response."""
        url = _node_url(node, "/debug/events", **params)
        status, data, _ = self._do("GET", url, timeout=timeout,
                                   bypass_breaker=True)
        if status >= 400:
            raise ClientError(f"GET {url}: {status}", status=status)
        return json.loads(data) if data else {}

    def heatmap_json(self, node, timeout=None, **params):
        """One peer's /debug/heatmap page — the cluster heat-merge
        scrape leg (``?scope=cluster`` and the autopilot's placement
        sensor). Bypasses the breaker like the other debug scrapes:
        a sensor sweep must not consume the half-open probe slot or
        open a breaker; failures degrade per-peer in the merge."""
        url = _node_url(node, "/debug/heatmap", **params)
        status, data, _ = self._do("GET", url, timeout=timeout,
                                   bypass_breaker=True)
        if status >= 400:
            raise ClientError(f"GET {url}: {status}", status=status)
        return json.loads(data) if data else {}

    # --------------------------------------------------------------- import

    @staticmethod
    def _import_headers(internal):
        """``internal=True`` (the default) marks intra-cluster fan-out
        — never queued behind user traffic. Operator bulk loads (the
        CLI import commands) pass False and ride the BATCH class so
        the peer's admission gate and quotas still bound them — the
        heaviest user-plane traffic must not outrank serving."""
        return _INTERNAL_HEADERS if internal \
            else {qos.PRIORITY_HEADER: "batch"}

    def import_bits(self, cluster, index, frame, slice_num, row_ids,
                    column_ids, timestamps=None, internal=True):
        """Import to EVERY owner of the slice (ref: client.go:278-428).
        Owners are posted in PARALLEL (ReplicaN >= 2 write latency is
        one round trip, not the sum of sequential ones); any owner
        failure still fails the import."""
        from pilosa_tpu.server import wireproto

        body = wireproto.encode_import_request(
            index, frame, slice_num, row_ids, column_ids, timestamps)
        self._post_owners(
            self._slice_owners(cluster, index, slice_num), "/import",
            body, internal)

    def _post_owners(self, owners, path, body, internal,
                     content_type="application/x-protobuf"):
        """POST ``body`` to every owner concurrently; wait for ALL,
        then raise the first failure in owner order (fail-on-any-owner
        — the error contract of the old serial loop, minus the
        sequential round-trip latency and minus its skip-the-rest
        behavior: replicas that CAN take the write do, which only
        narrows the window anti-entropy must repair)."""
        owners = list(owners)

        def post(node):
            url = _node_url(node, path)
            status, data, _ = self._do(
                "POST", url, body,
                content_type=content_type,
                accept="application/x-protobuf",
                extra_headers=self._import_headers(internal))
            if status >= 400:
                raise ClientError(f"POST {url}: {status}: {data!r}")

        if len(owners) <= 1:
            for node in owners:
                post(node)
            return
        errs = [None] * len(owners)

        def run(i, node):
            try:
                post(node)
            except Exception as exc:  # noqa: BLE001 — re-raised below
                errs[i] = exc

        pool = self._fan_pool
        if pool is None:
            from pilosa_tpu.utils.fanpool import FanoutPool

            with self._pool_mu:  # double-checked: one pool, ever
                if self._fan_pool is None:
                    self._fan_pool = FanoutPool(max_idle=8)
                pool = self._fan_pool
        waits = [pool.run(lambda i=i, n=n: run(i, n))
                 for i, n in enumerate(owners)]
        for w in waits:
            w.wait()
        for exc in errs:
            if exc is not None:
                raise exc

    def import_k(self, node, index, frame, row_keys, column_keys,
                 timestamps=None, internal=True):
        """Keyed import: string keys, translated server-side
        (ref: ImportK client.go:307-330 — posts to one node; the slice
        is unknowable before translation)."""
        from pilosa_tpu.server import wireproto

        body = wireproto.encode_import_request(
            index, frame, 0, [], [], timestamps,
            row_keys=row_keys, column_keys=column_keys)
        url = _node_url(node, "/import")
        status, data, _ = self._do(
            "POST", url, body, content_type="application/x-protobuf",
            accept="application/x-protobuf",
            extra_headers=self._import_headers(internal))
        if status >= 400:
            raise ClientError(f"POST {url}: {status}: {data!r}")

    def ingest_slice(self, cluster, index, frame, slice_num, rows,
                     columns, timestamps=None, internal=True):
        """One slice-targeted bulk-ingest leg to EVERY owner of the
        slice (the ingest pipeline's coordinator fan-out,
        ingest/pipeline.py) — the same parallel fail-on-any-owner
        replica path as import_bits, carrying the columnar binary
        frame instead of per-bit protobuf. Mid-resize the owner set is
        the union of both placement generations, so ingest keeps
        landing on both through a live resize."""
        from pilosa_tpu.ingest import codec as ingest_codec

        body = ingest_codec.encode_bits(frame, rows, columns,
                                        timestamps)
        self._post_owners(
            self._slice_owners(cluster, index, slice_num),
            f"/index/{index}/ingest?slice={slice_num}", body, internal,
            content_type=ingest_codec.CONTENT_TYPE)

    def import_values(self, cluster, index, frame, slice_num, field,
                      column_ids, values, internal=True):
        """Parallel per-owner posts, as import_bits."""
        from pilosa_tpu.server import wireproto

        body = wireproto.encode_import_value_request(
            index, frame, slice_num, field, column_ids, values)
        self._post_owners(
            self._slice_owners(cluster, index, slice_num),
            "/import-value", body, internal)

    def _slice_owners(self, cluster, index, slice_num):
        if hasattr(cluster, "fragment_nodes"):
            return cluster.fragment_nodes(index, slice_num)
        return [cluster]  # single node

    def export_csv(self, node, index, frame, view, slice_num):
        status, data, _ = self._do("GET", _node_url(
            node, "/export", index=index, frame=frame, view=view,
            slice=slice_num))
        if status >= 400:
            raise ClientError(f"export: {status}")
        return data.decode()

    # ----------------------------------------------------- fragment internals

    def fragment_digest(self, node, index, frame, view, slice_num,
                        extra_headers=None):
        """8-byte fragment digest (hex over the wire); see
        Fragment.digest. 404 propagates as ClientError — the syncer
        treats it as the canonical empty fragment."""
        out = self._json("GET", _node_url(
            node, "/fragment/digest", index=index, frame=frame, view=view,
            slice=slice_num), extra_headers=extra_headers)
        return bytes.fromhex(out["digest"])

    def fragment_blocks(self, node, index, frame, view, slice_num):
        """[(id, checksum bytes)] (ref: client.go:923). Checksums ride
        as base64 — Go's []byte JSON encoding. (Round-1 in-house nodes
        sent hex; _decode_checksum disambiguates by shape.)"""
        out = self._json("GET", _node_url(
            node, "/fragment/blocks", index=index, frame=frame, view=view,
            slice=slice_num))
        return [(b["id"], _decode_checksum(b["checksum"]))
                for b in out.get("blocks", [])]

    def block_data(self, node, index, frame, view, slice_num, block):
        """(rowIDs, columnIDs) via protobuf BlockDataRequest/Response
        (ref: client.go:965-1011, internal/private.proto:24-35). A peer
        that rejects the protobuf body (round-1 in-house node) is
        retried once over the legacy query-param/JSON form."""
        from pilosa_tpu.server import wireproto

        body = wireproto.encode_block_data_request(
            index, frame, view, slice_num, block)
        status, data, headers = self._do(
            "GET", _node_url(node, "/fragment/block/data"), body=body,
            content_type="application/protobuf",
            accept="application/protobuf")
        if status < 400 and "protobuf" in headers.get("Content-Type", ""):
            return wireproto.decode_block_data_response(data)
        if status == 404:
            raise ClientError(f"block data: {status}: {data[:200]!r}",
                              status=404)
        out = self._json("GET", _node_url(
            node, "/fragment/block/data", index=index, frame=frame,
            view=view, slice=slice_num, block=block))
        return out.get("rowIDs", []), out.get("columnIDs", [])

    def backup_fragment(self, node, index, frame, view, slice_num,
                        extra_headers=None):
        """Raw backup tar bytes (ref: BackupTo client.go:589-666).
        ``extra_headers`` lets the rebalancer stamp its QoS priority
        class on migration streams."""
        status, data, _ = self._do("GET", _node_url(
            node, "/fragment/data", index=index, frame=frame, view=view,
            slice=slice_num), extra_headers=extra_headers)
        if status >= 400:
            raise ClientError(f"backup: {status}", status=status)
        return data

    def restore_fragment(self, node, index, frame, view, slice_num, tar_bytes,
                         extra_headers=None, merge=False):
        """(ref: RestoreFrom client.go:727-806). ``merge=True`` unions
        the snapshot into the remote fragment instead of replacing it
        (the rebalance install contract — see handler)."""
        params = {"index": index, "frame": frame, "view": view,
                  "slice": slice_num}
        if merge:
            params["merge"] = 1
        status, data, _ = self._do(
            "POST", _node_url(node, "/fragment/data", **params),
            tar_bytes, content_type="application/octet-stream",
            extra_headers=extra_headers)
        if status >= 400:
            raise ClientError(f"restore: {status}: {data!r}", status=status)

    # ------------------------------------------------------------ attr diff

    def column_attr_diff(self, node, index, blocks):
        """(ref: client.go:1013)."""
        out = self._json("POST", _node_url(node, f"/index/{index}/attr/diff"),
                         {"blocks": [{"id": b, "checksum": _b64(cs)}
                                     for b, cs in blocks]})
        return {int(k): v for k, v in out.get("attrs", {}).items()}

    def row_attr_diff(self, node, index, frame, blocks):
        """(ref: client.go:1094)."""
        out = self._json(
            "POST", _node_url(node, f"/index/{index}/frame/{frame}/attr/diff"),
            {"blocks": [{"id": b, "checksum": _b64(cs)} for b, cs in blocks]})
        return {int(k): v for k, v in out.get("attrs", {}).items()}

    # ------------------------------------------------------------- messages

    def probe(self, node, timeout=None):
        """Health-probe a node's /id (membership direct probe; also the
        server-side helper for indirect probes). Honors the client's
        TLS context, unlike a bare urlopen."""
        try:
            # Probes bypass the circuit breaker: they ARE the failure
            # detector, and a breaker-refused probe would keep a
            # recovered peer looking dead forever.
            status, _, _ = self._do("GET", _node_url(node, "/id"),
                                    timeout=timeout, bypass_breaker=True)
            return status == 200
        except Exception:  # noqa: BLE001 — a probe's only verdict is
            return False   # up/down; read-phase socket errors, http
            # protocol garbage etc. all mean "down" (and must never
            # kill the membership probe thread).

    def heartbeat(self, node, status, timeout=None):
        """Bidirectional state-exchange probe: POST our compact
        NodeStatus, receive the peer's (the memberlist push/pull
        analog riding the SWIM direct probe). Returns the peer's
        status dict, ``None`` when the peer doesn't serve the endpoint
        (older build — caller falls back to the plain probe), and
        raises on transport failure (peer down)."""
        status_code, body, _ = self._do(
            "POST", _node_url(node, "/internal/heartbeat"),
            json.dumps(status).encode(), timeout=timeout,
            bypass_breaker=True)
        if status_code == 404:
            return None
        if status_code != 200:
            # A wedged peer (5xx on every handler, dead backend behind
            # a proxy) must feed the failure detector exactly as the
            # plain probe's `status == 200` check would.
            raise ClientError(
                f"heartbeat {node.host}: HTTP {status_code}")
        try:
            return json.loads(body)
        except ValueError:
            return {}

    def epochs_fetch(self, node, timeout=None):
        """One peer's current mutation-epoch counters
        (GET /internal/epochs) — the epoch registry's freshness probe.
        Bypasses the circuit breaker like the other probes: it IS part
        of the freshness detector, and a breaker-refused probe would
        hold caches cold against a recovering peer; its failures have
        their own accounting (the registry's probe_failures)."""
        url = _node_url(node, "/internal/epochs")
        status, data, _ = self._do("GET", url, timeout=timeout,
                                   bypass_breaker=True)
        if status >= 400:
            raise ClientError(f"GET {url}: {status}", status=status)
        return json.loads(data)

    def indirect_probe(self, helper, target, timeout=8):
        """Ask ``helper`` to probe ``target`` (SWIM indirect ping;
        membership.py suspicion path). True iff the helper reached it.
        Short timeout: this runs inside the serial membership probe
        loop — a black-holed helper must not stall failure detection."""
        out = self._json("GET", _node_url(
            helper, "/internal/probe", host=target.host), timeout=timeout)
        return bool(out.get("ok"))

    def send_message(self, node, msg, timeout=None):
        """POST /cluster/message as the reference envelope — 1 type
        byte + protobuf (ref: server.go:444-465, broadcast.go:139). A
        peer that can't parse the envelope (round-1 in-house node,
        JSON-only) gets one JSON retry so rolling upgrades don't fail
        DDL broadcasts."""
        from pilosa_tpu.server import wireproto

        body = wireproto.encode_cluster_message(msg)
        status, data, _ = self._do(
            "POST", _node_url(node, "/cluster/message"), body=body,
            content_type="application/x-protobuf", timeout=timeout)
        if status >= 400:
            self._json("POST", _node_url(node, "/cluster/message"), msg,
                       timeout=timeout)
