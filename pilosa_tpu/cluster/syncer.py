"""Anti-entropy: holder-wide replica repair (ref: holder.go:455-671
HolderSyncer + fragment.go:1681-1873 FragmentSyncer).

Every pass: for each index, sync column attrs (block-checksum diff),
each frame's row attrs, then every owned fragment — compare xxhash block
checksums with each replica, majority-merge differing blocks, and push
set/clear deltas back to peers as PQL.
"""
import logging
import threading

from pilosa_tpu import SLICE_WIDTH
from pilosa_tpu import faults

_LOG = logging.getLogger("pilosa_tpu.cluster.syncer")


def _is_not_found(exc):
    """Remote-fragment-missing test: HTTP status when the client
    carried one, plus the reference error text for peers whose errors
    arrive as bare messages. NEVER substring-match '404' — the message
    embeds the URL, and slice 404 of a 10B-column index puts
    'slice=404' in it."""
    return (getattr(exc, "status", None) == 404
            or "fragment not found" in str(exc))


class HolderSyncer:
    # The fragment digest pre-check is EXACT: Fragment.digest() is a
    # content-true multilinear hash over decoded words (fragment.py),
    # so any divergence — including the cardinality-preserving kind
    # the earlier (key, cardinality) digest was systematically blind
    # to — flips it with probability 1 - 2^-64. No periodic
    # unconditional walk is needed; when digests differ, the block
    # checksums below remain the authority (ref: the reference's only
    # mode is that walk, fragment.go:1703-1782).

    def __init__(self, holder, cluster, local_host, client):
        self.holder = holder
        self.cluster = cluster
        self.local_host = local_host
        self.client = client
        self._closing = threading.Event()
        # Fragments whose sync aborted this/any pass (peer down,
        # transport fault, injected syncer.blocks.error) — surfaced as
        # pilosa_syncer_errors_total so a persistently-failing repair
        # is visible instead of silently retried forever.
        self.errors_total = 0

    def close(self):
        self._closing.set()

    @property
    def is_closing(self):
        return self._closing.is_set()

    def _peers(self):
        return [n for n in self.cluster.nodes if n.host != self.local_host]

    # --------------------------------------------------------------- holder

    def sync_holder(self):
        """(ref: HolderSyncer.SyncHolder holder.go:480-538)."""
        for idx in self.holder.indexes_list():
            if self.is_closing:
                return
            self.sync_index(idx)
            for frame_name in sorted(idx.frames):
                frame = idx.frames[frame_name]
                self.sync_frame(idx, frame)
                # Only the standard view's bit data is synced, as in the
                # reference (fragment.go:1807 "Only sync the standard
                # block") — replica SetBit writes fan out to inverse/time
                # views on application.
                max_slice = idx.max_slice()
                for slice_num in range(max_slice + 1):
                    if self.is_closing:
                        return
                    if not self.cluster.owns_fragment(
                            self.local_host, idx.name, slice_num):
                        continue
                    # One fragment's failed sync (unreachable replica,
                    # injected fault) must not abort the rest of the
                    # pass: count it, move on — the next anti-entropy
                    # round retries.
                    try:
                        self.sync_fragment(idx.name, frame_name,
                                           "standard", slice_num)
                    except Exception:  # noqa: BLE001 — isolate per frag
                        self.errors_total += 1
                        self.holder.stats.count("syncer_errors_total", 1)
                        _LOG.warning(
                            "anti-entropy sync of %s/%s slice %d failed",
                            idx.name, frame_name, slice_num,
                            exc_info=True)

    def _sync_attr_store(self, store, fetch_diff):
        """Shared attr sync: push local blocks, merge remote differences
        (ref: syncIndex holder.go:540-586)."""
        blocks = store.blocks()
        for node in self._peers():
            diff = fetch_diff(node, blocks)
            if diff:
                store.set_bulk_attrs(diff)

    def sync_index(self, idx):
        self._sync_attr_store(
            idx.column_attr_store,
            lambda node, blocks: self.client.column_attr_diff(
                node, idx.name, blocks))

    def sync_frame(self, idx, frame):
        """(ref: syncFrame holder.go:588-637)."""
        self._sync_attr_store(
            frame.row_attr_store,
            lambda node, blocks: self.client.row_attr_diff(
                node, idx.name, frame.name, blocks))

    # ------------------------------------------------------------- fragment

    def sync_fragment(self, index, frame, view, slice_num):
        """(ref: FragmentSyncer.SyncFragment fragment.go:1703-1782).

        Scope is the fragment's REPLICA set only (Cluster.FragmentNodes,
        fragment.go:1704) — non-replica nodes must not participate in
        the majority merge or they would vote every local bit out. An
        unreachable replica aborts the sync of this fragment (the
        reference tolerates only fragment-not-found, :1725-1727); a
        missing remote fragment counts as legitimately empty.
        """
        local_frame = self.holder.index(index).frame(frame)
        frag = (local_frame.create_view_if_not_exists(view)
                .create_fragment_if_not_exists(slice_num))

        peers = [n for n in self.cluster.fragment_nodes(index, slice_num)
                 if n.host != self.local_host]
        if not peers:
            return

        # Fragment-level digest pre-check (beyond-ref; the reference
        # walks every fragment's block checksums unconditionally,
        # fragment.go:1703-1782): one content-true value per replica
        # skips the whole walk when replicas agree, which at
        # 10k-fragment scale is the common case for all but the
        # fragments written since the last pass. A peer that doesn't
        # serve the digest route (None) falls through to the walk.
        local_digest = frag.digest()
        # Generator: the first mismatching/unsupporting peer stops the
        # digest RPCs — the block walk below re-contacts everyone.
        if all((d := self._fragment_digest_or_empty(
                    node, index, frame, view, slice_num)) is not None
               and d == local_digest for node in peers):
            return

        peer_blocks = []
        for node in peers:
            peer_blocks.append(dict(self._fragment_blocks_or_empty(
                node, index, frame, view, slice_num)))

        local_blocks = dict(frag.blocks())
        block_ids = sorted(set(local_blocks)
                           | {b for pb in peer_blocks for b in pb})

        for block_id in block_ids:
            if self.is_closing:
                return
            local_cs = local_blocks.get(block_id)
            if all(pb.get(block_id) == local_cs for pb in peer_blocks):
                continue  # replicas agree
            self.sync_block(frag, index, frame, view, slice_num, block_id,
                            peers)

    def _fragment_digest_or_empty(self, node, index, frame, view, slice_num):
        """A 404 whose body says 'fragment not found' is the canonical
        empty digest. A 404 WITHOUT that body is a peer that doesn't
        serve the digest route at all (mixed-version cluster — the
        generic route miss also answers 404 'not found'): return None
        so the caller falls through to the unconditional block walk
        instead of mistaking route-absence for emptiness. Any other
        failure propagates and aborts this fragment's sync."""
        from pilosa_tpu.cluster.client import ClientError

        try:
            return self.client.fragment_digest(node, index, frame, view,
                                               slice_num)
        except ClientError as e:
            if "fragment not found" in str(e):
                return b"\x00" * 8
            if getattr(e, "status", None) == 404:
                return None
            raise

    def _fragment_blocks_or_empty(self, node, index, frame, view, slice_num):
        """A 404 (remote fragment doesn't exist) is an empty replica;
        any other failure propagates and aborts this fragment's sync."""
        from pilosa_tpu.cluster.client import ClientError

        if faults.ACTIVE.enabled:
            faults.ACTIVE.fire("syncer.blocks.error")
        try:
            return self.client.fragment_blocks(node, index, frame, view,
                                               slice_num)
        except ClientError as e:
            if _is_not_found(e):
                return []
            raise

    def sync_block(self, frag, index, frame, view, slice_num, block_id, peers):
        """Pull remote pairs, consensus-merge, push deltas as PQL
        (ref: syncBlock fragment.go:1784-1873)."""
        from pilosa_tpu.cluster.client import ClientError

        pair_sets = []
        for node in peers:
            try:
                rows, cols = self.client.block_data(
                    node, index, frame, view, slice_num, block_id)
            except ClientError as e:
                if _is_not_found(e):
                    rows, cols = [], []
                else:
                    raise
            pair_sets.append((rows, cols))

        diffs = frag.merge_block(block_id, pair_sets)

        # Push set/clear deltas to each peer as PQL writes with Remote
        # semantics, batched to MaxWritesPerRequest per query
        # (ref: fragment.go:1838-1869).
        max_writes = self.cluster.max_writes_per_request or 5000
        idx = self.holder.index(index)
        row_label = idx.frame(frame).row_label
        col_label = idx.column_label
        for node, (sets, clears) in zip(peers, diffs):
            calls = [
                f'SetBit(frame="{frame}", {row_label}={row}, '
                f'{col_label}={slice_num * SLICE_WIDTH + col})'
                for row, col in sets
            ] + [
                f'ClearBit(frame="{frame}", {row_label}={row}, '
                f'{col_label}={slice_num * SLICE_WIDTH + col})'
                for row, col in clears
            ]
            for i in range(0, len(calls), max_writes):
                if self.is_closing:
                    return
                self.client.execute_query(
                    node, index, "\n".join(calls[i : i + max_writes]),
                    remote=True)
