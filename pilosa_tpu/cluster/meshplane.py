"""Collective data plane — intra-pod query fan-out as ONE shard_map
program instead of per-node HTTP.

PAPER.md §7 is explicit that the reference's goroutine-per-node HTTP
scatter/gather becomes JAX collectives over ICI/DCN, yet until this
tier every multi-node query serialized protobuf over sockets between
chips wired at hundreds of GB/s — and the PR 10 ``--phases`` capture
shows fan-out/dispatch, not kernels, dominating per-query cost under
concurrency. This module is the two-tier answer (ROADMAP item 3):

- **within a pod** (one JAX process group sharing one device set —
  operationally: nodes registered under the same ``[mesh] group``):
  slice stacks live as sharded device arrays (``NamedSharding`` over
  the slice axis of a ``Mesh``) and Count/Intersect/Union/Difference/
  Xor/TopN/Sum reduce via ``psum`` inside one ``shard_map`` program
  per query (``parallel/mesh.py`` tree cells). The executor's
  ``_map_reduce`` consults this plane BEFORE the HTTP fan-out; a
  served query never opens a socket.
- **across pods** (or whenever the plane declines): the existing
  HTTP + epoch + placement machinery runs untouched. Every decline is
  counted by reason (``pilosa_mesh_fallback_total{reason=}``), so the
  two tiers are observable as one routing decision.

Membership is a process-global **peer-group registry**: each server
whose config enables the plane registers (host → plane) under its
group name. Registration is the liveness signal — a closing node
unregisters before its listener drains, and a query staged against
its holder after that raises and falls back to HTTP. In-process
multi-node clusters (the test/bench topology — and the single-host
many-chips deployment this emulates) share one registry by
construction; separate OS processes never see each other's registry
and therefore never falsely claim mesh residency.

Validity rides the PR 6 plan-cache protocol: the slice→owner cover
memo keys on the cluster topology state (which folds in the placement
generation/version, PR 10) plus the registry version; staged stacks
carry (mutation epoch, topology state, registry version) tokens —
in-process peers share the module-global epoch counters
(storage/fragment.py), so a write on ANY member invalidates the
coordinator's stacks immediately. During a live resize the plane
declines while the placement is in TRANSITION (stream in flight; the
old generation is authoritative but moving) and resumes at COMMITTED
(every moved fragment checksum-verified, reads prefer the new
generation) — queries fall back to HTTP mid-transition and return to
the collective path at commit with zero failed ops.
"""
import logging
import threading
import time
from collections import OrderedDict

import numpy as np

from pilosa_tpu import WORDS_PER_SLICE, lockcheck, querystats, tracing
from pilosa_tpu.cluster.placement import PHASE_TRANSITION
from pilosa_tpu.observe import kerneltime as kerneltime_mod
from pilosa_tpu.plancache import slice_key
from pilosa_tpu.storage import fragment as _frag

logger = logging.getLogger(__name__)

# try_collective's "not served here" sentinel: distinct from every real
# reduce result (None is a legitimate empty result for some reduces).
DECLINED = object()

# Fixed decline vocabulary, pre-seeded so the /metrics series exist
# from boot (a zero-valued family is diffable; an absent one is not):
#   unsupported — call shape the plane doesn't compile (bitmap
#                 materialization, Min/Max, TopN discovery, filters)
#   no_group    — the group has no other registered member to cover
#                 remote-owned slices
#   not_resident— some owner host is outside the registered group
#   transition  — placement mid-resize (stream in flight)
#   plan        — the batched planner declined the tree
#   budget      — a stack exceeds the [mesh] stack-bytes budget
#   int32       — slice set wider than the int32 psum contract
#   schema      — frame/field missing (serial path owns the error)
#   error       — unexpected failure; logged, query falls back
FALLBACK_REASONS = ("unsupported", "no_group", "not_resident",
                    "transition", "plan", "budget", "int32", "schema",
                    "error")

KINDS = ("count", "topn", "sum")

DEFAULT_GROUP = "local"
DEFAULT_STACK_BYTES = 1 << 30

# Smallest device-stack window (uint32 words) — matches the batched
# executor's MIN_WIN32 so clustered data compiles the same shapes.
MIN_WIN32 = 128


class MeshDecline(Exception):
    """Internal control flow: this query falls back to HTTP, counted
    under ``reason``."""

    def __init__(self, reason):
        super().__init__(reason)
        self.reason = reason


def _topn_static_gate(ex, call):
    """TopN's static mesh-eligibility gate — explicit-id recount
    only, no tanimoto/threshold/filter semantics (those apply per
    NODE partial over HTTP, which a global psum can't reproduce).
    Returns (sorted unique row_ids, frame_name, view) or raises
    MeshDecline. ONE implementation shared by ``_run_topn`` and
    ``explain_decision`` so the twin cannot drift."""
    row_ids, has_ids = call.uint_slice_arg("ids")
    if not has_ids or not row_ids:
        raise MeshDecline("unsupported")
    frame_name, view, _n, min_threshold, tanimoto = \
        ex._topn_call_params(call)
    if (tanimoto or min_threshold > 1
            or (call.args.get("field")
                and call.args.get("filters") is not None)):
        raise MeshDecline("unsupported")
    return sorted(set(row_ids)), frame_name, view


def _sum_static_gate(ex, index, call):
    """Sum/Average's static schema gate: (frame_name, field_name,
    field) or MeshDecline("schema"/"unsupported"). Shared by
    ``_run_sum`` and ``explain_decision``."""
    from pilosa_tpu import errors as perr

    frame_name = call.args.get("frame") or ""
    field_name = call.args.get("field") or ""
    frame = ex.holder.index(index).frame(frame_name)
    if frame is None:
        raise MeshDecline("schema")
    try:
        field = frame.field(field_name)
    except perr.ErrFieldNotFound:
        raise MeshDecline("schema")
    if len(call.children) > 1:
        raise MeshDecline("unsupported")
    return frame_name, field_name, field


# ------------------------------------------------------ peer-group registry

_registry_mu = lockcheck.register("meshplane._registry_mu",
                                  threading.Lock())

# ONE collective program in flight per process: XLA:CPU collectives
# rendezvous all participants of a launch on the shared device set,
# and two concurrent shard_map launches can each hold a subset of the
# per-device execution slots the other needs — a cross-program
# deadlock observed under concurrent serving (meshcheck's resize
# soak). Serializing launch→result is the same funnel the PR 12
# coalescer applies to batched kernels, process-global because every
# plane in this process shares the one device set.
_dispatch_mu = lockcheck.register("meshplane._dispatch_mu",
                                  threading.Lock(),
                                  allow_device_sync=True)
_groups = {}          # group name -> {host: MeshPlane}
_groups_version = 0   # bumps on every (un)registration


def _bump_registry_locked():
    global _groups_version
    _groups_version += 1


def registry_version():
    with _registry_mu:
        return _groups_version


def group_members(group):
    """Snapshot {host: plane} for ``group``."""
    with _registry_mu:
        return dict(_groups.get(group) or ())


class MeshPlane:
    """One node's view of its mesh peer group.

    Thread-safe: ``try_collective`` runs concurrently from handler
    threads; the stack cache is one OrderedDict under a short lock,
    and device staging/dispatch never holds it.
    """

    def __init__(self, holder, cluster, host, group=DEFAULT_GROUP,
                 stack_bytes=DEFAULT_STACK_BYTES, engine=None):
        self.holder = holder
        self.cluster = cluster
        self.local_host = host
        self.group = group or DEFAULT_GROUP
        self.stack_bytes = int(stack_bytes or DEFAULT_STACK_BYTES)
        self._engine = engine
        self._mu = lockcheck.register("meshplane.MeshPlane._mu",
                                      threading.Lock())
        self._stacks = OrderedDict()  # key -> (token, array, nbytes)
        self._bits = {}  # (bits tuple, depth) -> replicated device arg
        self._stack_bytes = 0
        self._stats = {
            "launches": {k: 0 for k in KINDS},
            "fallbacks": {r: 0 for r in FALLBACK_REASONS},
            "stack_hits": 0, "stack_misses": 0, "stack_evictions": 0,
        }

    # ------------------------------------------------------------ members

    @property
    def engine(self):
        """Lazily built MeshQueryEngine over the local device set —
        construction must not force backend init on servers that never
        serve a collective query."""
        eng = self._engine
        if eng is None:
            from pilosa_tpu.parallel.mesh import MeshQueryEngine

            eng = self._engine = MeshQueryEngine()
        return eng

    def register(self):
        with _registry_mu:
            _groups.setdefault(self.group, {})[self.local_host] = self
            _bump_registry_locked()
        return self

    def set_local_host(self, host):
        """A ':0' bind resolved to a real port (server.open): re-key
        the registration so owner-host lookups match."""
        if host == self.local_host:
            return
        with _registry_mu:
            g = _groups.setdefault(self.group, {})
            if g.get(self.local_host) is self:
                del g[self.local_host]
            g[host] = self
            _bump_registry_locked()
        self.local_host = host

    def close(self):
        """Unregister BEFORE the server drains: peers stop routing
        collective reads at our holder the moment we leave."""
        with _registry_mu:
            g = _groups.get(self.group)
            if g and g.get(self.local_host) is self:
                del g[self.local_host]
                _bump_registry_locked()

    # ------------------------------------------------------------- serving

    def try_collective(self, ex, index, call, slices):
        """Serve ``call`` over ``slices`` as one collective program,
        or return DECLINED (counted by reason) so ``_map_reduce``
        proceeds to the HTTP fan-out. The returned value is exactly
        what the fan-out's reduce over the same slices would produce —
        bit-exact by the tree cells' contract."""
        name = call.name
        if name == "Count":
            kind = "count"
        elif name == "TopN":
            kind = "topn"
        elif name in ("Sum", "Average"):
            kind = "sum"
        else:
            return self._decline("unsupported")
        try:
            owners = self._owners(ex, index, slices)
            with tracing.span("mesh.collective", kind=kind,
                              slices=len(slices)):
                t0 = time.perf_counter()
                compiles0 = self.engine.compiles
                if kind == "count":
                    out = self._run_count(ex, index, call, slices,
                                          owners)
                elif kind == "topn":
                    out = self._run_topn(ex, index, call, slices,
                                         owners)
                else:
                    out = self._run_sum(ex, index, call, slices, owners)
                self._note_launch(kind, time.perf_counter() - t0,
                                  len(slices),
                                  compiled=self.engine.compiles
                                  > compiles0)
                querystats.note_tier("mesh")
                return out
        except MeshDecline as d:
            return self._decline(d.reason)
        except Exception:  # noqa: BLE001 — HTTP fan-out is the backstop
            logger.warning("mesh collective failed; falling back to "
                           "HTTP fan-out", exc_info=True)
            return self._decline("error")

    def _decline(self, reason):
        with self._mu:
            self._stats["fallbacks"][reason] += 1
        # Per-query attribution (the aggregate counter above answers
        # "how often"; this answers "why was THIS query slow"): the
        # decline hop rides the active profile/explain accumulator
        # into ?profile=true, the slow-query ring, and trace spans.
        querystats.note_fallback("mesh", reason)
        return DECLINED

    def explain_decision(self, ex, index, call, slices):
        """Read-only prediction of what ``try_collective`` would do:
        ("served", None) or ("declined", reason). Every static gate
        is the SAME predicate the serving path runs
        (``_coverage_decline``, ``_topn_static_gate``,
        ``_sum_static_gate`` — shared so the twin cannot drift), but
        it never stages a stack, launches a program, or writes a
        cache/memo entry — the explain-only contract."""
        name = call.name
        try:
            if name == "Count":
                if len(call.children) != 1:
                    return "declined", "unsupported"
            elif name == "TopN":
                _topn_static_gate(ex, call)
            elif name in ("Sum", "Average"):
                _sum_static_gate(ex, index, call)
            else:
                return "declined", "unsupported"
        except MeshDecline as d:
            return "declined", d.reason
        except Exception:  # noqa: BLE001 — serial path owns the error
            return "declined", "unsupported"
        reason = self._coverage_decline(slices)
        if reason is not None:
            return "declined", reason
        from pilosa_tpu.observe import explain as explain_mod

        # Count's tree — and TopN's src / Sum's filter child when
        # present — must compile through the batched planner, exactly
        # like _run_count/_run_topn/_run_sum.
        if name == "Count" or call.children:
            plan, _leaves = explain_mod.plan_readonly(
                ex, index, call.children[0])
            if plan is None:
                return "declined", "plan"
        # Residency probe SAMPLED like the explain owner summary — a
        # static prediction over a 9,540-slice universe must not pay
        # a per-slice ownership walk per explain (the serving path's
        # own _owners check is exact and plan-cache-memoized; this
        # read-only twin trades edge-case exactness for O(1)-ish
        # cost).
        members = group_members(self.group)
        for s in explain_mod._sample(slices,
                                     explain_mod.OWNER_SAMPLE_SLICES):
            nodes = self.cluster.fragment_nodes(index, s)
            h = nodes[0].host if nodes else None
            if h is None or h not in members:
                return "declined", "not_resident"
        return "served", None

    def _note_launch(self, kind, seconds, n_slices, compiled):
        with self._mu:
            self._stats["launches"][kind] += 1
        obs = kerneltime_mod.ACTIVE
        if obs.enabled:
            # Compile vs steady-state attribution rides the PR 13
            # kerneltime tier: one cost cell per (kind, slice-scale).
            obs.note("mesh_" + kind, "collective",
                     kerneltime_mod.shape_bucket(
                         n_slices * WORDS_PER_SLICE * 4),
                     seconds, compiled=compiled, device=True)

    # ------------------------------------------------------------ coverage

    def _coverage_decline(self, slices):
        """The static coverage gates (slice width vs the int32 psum
        contract, placement TRANSITION, group membership) as a
        reason-or-None predicate — ONE implementation shared by the
        serving path (``_owners``, which raises) and the explain twin
        (``explain_decision``), so the two can never drift."""
        if not slices:
            return "unsupported"
        from pilosa_tpu.parallel.mesh import INT32_SAFE_SLICES

        if len(slices) > INT32_SAFE_SLICES:
            return "int32"
        cl = self.cluster
        pl = getattr(cl, "placement", None)
        if pl is not None and pl.active \
                and pl.mesh_view()[1] == PHASE_TRANSITION:
            # Stream in flight: the old generation is authoritative
            # but fragments are moving — serve over HTTP until commit
            # verifies the new owners. mesh_view is ONE consistent
            # read of (generation, phase, host order).
            return "transition"
        members = group_members(self.group)
        if len(members) <= 1 and len(cl.nodes) > 1:
            return "no_group"
        return None

    def _owners(self, ex, index, slices):
        """Preferred-owner host per slice, all of them registered group
        members — or a MeshDecline. Memoized in the PR 6 plan cache
        against (topology state ⊇ placement generation/version,
        registry version), so the per-slice fragment_nodes walk runs
        once per topology/registration change, not per query."""
        reason = self._coverage_decline(slices)
        if reason is not None:
            raise MeshDecline(reason)
        cl = self.cluster
        members = group_members(self.group)
        state = (cl.topology_state(), registry_version())
        key = ("meshcover", index, slice_key(slices))
        hit = ex.plans.get(key, state)
        if hit is None:
            owners = []
            ok = True
            for s in slices:
                nodes = cl.fragment_nodes(index, s)
                h = nodes[0].host if nodes else None
                if h is None or h not in members:
                    ok = False
                    break
                owners.append(h)
            hit = ("ok", tuple(owners)) if ok else ("miss",)
            ex.plans.put(key, state, hit)
        if hit[0] != "ok":
            raise MeshDecline("not_resident")
        return hit[1]

    # ----------------------------------------------------------- programs

    def _run_count(self, ex, index, call, slices, owners):
        if len(call.children) != 1:
            raise MeshDecline("unsupported")
        plan, leaves = ex._plan_memoized(index, call.children[0])
        if plan is None:
            raise MeshDecline("plan")
        win = self._window(ex, index, slices, owners,
                           self._leaf_views(leaves))
        args, specs = self._stage(ex, index, leaves, slices, owners,
                                  win)
        if "slice" not in specs:
            # Statically-empty plan (e.g. an out-of-range BSI Range
            # shortcut): no sharded stack exists and no program need
            # run — the count over every slice is exactly 0.
            return 0
        with _dispatch_mu:
            return int(np.asarray(self.engine.tree_count(
                plan, args, specs, len(slices))))

    def _run_topn(self, ex, index, call, slices, owners):
        """TopN's exact re-count (phase 2 — explicit ids, the device
        half of the two-phase algorithm) as one collective. Candidate
        discovery reads host cache metadata and stays on its existing
        path; a recount with a non-default threshold, a Tanimoto
        score, or attribute filters keeps the HTTP semantics (those
        apply per NODE partial there, which a global psum can't
        reproduce bit-for-bit)."""
        row_ids, frame_name, view = _topn_static_gate(ex, call)
        src_plan, leaves = None, []
        if call.children:
            src_plan, leaves = ex._plan_memoized(index,
                                                 call.children[0])
            if src_plan is None:
                raise MeshDecline("plan")
        win = self._window(
            ex, index, slices, owners,
            self._leaf_views(leaves, extra=((frame_name, view),)))
        matrix = self._matrix_stack(index, frame_name, view,
                                    tuple(row_ids), slices, owners, win)
        src_args, specs = self._stage(
            ex, index, leaves, slices, owners, win,
            extra_bytes=self.engine.pad_slices(len(slices))
            * len(row_ids) * win[1] * 4)
        with _dispatch_mu:
            counts = np.asarray(self.engine.topn_tree_counts(
                matrix, src_plan, src_args, specs, len(slices)))
        pairs = [(int(r), int(c)) for r, c in zip(row_ids, counts)
                 if c > 0]
        pairs.sort(key=lambda rc: (-rc[1], rc[0]))
        return pairs

    def _run_sum(self, ex, index, call, slices, owners):
        from pilosa_tpu.executor import SumCount
        from pilosa_tpu.storage.view import view_field_name

        frame_name, field_name, field = _sum_static_gate(ex, index,
                                                         call)
        depth = field.bit_depth()
        filt_plan, leaves = None, []
        if call.children:
            filt_plan, leaves = ex._plan_memoized(index,
                                                  call.children[0])
            if filt_plan is None:
                raise MeshDecline("plan")
        win = self._window(
            ex, index, slices, owners,
            self._leaf_views(leaves, extra=(
                (frame_name, view_field_name(field_name)),)))
        planes = self._planes_stack(
            index, frame_name, view_field_name(field_name), depth,
            slices, owners, win)
        filt_args, specs = self._stage(
            ex, index, leaves, slices, owners, win,
            extra_bytes=self.engine.pad_slices(len(slices))
            * (depth + 1) * win[1] * 4)
        with _dispatch_mu:
            out = np.asarray(self.engine.bsi_sum_counts(
                planes, filt_plan, filt_args, specs, len(slices)))
        count = int(out[depth])
        total = sum((1 << i) * int(c) for i, c in enumerate(out[:depth]))
        return SumCount(total + count * field.min, count)

    # ------------------------------------------------------------- staging

    def _stack_token(self, index):
        """Validity token for staged stacks: any member's write bumps
        the (process-shared) mutation epoch; membership/topology/
        placement changes rotate the other components."""
        return (_frag.mutation_epoch(index),
                self.cluster.topology_state(), registry_version())

    @staticmethod
    def _leaf_views(leaves, extra=()):
        """(frame, view) pairs a plan's leaves actually read — the
        window walk is scoped to THEM, like the executor's leaf-
        scoped _union_window (an unrelated full-width frame must not
        inflate this query's stacks)."""
        from pilosa_tpu.storage.view import view_field_name

        out = set(extra)
        for leaf in leaves:
            if leaf[0] == "row":
                out.add((leaf[1], leaf[3]))
            elif leaf[0] == "planes":
                out.add((leaf[1], view_field_name(leaf[2])))
        return out

    @staticmethod
    def _bucket_window(lo, hi):
        """Power-of-FOUR width bucket with a width-aligned base — the
        batched executor's window economy (executor._union_window):
        device stacks size to the data's span, and the bucketing caps
        how many distinct program shapes a drifting window compiles."""
        width = MIN_WIN32
        while width < WORDS_PER_SLICE:
            base = lo - (lo % width)
            if base + width >= hi:
                return base, width
            width *= 4
        return 0, WORDS_PER_SLICE

    def _window(self, ex, index, slices, owners, views):
        """(base32, width32) covering every fragment the plan's leaf
        ``views`` hold for ``slices`` — ONE window per program, so
        every leaf stack of a query shares a shape and the elementwise
        tree fold needs no alignment. Scoped to the leaves' (frame,
        view) pairs, like the executor's _union_window. Epoch-memoized
        in the plan cache (a write that widens a fragment's span bumps
        the epoch and recomputes). A racing mutation serves the
        consistent pre-write snapshot — the same linearizability class
        as the executor's win32/stack-cache token race."""
        token = self._stack_token(index)
        views = tuple(sorted(views))
        key = ("meshwin", index, views, slice_key(slices))
        hit = ex.plans.get(key, token)
        if hit is not None:
            return hit
        members = group_members(self.group)
        lo = hi = None
        for i, s in enumerate(slices):
            plane = members.get(owners[i])
            if plane is None:
                raise RuntimeError(
                    f"mesh member {owners[i]} left the group "
                    f"mid-staging")
            for frame_name, view in views:
                frag = plane.holder.fragment(index, frame_name, view,
                                             s)
                if frag is None:
                    continue
                win = frag.win32()
                if win is None:
                    continue
                b, w = win
                lo = b if lo is None else min(lo, b)
                hi = b + w if hi is None else max(hi, b + w)
        win = ((0, MIN_WIN32) if lo is None
               else self._bucket_window(lo, hi))
        ex.plans.put(key, token, win)
        return win

    def _stage(self, ex, index, leaves, slices, owners, win,
               extra_bytes=0):
        """Stage every leaf's sharded stack. ``extra_bytes`` carries
        stacks the caller staged directly (TopN's ids matrix, Sum's
        planes) so the budget bounds the QUERY'S aggregate working
        set, not just each stack — the per-query analog of the
        batched executor's BATCH_OVER_BUDGET (LRU eviction cannot
        free arrays an in-flight query still references)."""
        import jax.numpy as jnp

        width = win[1]
        pad = self.engine.pad_slices(len(slices))
        total = extra_bytes
        args, specs = [], []
        for leaf in leaves:
            kind = leaf[0]
            if kind == "row":
                total += pad * width * 4
            elif kind == "planes":
                total += pad * (leaf[3] + 1) * width * 4
            if total > self.stack_bytes:
                raise MeshDecline("budget")
            if kind == "bits":
                # Predicate-bit vectors are immutable by value — cache
                # the replicated device arg (the _nv discipline: a
                # fresh jnp.asarray would device_put on EVERY query).
                arr = self._bits.get(leaf[1:])
                if arr is None:
                    if len(self._bits) > 4096:
                        self._bits.clear()
                    arr = self._bits[leaf[1:]] = jnp.asarray(
                        list(leaf[1]), dtype=jnp.int32)
                args.append(arr)
                specs.append("rep")
            elif kind == "row":
                _, frame_name, row_id, view = leaf
                args.append(self._row_stack(index, frame_name, view,
                                            row_id, slices, owners,
                                            win))
                specs.append("slice")
            elif kind == "planes":
                _, frame_name, field_name, depth = leaf
                from pilosa_tpu.storage.view import view_field_name

                args.append(self._planes_stack(
                    index, frame_name, view_field_name(field_name),
                    depth, slices, owners, win))
                specs.append("slice")
            else:
                raise MeshDecline("plan")
        return tuple(args), tuple(specs)

    @staticmethod
    def _member_fragment(members, index, frame_name, view, s, host):
        """The owning member's fragment for one slice, from a members
        snapshot taken once per stack build — registration IS
        liveness: a member that closed mid-query raises so the query
        falls back loudly instead of counting zeros."""
        plane = members.get(host)
        if plane is None:
            raise RuntimeError(
                f"mesh member {host} left the group mid-staging")
        return plane.holder.fragment(index, frame_name, view, s)

    def _row_stack(self, index, frame_name, view, row_id, slices,
                   owners, win):
        base, width = win
        key = ("row", index, frame_name, view, row_id, win,
               slice_key(slices))
        token = self._stack_token(index)
        pad = self.engine.pad_slices(len(slices))
        nbytes = pad * width * 4

        def build():
            members = group_members(self.group)
            host = np.zeros((pad, width), np.uint32)
            for i, (s, h) in enumerate(zip(slices, owners)):
                frag = self._member_fragment(members, index,
                                             frame_name, view, s, h)
                if frag is not None:
                    host[i] = np.ascontiguousarray(
                        frag.row_words(row_id)).view(
                            np.uint32)[base:base + width]
            return self.engine.shard_rows(host)

        return self._stack(key, token, nbytes, build)

    def _planes_stack(self, index, frame_name, view, depth, slices,
                      owners, win):
        base, width = win
        key = ("planes", index, frame_name, view, depth, win,
               slice_key(slices))
        token = self._stack_token(index)
        pad = self.engine.pad_slices(len(slices))
        nbytes = pad * (depth + 1) * width * 4

        def build():
            members = group_members(self.group)
            host = np.zeros((pad, depth + 1, width), np.uint32)
            for i, (s, h) in enumerate(zip(slices, owners)):
                frag = self._member_fragment(members, index,
                                             frame_name, view, s, h)
                if frag is None:
                    continue
                for p in range(depth + 1):
                    host[i, p] = np.ascontiguousarray(
                        frag.row_words(p)).view(
                            np.uint32)[base:base + width]
            return self.engine.shard_rows(host)

        return self._stack(key, token, nbytes, build)

    def _matrix_stack(self, index, frame_name, view, row_ids, slices,
                      owners, win):
        base, width = win
        key = ("matrix", index, frame_name, view, row_ids, win,
               slice_key(slices))
        token = self._stack_token(index)
        pad = self.engine.pad_slices(len(slices))
        nbytes = pad * len(row_ids) * width * 4

        def build():
            members = group_members(self.group)
            host = np.zeros((pad, len(row_ids), width), np.uint32)
            for i, (s, h) in enumerate(zip(slices, owners)):
                frag = self._member_fragment(members, index,
                                             frame_name, view, s, h)
                if frag is None:
                    continue
                for j, rid in enumerate(row_ids):
                    host[i, j] = np.ascontiguousarray(
                        frag.row_words(rid)).view(
                            np.uint32)[base:base + width]
            return self.engine.shard_rows(host)

        return self._stack(key, token, nbytes, build)

    def _stack(self, key, token, nbytes, build):
        """Epoch-validated byte-budgeted LRU of sharded device stacks.
        ``nbytes`` is the caller-computed size, checked BEFORE the
        host alloc/device_put — the budget must prevent the staging it
        bounds (an oversized client-chosen ids matrix must decline,
        not OOM). The token is read by the CALLER before staging, so a
        write landing mid-build makes the entry stale-on-arrival,
        never wrong (the plan-cache discipline). Device staging runs
        outside the lock."""
        if nbytes > self.stack_bytes:
            raise MeshDecline("budget")
        with self._mu:
            ent = self._stacks.get(key)
            if ent is not None and ent[0] == token:
                self._stacks.move_to_end(key)
                self._stats["stack_hits"] += 1
                return ent[1]
        arr = build()
        with self._mu:
            self._stats["stack_misses"] += 1
            old = self._stacks.pop(key, None)
            if old is not None:
                self._stack_bytes -= old[2]
            while (self._stacks
                   and self._stack_bytes + nbytes > self.stack_bytes):
                _, (_t, _a, nb) = self._stacks.popitem(last=False)
                self._stack_bytes -= nb
                self._stats["stack_evictions"] += 1
            self._stacks[key] = (token, arr, nbytes)
            self._stack_bytes += nbytes
        return arr

    # --------------------------------------------------------------- intro

    def _coords(self):
        """host → mesh coordinate: the pinned placement generation's
        host order when one exists (so device sharding and ownership
        agree across the group), else the static node list."""
        pl = getattr(self.cluster, "placement", None)
        if pl is not None and pl.active:
            return pl.mesh_coords()
        return {n.host: i for i, n in enumerate(self.cluster.nodes)}

    def metrics(self):
        """Flat dict for the /metrics ``pilosa_mesh_*`` group — always
        present while the plane is wired (zeroed on an idle server),
        declines tagged by reason, launches by call kind."""
        members = group_members(self.group)
        with self._mu:
            st = self._stats
            out = {
                "enabled": 1,
                "members": len(members),
                "stack_bytes": self._stack_bytes,
                "stack_capacity_bytes": self.stack_bytes,
                "stack_entries": len(self._stacks),
                "stack_hits_total": st["stack_hits"],
                "stack_misses_total": st["stack_misses"],
                "stack_evictions_total": st["stack_evictions"],
            }
            for k in KINDS:
                out[f"collective_launches_total;kind:{k}"] = \
                    st["launches"][k]
            for r in FALLBACK_REASONS:
                out[f"fallback_total;reason:{r}"] = st["fallbacks"][r]
        return out

    def snapshot(self):
        """GET /debug/mesh payload."""
        members = group_members(self.group)
        coords = self._coords()
        pl = getattr(self.cluster, "placement", None)
        placement = None
        if pl is not None and pl.active:
            w = pl.wire_state()
            placement = {"generation": w["generation"],
                         "phase": w["phase"]}
        with self._mu:
            st = self._stats
            return {
                "enabled": True,
                "group": self.group,
                "localHost": self.local_host,
                "members": {h: {"coord": coords.get(h)}
                            for h in sorted(members)},
                "devices": (self._engine.n_devices
                            if self._engine is not None else None),
                "placement": placement,
                "launches": dict(st["launches"]),
                "fallbacks": dict(st["fallbacks"]),
                "stack": {
                    "bytes": self._stack_bytes,
                    "capacityBytes": self.stack_bytes,
                    "entries": len(self._stacks),
                    "hits": st["stack_hits"],
                    "misses": st["stack_misses"],
                    "evictions": st["stack_evictions"],
                },
            }
