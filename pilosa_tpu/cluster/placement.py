"""Versioned slice placement — the routing half of elastic topology.

The legacy ``Cluster`` jump-hashes fragments straight off the live
node list, so adding or removing a node INSTANTLY reassigns slices
that the new owner does not yet hold (ROADMAP open item 5). This
module pins the hash to an explicit **generation**: an ordered host
list with a monotonically increasing generation number, changed only
by an operator-driven resize (POST /cluster/resize → rebalancer.py),
never by membership churn. A node joining the membership plane gains
RPC reachability but zero slice ownership until a resize commits.

A resize walks three phases, each broadcast cluster-wide as one
full-state message (idempotent, seq-guarded, also piggybacked on the
membership heartbeat so a peer that missed a broadcast converges
within one probe interval):

- ``TRANSITION`` (old gen → new gen streaming): reads fan out to the
  union of old+new owners **preferring the old generation** (its data
  is complete); writes land on BOTH generations' owners, so nothing
  acknowledged during the stream can be lost whichever way the resize
  resolves.
- ``COMMITTED`` (stream verified): reads prefer the NEW generation
  (every moved fragment is checksum-verified); writes STILL land on
  both generations, so a peer that has not yet seen the commit serves
  reads from old owners that keep receiving writes.
- ``STABLE`` (cleanup): the old generation is dropped, routing is new
  gen only, and each node prunes local fragments it no longer owns.

An aborted stream broadcasts the old generation's STABLE state back
out — the new generation never becomes visible to routing, and the
dual-written old owners are still complete.

Per-node roles during a resize: hosts in new-but-not-old are
``JOINING``, hosts in old-but-not-new are ``LEAVING`` (a LEAVING
node's server waits for handoff before SIGTERM exit — server.py).

Epoch continuity: none of this invalidates by wiping — the placement
``version`` counter is folded into the cluster topology state that
keys every owner-set/slice-plan memo (cluster.topology_state()), so
plan tokens rotate exactly at phase changes, never mid-stream, and
the PR 5 epoch vectors keep replay/memo validity correct across the
owner-set change (a token minted over the old owner set simply stops
matching).
"""
import threading

from pilosa_tpu import lockcheck

PHASE_STABLE = "stable"
PHASE_TRANSITION = "transition"
PHASE_COMMITTED = "committed"

# Ordering for same-generation convergence: a later phase of the SAME
# target generation always supersedes an earlier one.
_PHASE_RANK = {PHASE_TRANSITION: 0, PHASE_COMMITTED: 1, PHASE_STABLE: 2}

ROLE_JOINING = "JOINING"
ROLE_LEAVING = "LEAVING"
ROLE_MEMBER = "MEMBER"


class PlacementMap:
    """Generation-pinned slice→host placement.

    ``active=False`` (the boot state) means no resize has ever touched
    this cluster: ``Cluster.fragment_nodes`` keeps its legacy
    live-node-list jump hash, byte-identical to every pre-placement
    behavior. The first applied resize state (local begin or a peer's
    broadcast/heartbeat) activates the map, and from then on routing
    is pinned to the committed generation.

    Thread-safe; every read used on the serving path is a snapshot
    under one short lock, memoized one level up by
    ``Cluster.fragment_nodes`` against ``version``.
    """

    def __init__(self, hosts=None):
        self._mu = lockcheck.register("placement.PlacementMap._mu",
                                      threading.Lock())
        self.active = False
        self.generation = 0          # committed generation number
        self.phase = PHASE_STABLE
        self._hosts = tuple(hosts or ())       # current-gen ordered hosts
        self._prev_hosts = ()                  # prior gen during a resize
        self._prev_generation = 0
        # Bumps on EVERY applied change; folded into
        # Cluster.topology_state() so owner/plan memos rotate at phase
        # boundaries (begin/commit/cleanup/abort), never mid-stream.
        self.version = 0
        # Broadcast sequence guard: full-state messages apply only when
        # strictly newer, so re-deliveries and heartbeat piggybacks are
        # idempotent and an abort (which moves "backwards" to the old
        # generation) still supersedes the transition it cancels.
        self.seq = 0
        # Flight recorder (observe.events), server-installed; None
        # when off. Phase changes emit AFTER _mu releases.
        self.events = None

    def _emit(self, kind, **fields):
        ev = self.events
        if ev is not None:
            ev.emit(kind, **fields)

    # ------------------------------------------------------------ hashing

    @staticmethod
    def _owners_for(hosts, pid, replica_n, hasher):
        """Primary + replica successors for one partition over one
        generation's ordered host list — the same ring walk as
        ``Cluster.partition_nodes``, host-level."""
        if not hosts:
            return ()
        r = min(replica_n, len(hosts)) or 1
        start = hasher.hash(pid, len(hosts))
        return tuple(hosts[(start + i) % len(hosts)]
                     for i in range(r))

    @staticmethod
    def preview_owners(hosts, pid, replica_n, hasher):
        """Owners of ``pid`` under a CANDIDATE ordered host list —
        the autopilot placement planner's pure simulation surface
        (same ring walk as the pinned generation; no placement state
        is read or touched)."""
        return PlacementMap._owners_for(tuple(hosts), pid, replica_n,
                                        hasher)

    def owner_hosts(self, pid, replica_n, hasher):
        """Ordered owner hosts for partition ``pid``. Stable: the
        pinned generation. Transition: union preferring OLD (data-
        complete) owners. Committed: union preferring NEW (verified)
        owners. Writers iterate the whole tuple (dual writes during a
        resize); readers take the first live entry."""
        with self._mu:
            phase = self.phase
            hosts = self._hosts
            prev = self._prev_hosts
        cur = self._owners_for(hosts, pid, replica_n, hasher)
        if phase == PHASE_STABLE or not prev:
            return cur
        old = self._owners_for(prev, pid, replica_n, hasher)
        if phase == PHASE_TRANSITION:
            return old + tuple(h for h in cur if h not in old)
        return cur + tuple(h for h in old if h not in cur)

    # ------------------------------------------------------ state machine

    def rename_host(self, old, new):
        """A ':0' bind resolved to a real port (server.open): keep the
        generation host lists pointing at the reachable name."""
        with self._mu:
            self._hosts = tuple(new if h == old else h
                                for h in self._hosts)
            self._prev_hosts = tuple(new if h == old else h
                                     for h in self._prev_hosts)
            if self.active:
                self.version += 1

    def pin(self, hosts):
        """Activate at a STABLE generation pinned to ``hosts`` (no-op
        when already active). The first step of a resize, BEFORE any
        membership mutation: once pinned, adding the joining node to
        the live list cannot reroute a single slice — the window
        between "node joined" and "transition begun" would otherwise
        reproduce the exact instant-reassignment bug this module
        exists to kill."""
        with self._mu:
            if self.active:
                return
            self.active = True
            self._hosts = tuple(hosts)
            if self.generation == 0:
                self.generation = 1
            self.seq += 1
            self.version += 1
            gen = self.generation
            n_hosts = len(self._hosts)
        self._emit("placement.pin", generation=gen, hosts=n_hosts)

    def next_generation(self):
        with self._mu:
            return self.generation + 1

    def begin(self, new_hosts, prev_hosts, generation, seq=None):
        """Coordinator-side transition start. Returns the wire state
        to broadcast. Raises if a resize is already in flight."""
        new_hosts = tuple(new_hosts)
        with self._mu:
            if self.active and self.phase != PHASE_STABLE:
                raise RuntimeError(
                    f"resize already in flight (generation "
                    f"{self.generation}→ phase {self.phase})")
            if generation <= self.generation:
                raise RuntimeError(
                    f"generation {generation} not newer than committed "
                    f"{self.generation}")
            self.active = True
            self._prev_hosts = tuple(prev_hosts)
            self._prev_generation = self.generation
            self._hosts = new_hosts
            self.generation = generation
            self.phase = PHASE_TRANSITION
            self.seq = self.seq + 1 if seq is None else max(
                self.seq + 1, seq)
            self.version += 1
            wire = self._wire_locked()
        self._emit("placement.transition", generation=wire["generation"],
                   prevGeneration=wire["prevGeneration"])
        return wire

    def commit(self):
        """Transition → committed (reads flip to the new generation;
        writes stay dual until cleanup). Returns the wire state."""
        with self._mu:
            if self.phase != PHASE_TRANSITION:
                raise RuntimeError(f"commit from phase {self.phase}")
            self.phase = PHASE_COMMITTED
            self.seq += 1
            self.version += 1
            wire = self._wire_locked()
        self._emit("placement.committed", generation=wire["generation"])
        return wire

    def cleanup(self):
        """Committed → stable: drop the old generation. Returns the
        wire state; the caller prunes no-longer-owned fragments."""
        with self._mu:
            if self.phase != PHASE_COMMITTED:
                raise RuntimeError(f"cleanup from phase {self.phase}")
            self.phase = PHASE_STABLE
            self._prev_hosts = ()
            self.seq += 1
            self.version += 1
            wire = self._wire_locked()
        self._emit("placement.stable", generation=wire["generation"])
        return wire

    def abort(self):
        """Transition → stable on the OLD generation: the new
        generation never becomes routable. Returns the wire state."""
        with self._mu:
            if self.phase != PHASE_TRANSITION:
                raise RuntimeError(f"abort from phase {self.phase}")
            aborted = self.generation
            self._hosts = self._prev_hosts
            self.generation = self._prev_generation
            self._prev_hosts = ()
            self.phase = PHASE_STABLE
            self.seq += 1
            self.version += 1
            wire = self._wire_locked()
        self._emit("placement.abort", generation=wire["generation"],
                   abortedGeneration=aborted)
        return wire

    # ----------------------------------------------------------- the wire

    def _wire_locked(self):
        """Full-state wire dict. Caller holds the lock."""
        return {
            "generation": self.generation,
            "prevGeneration": self._prev_generation,
            "phase": self.phase,
            "hosts": list(self._hosts),
            "prevHosts": list(self._prev_hosts),
            "seq": self.seq,
        }

    def wire_state(self):
        with self._mu:
            return self._wire_locked()

    def classify(self, state):
        """How ``apply_state`` would treat ``state``, without applying:
        ``"newer"`` (would apply), ``"duplicate"`` (exact re-delivery —
        benign, counts as delivered), ``"stale"`` (the SENDER is behind
        — e.g. a restarted coordinator whose in-memory seq reset), or
        ``"malformed"``. Broadcast receivers answer stale/malformed
        with an ERROR instead of a silent 200, so a behind-the-cluster
        coordinator aborts instead of streaming and committing against
        peers that ignored every phase change."""
        try:
            seq = int(state["seq"])
            gen = int(state["generation"])
            phase = state["phase"]
            hosts = tuple(str(h) for h in state["hosts"])
        except (KeyError, TypeError, ValueError):
            return "malformed"
        if phase not in _PHASE_RANK or not hosts:
            return "malformed"
        with self._mu:
            if not self.active:
                return "newer"
            incoming = (seq, gen, _PHASE_RANK[phase])
            local = (self.seq, self.generation, _PHASE_RANK[self.phase])
        if incoming > local:
            return "newer"
        if incoming == local:
            return "duplicate"
        return "stale"

    def apply_state(self, state):
        """Apply a peer's full placement state (broadcast message or
        heartbeat piggyback). Strictly-newer-seq wins; equal seq with
        a later phase rank of the same generation wins (two
        coordinators cannot both start a resize — begin refuses unless
        stable — so seq ties only arise from re-deliveries). Returns
        True when local state changed."""
        try:
            seq = int(state["seq"])
            gen = int(state["generation"])
            phase = state["phase"]
            hosts = tuple(str(h) for h in state["hosts"])
            prev = tuple(str(h) for h in state.get("prevHosts") or ())
            prev_gen = int(state.get("prevGeneration") or 0)
        except (KeyError, TypeError, ValueError):
            return False
        if phase not in _PHASE_RANK or not hosts:
            return False
        with self._mu:
            newer = (seq, gen, _PHASE_RANK[phase]) > (
                self.seq, self.generation, _PHASE_RANK[self.phase])
            if self.active and not newer:
                return False
            self.active = True
            self.seq = seq
            self.generation = gen
            self._prev_generation = prev_gen
            self.phase = phase
            self._hosts = hosts
            self._prev_hosts = prev if phase != PHASE_STABLE else ()
            self.version += 1
        self._emit("placement.apply", generation=gen, phase=phase)
        return True

    # ------------------------------------------------------------- intro

    def role(self, host):
        """JOINING / LEAVING / MEMBER / None for ``host`` under the
        current phase (None = not a member at all)."""
        with self._mu:
            in_cur = host in self._hosts
            in_prev = host in self._prev_hosts
            mid_resize = self.phase != PHASE_STABLE
        if mid_resize and in_cur and not in_prev:
            return ROLE_JOINING
        if mid_resize and in_prev and not in_cur:
            return ROLE_LEAVING
        if in_cur or (mid_resize and in_prev):
            return ROLE_MEMBER
        return None

    def is_leaving(self, host):
        return self.role(host) == ROLE_LEAVING

    def member_hosts(self):
        """Union of current + prior generation hosts (everyone routing
        may touch mid-resize)."""
        with self._mu:
            return tuple(dict.fromkeys(self._hosts + self._prev_hosts))

    # ------------------------------------------------------- mesh plane

    def mesh_view(self):
        """(generation, phase, ordered hosts) snapshot for the mesh
        data plane (cluster/meshplane.py): one lock, one consistent
        read — the plane gates on the phase and derives device
        coordinates from the SAME generation order that routing is
        pinned to, so host ownership and device sharding can never
        disagree mid-resize."""
        with self._mu:
            return self.generation, self.phase, self._hosts

    def mesh_coords(self, hosts=None):
        """host → mesh coordinate: the position in the pinned CURRENT
        generation's ordered host list (the slice axis is laid out in
        this order when a pod maps group members to device blocks).
        Deterministic across every member because the generation list
        itself is broadcast state; hosts outside the generation (e.g.
        a JOINING node before commit) map to None."""
        with self._mu:
            gen_hosts = self._hosts
        coords = {h: i for i, h in enumerate(gen_hosts)}
        if hosts is None:
            return coords
        return {h: coords.get(h) for h in hosts}

    def current_hosts(self):
        with self._mu:
            return self._hosts

    def prev_hosts(self):
        with self._mu:
            return self._prev_hosts

    def snapshot(self):
        """Rich JSON for /debug/rebalance and /status."""
        with self._mu:
            out = self._wire_locked()
            out["active"] = self.active
            out["version"] = self.version
        roles = {}
        for h in out["hosts"]:
            roles[h] = self.role(h)
        for h in out["prevHosts"]:
            roles.setdefault(h, self.role(h))
        out["roles"] = roles
        return out
