"""Online rebalancer — background slice migration for elastic topology.

``Rebalancer.resize(new_hosts)`` (POST /cluster/resize) walks the
placement state machine (cluster/placement.py):

1. **Begin.** Pin the new generation in TRANSITION, broadcast the
   full placement state to every node in the union of both
   generations (a failure here aborts before anything streams —
   dual writes must be in force cluster-wide before data moves).
2. **Stream.** Compute the slice diff (owners under the old vs new
   generation's pinned jump hash) and copy every affected fragment to
   its new owners over the existing backup/restore block protocol
   (GET/POST /fragment/data — the anti-entropy transport), verifying
   each copy with the content-true fragment digest; a digest mismatch
   (concurrent write between snapshot and verify, or an injected
   ``rebalance.stream.corrupt``) re-snapshots and re-ships, bounded.
   Streams run ``stream-concurrency`` at a time, paced to
   ``bandwidth`` bytes/sec (0 = unpaced), and carry the ``rebalance``
   QoS priority class — below every user read at the admission gate.
3. **Commit.** Broadcast COMMITTED: reads flip to the verified new
   generation; writes stay dual. Delivery is retried until every
   member has it (the heartbeat piggyback converges any peer that
   stays unreachable — ``rebalance.commit.partial`` injects exactly
   that), and only then:
4. **Cleanup.** Broadcast STABLE; every node prunes local fragments
   it no longer owns. Any stream failure instead broadcasts the old
   generation back out (abort) — the new generation never becomes
   routable and the dual-written old owners are complete, so no
   acknowledged write is ever lost.

Epoch continuity: fragment installs on the new owner bump ITS
per-index mutation epoch (storage/fragment.read_from), and the
streaming RPC responses piggyback the bumped counters back to the
coordinator (cluster/epochs.py) — so when the commit rotates the
owner-set plan tokens, the epoch vector over the NEW owner set is
already warm and replay/memo/plan tiers recover within one probe TTL
instead of collapsing to cold.

Locking: ``_mu`` guards counters/state only and is NEVER held across
a stream RPC — ``lockcheck.io_point("rebalance.stream")`` asserts it
(and every other registered lock) on every transfer.
"""
import io
import logging
import threading
import time

from pilosa_tpu import faults, lockcheck, qos, tracing
from pilosa_tpu import stats as stats_mod
from pilosa_tpu.cluster import placement as placement_mod
from pilosa_tpu.cluster.cluster import Node

logger = logging.getLogger("pilosa_tpu.cluster.rebalancer")

# Stamped on every stream RPC: the admission gate on the receiving
# node queues migration traffic behind interactive reads (qos.py maps
# "rebalance" to the batch class).
_STREAM_HEADERS = {qos.PRIORITY_HEADER: "rebalance"}

# A digest mismatch after restore means a write raced the snapshot
# (dual writes are live during the stream) or the payload was
# corrupted in flight: re-snapshot and re-ship. Sustained writes to
# one fragment could starve a single attempt, so the bound is
# generous; exhausting it fails the stream (→ abort, never commit).
STREAM_VERIFY_RETRIES = 5

DEFAULT_STREAM_CONCURRENCY = 2
DEFAULT_COMMIT_RETRY_INTERVAL = 2.0
DEFAULT_COMMIT_RETRIES = 30


class RebalanceError(RuntimeError):
    pass


class Rebalancer:
    """One per multi-node server. Idle until ``resize()`` (the
    coordinator role) or a peer's placement broadcast/heartbeat
    (``receive_state`` / ``merge_placement``) arrives."""

    def __init__(self, holder, cluster, local_host, client,
                 stream_concurrency=DEFAULT_STREAM_CONCURRENCY,
                 bandwidth=0,
                 commit_retry_interval=DEFAULT_COMMIT_RETRY_INTERVAL,
                 commit_retries=DEFAULT_COMMIT_RETRIES,
                 tracer=None, stats=None, pending_hints_fn=None):
        self.holder = holder
        self.cluster = cluster
        self.local_host = local_host
        self.client = client
        self.stream_concurrency = max(1, int(stream_concurrency))
        self.bandwidth = max(0, int(bandwidth))  # bytes/sec; 0 = unpaced
        self.commit_retry_interval = float(commit_retry_interval)
        self.commit_retries = int(commit_retries)
        self.tracer = tracer or tracing.NOP
        self.stats = stats or stats_mod.NOP
        # Executor.pending_hint_hosts when wired (server.py): a resize
        # must not begin while acked writes sit in hint queues — their
        # replay targets pre-resize owners.
        self.pending_hints_fn = pending_hints_fn
        self._hist = stats_mod.NOP_HISTOGRAM
        self._peer_hists = {}
        self._mu = lockcheck.register("rebalancer.Rebalancer._mu",
                                      threading.Lock())
        self._running = False
        self._thread = None
        self._closing = threading.Event()
        # Bandwidth pacing slot (monotonic instant the next transfer
        # may start); guarded by _mu, advanced per payload.
        self._bw_next = 0.0
        self.counters = {
            "slices_total": 0, "slices_moved": 0,
            "fragments_moved": 0, "bytes_streamed": 0,
            "stream_retries": 0, "stream_failures": 0,
            "commits": 0, "aborts": 0, "cleanups": 0,
            "prunes": 0, "pruned_fragments": 0,
            "reconciled_fragments": 0, "reconciled_bits": 0,
        }
        self._last_error = None
        self._started_at = None    # monotonic, current/last run
        self._finished_at = None
        self._per_peer = {}        # host -> {"fragments", "bytes", "seconds"}
        # Flight recorder (observe.events), server-installed; None
        # when off. Stage transitions (begin/stream/verify/reconcile/
        # cleanup/abort/resume) are journal events.
        self.events = None

    def _emit(self, kind, **fields):
        ev = self.events
        if ev is not None:
            ev.emit(kind, **fields)

    # ------------------------------------------------------------- wiring

    @property
    def placement(self):
        return self.cluster.placement

    def set_histogram(self, hist):
        """Per-peer stream-duration histogram family
        (``pilosa_rebalance_stream_seconds{peer=...}``)."""
        self._hist = hist

    def _peer_hist(self, host):
        h = self._peer_hists.get(host)
        if h is None:
            h = self._peer_hists[host] = self._hist.with_tags(
                f"peer:{host}")
        return h

    def close(self):
        self._closing.set()

    # ------------------------------------------------- coordinator: resize

    def resize(self, new_hosts, reason=None):
        """Begin a resize to ``new_hosts`` (ordered — the jump hash is
        order-sensitive and every node must agree). Broadcasts the
        transition, then streams in the background; returns a summary
        dict immediately. Raises RebalanceError on conflict/validation
        failure (mapped to 409/400 by the handler). ``reason`` tags
        the ``rebalance.begin`` journal entry with who asked (the
        autopilot stamps ``"autopilot"``; operator POSTs leave it
        unset) so a merged timeline attributes every move."""
        new_hosts = [str(h) for h in new_hosts]
        if not new_hosts or len(set(new_hosts)) != len(new_hosts):
            raise RebalanceError("hosts must be a non-empty unique list")
        with self._mu:
            if self._running:
                raise RebalanceError("a rebalance is already running")
            self._running = True
        try:
            pl = self.placement
            if (pl.active
                    and pl.phase == placement_mod.PHASE_COMMITTED
                    and list(new_hosts) == list(pl.current_hosts())):
                # Resume: the committed generation's finish work
                # (delivery / reconcile / cleanup) died with a
                # restarted coordinator — re-drive it. The operator's
                # unwedge path: POST the CURRENT host list again.
                return self._resume(new_hosts)
            return self._begin(new_hosts, reason)
        except BaseException:
            with self._mu:
                self._running = False
            raise

    def _begin(self, new_hosts, reason=None):
        pl = self.placement
        if pl.active:
            old_hosts = list(pl.current_hosts())
        else:
            old_hosts = [n.host for n in self.cluster.nodes]
            # Pin the CURRENT generation before anything else: from
            # here on, membership mutations (adding the joining nodes
            # below) cannot reroute a slice — only the begin/commit
            # phase changes can.
            pl.pin(old_hosts)
        if list(new_hosts) == old_hosts:
            raise RebalanceError("hosts unchanged")
        if self.pending_hints_fn is not None:
            pending = self.pending_hints_fn()
            if pending:
                raise RebalanceError(
                    f"hinted writes pending for {pending}: wait for "
                    f"replay (peer rejoin) or anti-entropy before "
                    f"resizing")
        self._ensure_nodes(new_hosts)
        # JOINING nodes need the schema before fragments can install
        # (restore creates views/fragments under an EXISTING frame) —
        # the same push a rejoining peer gets. Failing here fails the
        # resize before any state changed anywhere.
        for h in new_hosts:
            if h in old_hosts or h == self.local_host:
                continue
            node = self.cluster.node_by_host(h)
            try:
                self.client.post_schema(
                    node, self.holder.schema(include_meta=True))
                # Max-slice knowledge too: a query routed THROUGH the
                # joining node before its first heartbeat exchange
                # must still walk the full slice universe.
                for idx in self.holder.indexes_list():
                    self.client.send_message(node, {
                        "type": "create-slice", "index": idx.name,
                        "slice": idx.max_slice()})
                    inv = idx.max_inverse_slice()
                    if inv:
                        self.client.send_message(node, {
                            "type": "create-slice", "index": idx.name,
                            "slice": inv, "inverse": True})
            except Exception as e:  # noqa: BLE001 — pre-flight verdict
                raise RebalanceError(
                    f"schema push to joining node {h} failed: {e}")
        try:
            state = pl.begin(new_hosts, old_hosts, pl.next_generation())
        except RuntimeError as e:
            raise RebalanceError(str(e))
        self.cluster.topology_version += 1
        with self._mu:
            self._last_error = None
            self._started_at = time.monotonic()
            self._finished_at = None
            self._per_peer = {}
        # Begin must reach EVERY member before data moves: dual writes
        # are the no-lost-acks invariant. Any delivery failure aborts
        # while nothing has streamed yet.
        failures = self._broadcast_state(state)
        if failures:
            abort_state = pl.abort()
            self.cluster.topology_version += 1
            self._broadcast_state(abort_state)  # best-effort revert
            with self._mu:
                self._running = False
                self.counters["aborts"] += 1
                self._last_error = f"begin broadcast failed: {failures}"
                self._finished_at = time.monotonic()
            raise RebalanceError(
                f"begin broadcast failed: {failures}")
        plan = self._plan_moves(old_hosts, new_hosts)
        with self._mu:
            self.counters["slices_total"] = len(
                {(t[0], t[3]) for t in plan})
            self.counters["slices_moved"] = 0
        self._thread = threading.Thread(
            target=self._run, args=(plan,), daemon=True,
            name="rebalancer")
        self._thread.start()
        added = [h for h in new_hosts if h not in old_hosts]
        removed = [h for h in old_hosts if h not in new_hosts]
        self._emit("rebalance.begin", generation=pl.generation,
                   added=added, removed=removed, moves=len(plan),
                   **({"reason": reason} if reason else {}))
        return {"generation": pl.generation, "added": added,
                "removed": removed, "moves": len(plan)}

    def _resume(self, hosts):
        """Re-drive a COMMITTED-but-unfinished resize (coordinator
        restart): recompute the move plan from the placement's own
        generation pair and run the finish sequence — commit delivery,
        reconcile, cleanup, prune."""
        pl = self.placement
        plan = self._plan_moves(list(pl.prev_hosts()), list(hosts))
        with self._mu:
            self._last_error = None
            self._started_at = time.monotonic()
            self._finished_at = None
        self._thread = threading.Thread(target=self._run_resume,
                                        args=(plan,), daemon=True,
                                        name="rebalancer-resume")
        self._thread.start()
        self._emit("rebalance.resume", generation=pl.generation,
                   moves=len(plan))
        return {"generation": pl.generation, "resumed": True,
                "moves": len(plan)}

    def _run_resume(self, plan):
        try:
            self._finish_commit(plan)
        except Exception:  # noqa: BLE001 — report, never die silently
            logger.warning("rebalance resume crashed", exc_info=True)
            with self._mu:
                self._last_error = "rebalance resume crashed (see log)"
        finally:
            with self._mu:
                self._running = False
                self._finished_at = time.monotonic()

    def _ensure_nodes(self, hosts):
        """Every placement host must be dialable: merge unknown hosts
        into the live node list (scheme follows the cluster's)."""
        scheme = (self.cluster.nodes[0].scheme
                  if self.cluster.nodes else "http")
        added = False
        for h in hosts:
            if self.cluster.node_by_host(h) is None:
                self.cluster.nodes.append(Node(h, scheme=scheme))
                added = True
        if added:
            self.cluster.topology_version += 1

    # ------------------------------------------------------------ planning

    def _plan_moves(self, old_hosts, new_hosts):
        """[(index, src_host, dst_host, slice)] for every slice whose
        NEW owner set contains a host the OLD set did not. Sources
        prefer this node (no extra read RPC), then the first live old
        owner. Slices born during the stream need no move: they are
        dual-written from their first bit."""
        pl = self.placement
        moves = []
        ns = self.cluster.node_set
        for idx in self.holder.indexes_list():
            max_slice = idx.max_slice()
            for s in range(max_slice + 1):
                pid = self.cluster.partition(idx.name, s)
                old = pl._owners_for(tuple(old_hosts), pid,
                                     self.cluster.replica_n,
                                     self.cluster.hasher)
                new = pl._owners_for(tuple(new_hosts), pid,
                                     self.cluster.replica_n,
                                     self.cluster.hasher)
                dsts = [h for h in new if h not in old]
                if not dsts:
                    continue
                srcs = [h for h in old
                        if ns is None or not hasattr(ns, "is_down")
                        or not ns.is_down(h)]
                if not srcs:
                    srcs = list(old)
                src = (self.local_host if self.local_host in srcs
                       else srcs[0])
                for dst in dsts:
                    moves.append((idx.name, src, dst, s))
        return moves

    # ----------------------------------------------------------- streaming

    def _run(self, plan):
        """Background migration: stream every move, then commit +
        cleanup — or abort on any failure. Never raises (logs +
        /debug/rebalance carry the verdict)."""
        root = self.tracer.start("rebalance",
                                 generation=self.placement.generation,
                                 moves=len(plan))
        try:
            with root:
                ok = self._stream_all(plan, root)
                if ok and not self._closing.is_set():
                    self._commit_and_cleanup(plan)
                elif not ok:
                    self._abort()
        except Exception:  # noqa: BLE001 — the run thread must report,
            logger.warning("rebalance run crashed", exc_info=True)
            with self._mu:  # never die silently
                self._last_error = "rebalance run crashed (see log)"
            self._abort()
        finally:
            with self._mu:
                self._running = False
                self._finished_at = time.monotonic()

    def _stream_all(self, plan, parent_span):
        """Fan the move list over ``stream_concurrency`` workers.
        Returns True when every move verified."""
        tasks = list(plan)
        self._emit("rebalance.stream", moves=len(tasks))
        task_mu = threading.Lock()
        failed = []
        moved_slices = set()

        def worker():
            while True:
                with task_mu:
                    if not tasks or failed or self._closing.is_set():
                        return
                    index, src, dst, s = tasks.pop()
                try:
                    with tracing.child_of(parent_span, "rebalance.stream",
                                          index=index, slice=s,
                                          src=src, dst=dst):
                        self._stream_slice(index, src, dst, s)
                except Exception as exc:  # noqa: BLE001 — verdict below
                    logger.warning(
                        "rebalance stream %s slice %d %s→%s failed",
                        index, s, src, dst, exc_info=True)
                    with task_mu:
                        failed.append((index, s, dst, str(exc)))
                    with self._mu:
                        self.counters["stream_failures"] += 1
                    return
                with task_mu:
                    moved_slices.add((index, s))
                with self._mu:
                    self.counters["slices_moved"] = len(moved_slices)

        threads = [threading.Thread(target=worker, daemon=True,
                                    name=f"rebalance-stream-{i}")
                   for i in range(min(self.stream_concurrency,
                                      max(1, len(tasks))))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if failed:
            with self._mu:
                self._last_error = (
                    f"stream failed: {failed[0][0]} slice {failed[0][1]} "
                    f"→ {failed[0][2]}: {failed[0][3]}")
            return False
        if not self._closing.is_set():
            # Every move streamed AND digest-verified (the per-
            # fragment verify loop is part of _stream_fragment).
            self._emit("rebalance.verify", moved=len(moved_slices))
            return True
        return False

    def _stream_slice(self, index, src, dst, s):
        """Copy every fragment of one slice (all frames × views) from
        ``src`` to ``dst`` with digest verification."""
        dst_node = self.cluster.node_by_host(dst)
        src_node = self.cluster.node_by_host(src)
        if dst_node is None:
            raise RebalanceError(f"unknown destination {dst}")
        t0 = time.monotonic()
        n_frags = 0
        for frame_name, view_name in self._slice_views(index, src,
                                                       src_node):
            n_frags += self._stream_fragment(
                index, frame_name, view_name, s, src, src_node, dst_node)
        dt = time.monotonic() - t0
        with self._mu:
            pp = self._per_peer.setdefault(
                dst, {"fragments": 0, "bytes": 0, "seconds": 0.0})
            pp["fragments"] += n_frags
            pp["seconds"] += dt
        if self._hist.enabled:
            self._peer_hist(dst).observe(dt)

    def _slice_views(self, index, src, src_node):
        """(frame, view) pairs to consider for one slice. Local
        sources read the holder; remote sources are asked per frame
        (the schema itself converges via heartbeat, so frame names are
        known locally). Missing fragments skip at stream time (404)."""
        idx = self.holder.index(index)
        if idx is None:
            return []
        out = []
        for frame_name in sorted(idx.frames):
            frame = idx.frames[frame_name]
            if src == self.local_host or src_node is None:
                views = sorted(frame.views)
            else:
                try:
                    views = sorted(self.client.frame_views(
                        src_node, index, frame_name))
                except Exception:  # noqa: BLE001 — fall back to local; pilint: disable=swallow
                    views = sorted(frame.views)
            out.extend((frame_name, v) for v in views)
        return out

    # After a verified install, both copies receive every write (dual
    # writes) — a digest mismatch is almost always a half-landed write
    # (one leg applied, the other in flight) and SETTLES on its own.
    # Re-reading beats re-shipping: each settle read is two tiny RPCs.
    VERIFY_SETTLE_ATTEMPTS = 10
    VERIFY_SETTLE_WAIT = 0.15

    def _stream_fragment(self, index, frame, view, s, src, src_node,
                         dst_node):
        """One fragment: snapshot → (pace) → checksummed install →
        digest verify. Returns fragments shipped (0 when the source
        has no such fragment). No rebalancer/placement lock is held
        anywhere in here — asserted by the io_point.

        Install semantics: bit views UNION into the destination
        (merge=1) — a replacing restore would wipe dual writes applied
        to the new owner while the snapshot was in flight, the
        acked-write-loss race. The payload ships under a sha256
        checksum the receiver verifies BEFORE applying (merged garbage
        could never be re-shipped away); a rejected payload
        (rebalance.stream.corrupt) refetches clean. Digest mismatches
        after a verified install settle by re-reading (bit views) or
        re-shipping (BSI field views, which keep replace semantics —
        planes have no meaningful union)."""
        import hashlib

        from pilosa_tpu.cluster.client import ClientError

        last = None
        merge = not view.startswith("field_")
        for attempt in range(STREAM_VERIFY_RETRIES):
            if attempt:
                with self._mu:
                    self.counters["stream_retries"] += 1
            if faults.ACTIVE.enabled:
                faults.ACTIVE.fire("rebalance.stream.slow")
                faults.ACTIVE.fire("rebalance.stream.error")
            if lockcheck.ACTIVE.enabled:
                lockcheck.ACTIVE.io_point("rebalance.stream")
            data = self._fetch(index, frame, view, s, src, src_node)
            if data is None:
                return 0  # source has no such fragment — nothing moves
            checksum = hashlib.sha256(data).hexdigest()
            if (faults.ACTIVE.enabled
                    and faults.ACTIVE.fire("rebalance.stream.corrupt")):
                data = bytes(data[:1]) + bytes(
                    b ^ 0xFF for b in data[1:2]) + data[2:]
            self._pace(len(data))
            headers = dict(_STREAM_HEADERS)
            headers["X-Pilosa-Fragment-Checksum"] = checksum
            try:
                self.client.restore_fragment(
                    dst_node, index, frame, view, s, data,
                    extra_headers=headers, merge=merge)
            except ClientError as e:
                last = f"restore: {e}"
                continue
            with self._mu:
                self.counters["bytes_streamed"] += len(data)
                pp = self._per_peer.setdefault(
                    dst_node.host,
                    {"fragments": 0, "bytes": 0, "seconds": 0.0})
                pp["bytes"] += len(data)
            for settle in range(self.VERIFY_SETTLE_ATTEMPTS):
                if settle:
                    self._closing.wait(self.VERIFY_SETTLE_WAIT)
                src_digest = self._digest(index, frame, view, s, src,
                                          src_node)
                try:
                    dst_digest = self.client.fragment_digest(
                        dst_node, index, frame, view, s,
                        extra_headers=_STREAM_HEADERS)
                except ClientError as e:
                    last = f"verify: {e}"
                    break
                if src_digest == dst_digest:
                    with self._mu:
                        self.counters["fragments_moved"] += 1
                    return 1
                last = (f"digest mismatch after install "
                        f"({src_digest.hex()} != {dst_digest.hex()})")
                if not merge:
                    break  # replace semantics: re-ship a fresh snapshot
        raise RebalanceError(
            f"{index}/{frame}/{view} slice {s} → {dst_node.host}: "
            f"{last} after {STREAM_VERIFY_RETRIES} attempts")

    def _fetch(self, index, frame, view, s, src, src_node):
        """Backup tar bytes from the source, or None when the source
        holds no such fragment."""
        from pilosa_tpu.cluster.client import ClientError

        if src == self.local_host or src_node is None:
            frag = self.holder.fragment(index, frame, view, s)
            if frag is None:
                return None
            buf = io.BytesIO()
            frag.write_to(buf)
            return buf.getvalue()
        try:
            return self.client.backup_fragment(
                src_node, index, frame, view, s,
                extra_headers=_STREAM_HEADERS)
        except ClientError as e:
            if getattr(e, "status", None) == 404 \
                    or "fragment not found" in str(e):
                return None
            raise

    def _digest(self, index, frame, view, s, src, src_node):
        from pilosa_tpu.cluster.client import ClientError

        if src == self.local_host or src_node is None:
            frag = self.holder.fragment(index, frame, view, s)
            return frag.digest() if frag is not None else b"\x00" * 8
        try:
            return self.client.fragment_digest(
                src_node, index, frame, view, s,
                extra_headers=_STREAM_HEADERS)
        except ClientError as e:
            if getattr(e, "status", None) == 404 \
                    or "fragment not found" in str(e):
                return b"\x00" * 8
            raise

    def _pace(self, nbytes):
        """Bandwidth budget: transfers reserve their slot in a shared
        monotonic timeline (bytes / bandwidth seconds each) and sleep
        until it opens. 0 = unpaced."""
        if not self.bandwidth:
            return
        cost = nbytes / float(self.bandwidth)
        with self._mu:
            now = time.monotonic()
            start = max(now, self._bw_next)
            self._bw_next = start + cost
        delay = start - now
        if delay > 0:
            self._closing.wait(delay)

    # ------------------------------------------------------ commit/cleanup

    def _commit_and_cleanup(self, plan):
        pl = self.placement
        pl.commit()
        self.cluster.topology_version += 1
        with self._mu:
            self.counters["commits"] += 1
        self._emit("rebalance.commit", generation=pl.generation)
        self._finish_commit(plan)

    # After the rapid retry window, delivery/reconcile keep retrying
    # at this multiple of commit_retry_interval — a long partition
    # must never wedge the cluster in COMMITTED with nobody driving
    # cleanup (the self-heal loop; a coordinator RESTART instead uses
    # the resume path: POST /cluster/resize with the same hosts).
    SLOW_RETRY_MULTIPLE = 10

    def _finish_commit(self, plan):
        """The committed generation's finish work, run until done or
        the server closes. Commit must reach EVERY member before
        cleanup: a peer still in TRANSITION reads from old owners,
        which keep receiving dual writes until the old generation is
        dropped — mixed phases are safe, missing data is not.
        Unreachable peers retry here (rapid, then slow cadence) and
        converge via the heartbeat piggyback meanwhile."""
        pl = self.placement
        attempt = 0
        pending = self._member_peers()
        while pending and not self._closing.is_set():
            if pl.phase != placement_mod.PHASE_COMMITTED:
                return  # finished elsewhere (another coordinator/resume)
            failures = self._broadcast_state(
                pl.wire_state(), peers=pending,
                point="rebalance.commit.partial")
            pending = [n for n in pending
                       if n.host in {h for h, _ in failures}]
            if not pending:
                break
            attempt += 1
            slow = attempt >= self.commit_retries
            if attempt == self.commit_retries:
                with self._mu:
                    self._last_error = (
                        "commit delivery incomplete: "
                        f"{[n.host for n in pending]} — retrying in "
                        "background (dual writes remain in force; "
                        "heartbeat piggyback converges meanwhile)")
                logger.warning("rebalance commit incomplete: %s",
                               [n.host for n in pending])
            self._closing.wait(self.commit_retry_interval
                               * (self.SLOW_RETRY_MULTIPLE if slow
                                  else 1))
        if self._closing.is_set():
            return
        # Post-commit reconcile — the no-lost-acks closer. A dual
        # write whose two owner posts STRADDLE a stream's
        # restore+verify window can be wiped on the destination yet
        # verify clean (the source post had not landed when the source
        # digest was read). After commit every write lands on both
        # generations symmetrically, so divergence can only be
        # historical — one union merge over the moved fragments
        # repairs it, and only then is pruning the old copies safe.
        # Retried at the slow cadence: data stays safe (dual writes)
        # and the cluster must never wedge here.
        while not self._closing.is_set():
            if self._reconcile(plan):
                self._emit("rebalance.reconcile", moves=len(plan))
                break
            with self._mu:
                self._last_error = ("post-commit reconcile incomplete: "
                                    "retrying in background (dual "
                                    "writes remain in force — data is "
                                    "safe)")
            logger.warning("rebalance reconcile incomplete; retrying")
            self._closing.wait(self.commit_retry_interval
                               * self.SLOW_RETRY_MULTIPLE)
        if self._closing.is_set():
            return
        # Peer list BEFORE cleanup drops the old generation: LEAVING
        # nodes must hear the final state too (it releases their
        # handoff-drain wait and stops the dual writes aimed at them).
        peers = self._member_peers()
        state = pl.cleanup()
        self.cluster.topology_version += 1
        with self._mu:
            self.counters["cleanups"] += 1
            self._last_error = None
        self._emit("rebalance.cleanup", generation=pl.generation)
        self._broadcast_state(state, peers=peers)  # best-effort;
        self._apply_membership_trim()              # heartbeat converges
        self.prune_unowned()

    # ----------------------------------------------------------- reconcile

    # Non-standard views (inverse/time/field) reconcile by re-stream +
    # digest settle; bounded attempts before deferring cleanup.
    RECONCILE_ATTEMPTS = 4

    def _reconcile(self, plan):
        """Repair any stream/dual-write divergence on moved fragments
        before the old copies are pruned. Standard views union-merge
        through the anti-entropy block protocol (monotone — a missing
        acknowledged SET is re-applied as a real write, nothing is
        ever overwritten; raced clears resolve to set, the documented
        anti-entropy tie-break). Other views re-stream until digests
        settle. Returns True when every moved fragment reconciled."""
        ok = True
        for index, src, dst, s in plan:
            if self._closing.is_set():
                return False
            try:
                ok = self._reconcile_slice(index, src, dst, s) and ok
            except Exception:  # noqa: BLE001 — verdict drives cleanup
                logger.warning("reconcile of %s slice %d %s→%s failed",
                               index, s, src, dst, exc_info=True)
                ok = False
        return ok

    def _reconcile_slice(self, index, src, dst, s):
        src_node = self.cluster.node_by_host(src)
        dst_node = self.cluster.node_by_host(dst)
        if dst_node is None:
            return False
        all_ok = True
        for frame, view in self._slice_views(index, src, src_node):
            done = False
            for attempt in range(self.RECONCILE_ATTEMPTS):
                d_src = self._digest(index, frame, view, s, src,
                                     src_node)
                d_dst = self._digest(index, frame, view, s,
                                     dst_node.host, dst_node)
                if d_src == d_dst:
                    done = True
                    break
                with self._mu:
                    self.counters["reconciled_fragments"] += 1
                if view == "standard":
                    self._union_blocks(index, frame, s, src, src_node,
                                       dst_node)
                    done = True  # union is monotone: src ⊆ dst now for
                    break        # everything read; later writes are dual
                # Non-standard view: re-ship the whole fragment, then
                # let the loop's digest re-check settle.
                self._stream_fragment(index, frame, view, s, src,
                                      src_node, dst_node)
                self._closing.wait(0.1)
            all_ok = all_ok and done
        return all_ok

    def _blocks(self, index, frame, s, host, node):
        from pilosa_tpu.cluster.client import ClientError

        if host == self.local_host or node is None:
            frag = self.holder.fragment(index, frame, "standard", s)
            return dict(frag.blocks()) if frag is not None else {}
        try:
            return dict(self.client.fragment_blocks(
                node, index, frame, "standard", s))
        except ClientError as e:
            if getattr(e, "status", None) == 404 \
                    or "fragment not found" in str(e):
                return {}
            raise

    def _block_pairs(self, index, frame, s, block, host, node):
        from pilosa_tpu.cluster.client import ClientError

        if host == self.local_host or node is None:
            frag = self.holder.fragment(index, frame, "standard", s)
            if frag is None:
                return set()
            rows, cols = frag.block_data(block)
            return set(zip([int(r) for r in rows],
                           [int(c) for c in cols]))
        try:
            rows, cols = self.client.block_data(
                node, index, frame, "standard", s, block)
            return set(zip([int(r) for r in rows],
                           [int(c) for c in cols]))
        except ClientError as e:
            if getattr(e, "status", None) == 404 \
                    or "fragment not found" in str(e):
                return set()
            raise

    def _union_blocks(self, index, frame, s, src, src_node, dst_node):
        """Bidirectional union of standard-view bits over differing
        blocks, applied as real SetBit writes with Remote semantics
        (the receiving node fans them out to its inverse/time views,
        the same contract as anti-entropy block repair)."""
        from pilosa_tpu import SLICE_WIDTH

        src_blocks = self._blocks(index, frame, s, src, src_node)
        dst_blocks = self._blocks(index, frame, s, dst_node.host,
                                  dst_node)
        diff = [b for b in set(src_blocks) | set(dst_blocks)
                if src_blocks.get(b) != dst_blocks.get(b)]
        if not diff:
            return
        idx = self.holder.index(index)
        if idx is None:
            return
        fr = idx.frame(frame)
        row_label = fr.row_label if fr is not None else "rowID"
        col_label = idx.column_label
        sets_for_dst, sets_for_src = [], []
        for b in sorted(diff):
            sp = self._block_pairs(index, frame, s, b, src, src_node)
            dp = self._block_pairs(index, frame, s, b, dst_node.host,
                                   dst_node)
            sets_for_dst.extend(sorted(sp - dp))
            sets_for_src.extend(sorted(dp - sp))
        for node, pairs in ((dst_node, sets_for_dst),
                            (src_node, sets_for_src)):
            if not pairs or node is None:
                continue
            with self._mu:
                self.counters["reconciled_bits"] += len(pairs)
            calls = [
                f'SetBit(frame="{frame}", {row_label}={row}, '
                f'{col_label}={s * SLICE_WIDTH + col})'
                for row, col in pairs
            ]
            limit = self.cluster.max_writes_per_request or 5000
            for i in range(0, len(calls), limit):
                self.client.execute_query(
                    node, index, "\n".join(calls[i:i + limit]),
                    remote=True)

    def _abort(self):
        pl = self.placement
        if pl.phase != placement_mod.PHASE_TRANSITION:
            return
        # Peer list BEFORE abort drops the target generation: JOINING
        # nodes must hear the revert (they hold partial streams).
        peers = self._member_peers()
        state = pl.abort()
        self.cluster.topology_version += 1
        with self._mu:
            self.counters["aborts"] += 1
            reason = self._last_error
        self._emit("rebalance.abort", generation=pl.generation,
                   reason=reason)
        self._broadcast_state(state, peers=peers)  # best-effort;
        self.prune_unowned()  # drop partially streamed copies

    # ----------------------------------------------------------- messaging

    def _member_peers(self):
        hosts = self.placement.member_hosts() or tuple(
            n.host for n in self.cluster.nodes)
        return [n for h in hosts if h != self.local_host
                for n in (self.cluster.node_by_host(h),) if n is not None]

    def _broadcast_state(self, state, peers=None, point=None):
        """Send the full placement state to each peer; returns
        [(host, error)] for failed deliveries. ``point`` arms a
        chaos failpoint that drops individual deliveries
        (``rebalance.commit.partial``)."""
        failures = []
        msg = {"type": "placement-state", "state": state}
        for node in (self._member_peers() if peers is None else peers):
            if point is not None and faults.ACTIVE.enabled:
                try:
                    if faults.ACTIVE.fire(point):
                        failures.append((node.host, "injected drop"))
                        continue
                except OSError as e:
                    failures.append((node.host, str(e)))
                    continue
            try:
                self.client.send_message(node, msg)
            except Exception as e:  # noqa: BLE001 — collected verdict
                failures.append((node.host, str(e)))
        return failures

    def receive_state(self, state, strict=False):
        """Apply a peer's placement state. ``strict=True`` (the
        broadcast path, POST /cluster/message) turns silent
        non-application into a loud refusal the sending coordinator
        must act on: a STALE state (the sender's in-memory seq is
        behind this cluster's — a restarted coordinator) raises
        instead of 200-ing, so the sender aborts rather than streaming
        and committing against peers that ignored every phase change;
        a TRANSITION is refused while THIS node holds pending hinted
        writes (acked writes invisible to the migration's verify and
        reconcile — the sender aborts before any data moves). The
        heartbeat merge path stays lenient (``strict=False``): it is
        the convergence backstop for a resize already in force.

        Side effects on change: unknown hosts join the node list,
        routing memos rotate, and a cleanup prunes local fragments
        this node no longer owns."""
        if not isinstance(state, dict):
            if strict:
                raise RebalanceError("malformed placement state")
            return False
        verdict = self.placement.classify(state)
        if verdict == "malformed":
            if strict:
                raise RebalanceError("malformed placement state")
            return False
        if verdict == "stale":
            if strict:
                raise RebalanceError(
                    f"stale placement state (local generation "
                    f"{self.placement.generation} seq "
                    f"{self.placement.seq} is newer — converge via "
                    f"heartbeat before coordinating)")
            return False
        if verdict == "duplicate":
            return False
        if (strict and state.get("phase") == placement_mod.PHASE_TRANSITION
                and self.pending_hints_fn is not None):
            pending = self.pending_hints_fn()
            if pending:
                # The coordinator's own pre-flight only sees ITS hint
                # queues; every receiver vetoes for its own — so a
                # resize cannot begin anywhere while ANY node holds an
                # acked-but-undelivered hinted write whose replay
                # targets pre-resize owners.
                raise RebalanceError(
                    f"hinted writes pending on this node for "
                    f"{pending}: refusing transition")
        hosts = list(state.get("hosts") or ()) + list(
            state.get("prevHosts") or ())
        pl = self.placement
        before_phase = pl.phase if pl.active else None
        if not pl.active:
            # Pin the legacy routing BEFORE merging unknown hosts into
            # the live list — same instant-reassignment window as the
            # coordinator's begin (see _begin).
            pl.pin([n.host for n in self.cluster.nodes])
        # Nodes BEFORE state: once the new placement applies, every
        # host it names must already be dialable/mappable (a placement
        # host with no Node entry would be skipped by routing).
        self._ensure_nodes(hosts)
        changed = pl.apply_state(state)
        if not changed:
            return False
        self.cluster.topology_version += 1
        if pl.phase == placement_mod.PHASE_STABLE \
                and before_phase != placement_mod.PHASE_STABLE:
            # A cleanup (or abort) landed: drop fragments this node no
            # longer owns — in the background, off the message-serving
            # thread (prune walks the holder and deletes files).
            self._apply_membership_trim()
            threading.Thread(target=self._prune_quietly,
                             daemon=True,
                             name="rebalance-prune").start()
        return True

    def merge_placement(self, st):
        """Heartbeat-piggyback entry (server._merge_peer_status): the
        convergence backstop for peers that missed a broadcast."""
        state = st.get("placement")
        if isinstance(state, dict):
            self.receive_state(state)

    def _apply_membership_trim(self):
        """After a resize settles (stable phase), drop nodes outside
        the new generation from the live node list so membership stops
        probing and broadcasting to them. This node's own entry stays
        (a LEAVING node keeps proxying until the operator stops it)."""
        pl = self.placement
        if not pl.active or pl.phase != placement_mod.PHASE_STABLE:
            return
        keep = set(pl.current_hosts()) | {self.local_host}
        dropped = [n for n in self.cluster.nodes if n.host not in keep]
        if dropped:
            self.cluster.nodes[:] = [n for n in self.cluster.nodes
                                     if n.host in keep]
            self.cluster.topology_version += 1
            for n in dropped:
                self._emit("membership.leave", peer=n.host)

    # -------------------------------------------------------------- prune

    def _prune_quietly(self):
        try:
            self.prune_unowned()
        except Exception:  # noqa: BLE001 — disk-space hygiene only,
            logger.warning("post-rebalance prune failed",  # never fatal
                           exc_info=True)

    def prune_unowned(self):
        """Remove local fragments whose slice this host no longer owns
        under the CURRENT routing (stable: new generation; after an
        abort: the old one). Safe at any time — a fragment still owned
        is never touched, and anti-entropy re-fills anything a racing
        resize re-assigns back."""
        def keep(index, slice_num):
            return any(n.host == self.local_host
                       for n in self.cluster.fragment_nodes(
                           index, slice_num))

        removed = self.holder.prune_fragments(keep)
        if removed:
            with self._mu:
                self.counters["prunes"] += 1
                self.counters["pruned_fragments"] += removed
            self.stats.count("rebalance_pruned_fragments", removed)
        return removed

    # ------------------------------------------------------- waits / intro

    def wait_handoff(self, timeout):
        """Drain integration: a LEAVING node blocks its shutdown until
        the resize that removes it settles (commit + cleanup — its
        data has verified copies elsewhere) or ``timeout`` passes.
        Returns True when handoff completed."""
        deadline = time.monotonic() + max(0.0, float(timeout))
        pl = self.placement
        while time.monotonic() < deadline:
            if not pl.active or pl.phase == placement_mod.PHASE_STABLE:
                return True
            if pl.role(self.local_host) != placement_mod.ROLE_LEAVING:
                return True
            if self._closing.wait(0.05):
                return False
        return (not pl.active
                or pl.phase == placement_mod.PHASE_STABLE)

    def is_running(self):
        with self._mu:
            return self._running

    def snapshot(self):
        """Rich JSON for GET /debug/rebalance."""
        with self._mu:
            counters = dict(self.counters)
            per_peer = {h: dict(v) for h, v in self._per_peer.items()}
            running = self._running
            last_error = self._last_error
            started = self._started_at
            finished = self._finished_at
        now = time.monotonic()
        out = {
            "running": running,
            "counters": counters,
            "slicesPending": max(
                0, counters["slices_total"] - counters["slices_moved"]),
            "perPeer": per_peer,
            "lastError": last_error,
            "placement": self.placement.snapshot(),
            "localRole": self.placement.role(self.local_host),
            "streamConcurrency": self.stream_concurrency,
            "bandwidthBytesPerSec": self.bandwidth,
        }
        if started is not None:
            out["elapsedSeconds"] = round(
                (finished if finished is not None else now) - started, 3)
        return out

    def metrics(self):
        """Flat dict for the /metrics ``pilosa_rebalance_*`` group."""
        with self._mu:
            c = self.counters
            out = {
                "slices_moved_total": c["slices_moved"],
                "slices_pending": max(
                    0, c["slices_total"] - c["slices_moved"]),
                "fragments_moved_total": c["fragments_moved"],
                "bytes_streamed_total": c["bytes_streamed"],
                "stream_retries_total": c["stream_retries"],
                "stream_failures_total": c["stream_failures"],
                "commits_total": c["commits"],
                "aborts_total": c["aborts"],
                "pruned_fragments_total": c["pruned_fragments"],
                "reconciled_fragments_total": c["reconciled_fragments"],
                "reconciled_bits_total": c["reconciled_bits"],
                "active": 1 if self._running else 0,
            }
            for host, pp in self._per_peer.items():
                out[f"peer_stream_seconds;peer:{host}"] = round(
                    pp["seconds"], 6)
                out[f"peer_bytes_streamed;peer:{host}"] = pp["bytes"]
        out["generation"] = self.placement.generation
        return out
