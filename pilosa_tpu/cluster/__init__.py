"""Cluster topology, placement, and inter-node communication."""
from pilosa_tpu.cluster.cluster import (  # noqa: F401
    Cluster,
    ConstHasher,
    JmpHasher,
    ModHasher,
    Node,
)
