"""Cluster topology + deterministic slice placement (ref: cluster.go).

Placement is two-level, exactly as the reference: slice → partition via
fnv64a(index || bigendian(slice)) % 256, partition → node via jump
consistent hash, replicas = successor nodes around the ring
(cluster.go:224-307). Host-level ownership uses this; *within* a host's
TPU mesh, slices are packed contiguously over devices by the parallel
layer (see parallel/mesh.py) so collectives ride ICI.

Test hashers (ModHasher/ConstHasher) mirror test/cluster.go:24-55.
"""
DEFAULT_PARTITION_N = 256   # ref: cluster.go:32-38
DEFAULT_REPLICA_N = 1

NODE_STATE_UP = "UP"
NODE_STATE_DOWN = "DOWN"


def fnv64a(data: bytes) -> int:
    h = 0xCBF29CE484222325
    for b in data:
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


class JmpHasher:
    """Jump consistent hash (ref: cluster.go:288-307)."""

    def hash(self, key, n):
        b, j = -1, 0
        key &= 0xFFFFFFFFFFFFFFFF
        while j < n:
            b = j
            key = (key * 2862933555777941757 + 1) & 0xFFFFFFFFFFFFFFFF
            j = int(float(b + 1) * (float(1 << 31) / float((key >> 33) + 1)))
        return b


class ModHasher:
    """key % n — deterministic test placement (ref: test/cluster.go)."""

    def hash(self, key, n):
        return key % n


class ConstHasher:
    def __init__(self, i=0):
        self.i = i

    def hash(self, key, n):
        return self.i


class Node:
    """(ref: cluster.go:46-86)."""

    def __init__(self, host, scheme="http"):
        self.host = host
        self.scheme = scheme
        self.internal_state = None

    def uri(self):
        return f"{self.scheme}://{self.host}"

    def __repr__(self):
        return f"Node({self.host})"

    def __eq__(self, other):
        return isinstance(other, Node) and self.host == other.host

    def __hash__(self):
        return hash(self.host)


class Cluster:
    def __init__(self, nodes=None, hasher=None,
                 partition_n=DEFAULT_PARTITION_N, replica_n=DEFAULT_REPLICA_N,
                 long_query_time=None, max_writes_per_request=5000):
        self.nodes = nodes or []
        self.hasher = hasher or JmpHasher()
        self.partition_n = partition_n
        self.replica_n = replica_n
        self.long_query_time = long_query_time
        self.max_writes_per_request = max_writes_per_request
        self.node_set = None  # membership provider (gossip analog)
        # Per-peer circuit breakers (qos.PeerBreakers) when QoS is
        # enabled — shared with the internal client so routing
        # (healthy_nodes) and transport (client._do) agree on which
        # peers are currently dead. None (default) = no breaker tier.
        self.breakers = None
        # Ownership-cache epoch: ANY topology mutation (node joined,
        # node.host rewritten after a ':0' bind) must bump this —
        # fragment_nodes memoizes per (index, slice) against it. A
        # len(nodes) change invalidates even without a bump (belt and
        # braces for future join paths).
        self.topology_version = 0
        # Versioned slice placement (cluster/placement.py): inactive
        # until the first resize touches this cluster — until then
        # every routing decision is the legacy live-node-list jump
        # hash, byte-identical to pre-placement behavior. Once active,
        # ownership is pinned to the committed placement generation
        # and membership churn stops reassigning slices.
        from pilosa_tpu.cluster.placement import PlacementMap

        self.placement = PlacementMap(
            hosts=[n.host for n in self.nodes])
        import threading as _threading

        from pilosa_tpu import lockcheck as _lockcheck

        self._frag_cache = {}
        self._frag_cache_state = None
        self._frag_cache_mu = _lockcheck.register(
            "cluster.Cluster._frag_cache_mu", _threading.Lock())

    def node_by_host(self, host):
        for n in self.nodes:
            if n.host == host:
                return n
        return None

    def partition(self, index, slice_num):
        """(ref: cluster.go:224-238)."""
        buf = index.encode() + slice_num.to_bytes(8, "big")
        return fnv64a(buf) % self.partition_n

    def partition_nodes(self, partition_id):
        """Primary + ReplicaN-1 successors (ref: cluster.go:250-271)."""
        if not self.nodes:
            return []
        replica_n = min(self.replica_n, len(self.nodes)) or 1
        start = self.hasher.hash(partition_id, len(self.nodes))
        return [self.nodes[(start + i) % len(self.nodes)]
                for i in range(replica_n)]

    def topology_state(self):
        """The tuple every ownership memo keys on: mutating ANY
        component rotates every owner-set / slice-plan / fragment-node
        cache lazily. Placement phase changes (begin/commit/cleanup/
        abort of a resize) ride ``placement.version``."""
        pl = self.placement
        return (self.topology_version, len(self.nodes), self.replica_n,
                pl.version if pl.active else 0)

    def fragment_nodes(self, index, slice_num):
        """Memoized slice→replica-set lookup. The fnv64a + jump-hash
        math is pure but costs ~9 µs; the executor's per-query
        _slices_by_node asks for EVERY slice of the index, which at
        954 slices was ~2 ms/query and at 10B-column scale ~9 ms —
        dominating cluster serving (profiled round 5). Returns a
        TUPLE: cached values must be un-mutatable by callers.

        With an ACTIVE placement (a resize has touched this cluster)
        ownership comes from the pinned generation — mid-resize that
        is the ordered UNION of both generations (old first while
        streaming, new first once committed): readers take the first
        live entry, writers iterate the whole tuple, which is exactly
        the dual-write / union-read transition contract."""
        state = self.topology_state()
        key = (index, slice_num)
        with self._frag_cache_mu:
            if state != self._frag_cache_state:
                self._frag_cache = {}
                self._frag_cache_state = state
            hit = self._frag_cache.get(key)
        if hit is None:
            hit = self._fragment_nodes_uncached(index, slice_num)
            with self._frag_cache_mu:
                # Store only if the topology didn't move under the
                # computation — a stale replica set written into a
                # fresh-epoch cache would misroute until the NEXT
                # topology change.
                if state == self._frag_cache_state:
                    self._frag_cache[key] = hit
        return hit

    def _fragment_nodes_uncached(self, index, slice_num):
        pl = self.placement
        if pl.active:
            out = []
            for h in pl.owner_hosts(self.partition(index, slice_num),
                                    self.replica_n, self.hasher):
                n = self.node_by_host(h)
                if n is not None:
                    out.append(n)
            if out:
                return tuple(out)
            # Placement names only unknown hosts (state arrived before
            # its node merge) — fall through to the live-list hash
            # rather than returning an unroutable empty set.
        return tuple(self.partition_nodes(
            self.partition(index, slice_num)))

    def read_owner_candidates(self, index, slice_num):
        """The replica subset a READ of this slice may be served from
        (the routing/hedging candidate pool). Writes fan synchronously
        to the full ``fragment_nodes`` set, so in steady state any
        owner holds the slice's current data and the whole tuple
        qualifies. Mid-resize (active placement, phase != stable) the
        tuple is the dual-generation UNION and only the FIRST entry is
        guaranteed complete — candidates collapse to the preferred
        owner, exactly the legacy read contract. LEAVING hosts are
        filtered when an alternative exists: they are draining and the
        next commit removes them, so new read traffic should not pin
        them hot."""
        owners = self.fragment_nodes(index, slice_num)
        if len(owners) <= 1:
            return owners
        pl = self.placement
        if pl.active:
            from pilosa_tpu.cluster.placement import PHASE_STABLE

            if pl.phase != PHASE_STABLE:
                return owners[:1]
            kept = tuple(n for n in owners if not pl.is_leaving(n.host))
            if kept:
                return kept
        return owners

    def owns_fragment(self, host, index, slice_num):
        return any(n.host == host for n in self.fragment_nodes(index, slice_num))

    def owns_slices(self, index, max_slice, host):
        """Primary-owned slices (ref: cluster.go:274-287) — under the
        active placement generation when one exists."""
        out = []
        for s in range(max_slice + 1):
            owners = self.fragment_nodes(index, s)
            if owners and owners[0].host == host:
                out.append(s)
        return out

    def healthy_nodes(self, nodes=None, keep_host=None):
        """``nodes`` minus peers whose circuit breaker is currently
        open. ``keep_host`` (this node) is never filtered — local
        execution doesn't ride the internal client, so a breaker entry
        for our own host (a worker probing the public port, say) must
        not blackhole local slices. Identity when no breaker tier is
        configured or nothing is open."""
        nodes = self.nodes if nodes is None else nodes
        brk = self.breakers
        if brk is None:
            return nodes
        open_hosts = brk.open_hosts()
        if not open_hosts:
            return nodes
        return [n for n in nodes
                if n.host == keep_host or n.host not in open_hosts]

    def node_states(self):
        """UP/DOWN per host from membership (ref: cluster.go:180-200)."""
        states = {n.host: NODE_STATE_DOWN for n in self.nodes}
        members = (self.node_set.nodes() if self.node_set else self.nodes)
        for m in members:
            if m.host in states:
                states[m.host] = NODE_STATE_UP
        return states

    def status(self):
        out = {"nodes": [{"host": n.host, "scheme": n.scheme}
                         for n in self.nodes]}
        if self.placement.active:
            # Elastic topology: the committed generation plus per-node
            # JOINING/LEAVING roles while a resize is in flight.
            pl = self.placement.snapshot()
            out["placement"] = {"generation": pl["generation"],
                                "phase": pl["phase"],
                                "roles": pl["roles"]}
        if self.breakers is not None:
            # Peers the breaker tier currently refuses to dial — the
            # QoS analog of the membership DOWN list, surfaced beside
            # it so /status explains why traffic is skipping a node.
            out["breakerOpen"] = sorted(self.breakers.open_hosts())
        return out


def new_test_cluster(n):
    """Fake topology with deterministic placement (ref: test/cluster.go)."""
    return Cluster(nodes=[Node(f"host{i}") for i in range(n)],
                   hasher=ModHasher())
