"""Diagnostics reporting (ref: diagnostics/diagnostics.go:48-256,
server.go:586-630 monitorDiagnostics).

The reference phones home hourly by default; here reporting is **opt-in**
and the default sink is a local JSONL file — same payload shape
(host/cluster/schema properties), no surprise egress.
"""
import json
import platform
import threading
import time

from pilosa_tpu import __version__
from pilosa_tpu import lockcheck

DEFAULT_INTERVAL = 3600  # hourly (ref: server.go:598)


class Diagnostics:
    def __init__(self, server=None, sink_path=None, interval=DEFAULT_INTERVAL):
        self.server = server
        self.sink_path = sink_path
        self.interval = interval
        self._props = {}
        self._mu = lockcheck.register("diagnostics.Diagnostics._mu",
                                      threading.Lock())
        self._closing = threading.Event()

    def set(self, key, value):
        """(ref: Diagnostics.Set)."""
        with self._mu:
            self._props[key] = value

    def enrich_with_os_info(self):
        """(ref: EnrichWithOSInfo)."""
        self.set("OS", platform.system())
        self.set("Arch", platform.machine())
        self.set("PythonVersion", platform.python_version())

    def enrich_with_schema_properties(self):
        """(ref: server.go:735-764 enrichDiagnosticsWithSchemaProperties)."""
        if self.server is None:
            return
        num_frames = num_slices = 0
        bsi = time_q = 0
        holder = self.server.holder
        for idx in holder.indexes_list():
            num_slices += idx.max_slice() + 1
            for frame in idx.frames.values():
                num_frames += 1
                if frame.fields:
                    bsi += 1
                if frame.time_quantum:
                    time_q += 1
        self.set("NumIndexes", len(holder.indexes))
        self.set("NumFrames", num_frames)
        self.set("NumSlices", num_slices)
        self.set("BSIFieldEnabled", bsi > 0)
        self.set("TimeQuantumEnabled", time_q > 0)

    def enrich_with_perf_summary(self):
        """Compact tracing/stat summary so the hourly JSONL report is
        usable for post-hoc performance triage: slow-query count (from
        the expvar snapshot the /metrics endpoint serves) plus
        p50/p99 query latency from the tracer's recent-latency window
        when tracing is enabled."""
        if self.server is None:
            return
        stats = getattr(self.server, "stats", None)
        snapshot = getattr(stats, "snapshot", None)
        if snapshot is not None:
            snap = snapshot()
            self.set("SlowQueries", snap.get("slow_queries_total", 0))
            self.set("QueriesTraced",
                     snap.get("query_latency_seconds_count", 0))
        tracer = getattr(self.server, "tracer", None)
        if tracer is not None and getattr(tracer, "enabled", False):
            s = tracer.summary()
            self.set("TracingSummary", s)
            if "p50Ms" in s:
                self.set("QueryLatencyP50Ms", s["p50Ms"])
                self.set("QueryLatencyP99Ms", s["p99Ms"])

    def enrich_with_process_telemetry(self):
        """Process + memory gauges (stats.process_telemetry and the
        holder's memory rollup) so the hourly JSONL answers capacity
        questions — RSS, fds, uptime, resident fragment bytes —
        without having scraped /metrics at the right moment."""
        from pilosa_tpu import stats as stats_mod

        t = stats_mod.process_telemetry()
        for key, prop in (("rss_bytes", "ProcessRSSBytes"),
                          ("threads", "ProcessThreads"),
                          ("open_fds", "ProcessOpenFds"),
                          ("uptime_seconds", "ProcessUptimeSeconds")):
            if key in t:
                self.set(prop, t[key])
        if self.server is not None:
            try:
                totals = self.server.holder.memory_stats()["totals"]
            except Exception:  # noqa: BLE001 — best-effort enrichment
                return
            self.set("MemoryFragmentBytes", totals["hostBytes"])
            self.set("MemoryDeviceBytes", totals["deviceBytes"])
            self.set("MemoryDiskBytes", totals["diskBytes"])
            self.set("ResidentFragments", totals["residentFragments"])

    def enrich_with_flight_recorder(self):
        """Per-peer latency block (observe/replica.py vitals) and a
        last-N-events digest (observe/events.py journal) so one JSONL
        record answers "was a peer slow, and what was the cluster
        doing" without a live /debug scrape. Best-effort: absent or
        disabled subsystems leave the properties unset."""
        if self.server is None:
            return
        vitals = getattr(getattr(self.server, "client", None),
                         "vitals", None)
        if vitals is not None and getattr(vitals, "enabled", False):
            peers = {}
            for peer, st in vitals.snapshot().get("peers", {}).items():
                peers[peer] = {
                    "p50Ms": round(st["p50"] * 1000, 3),
                    "p99Ms": round(st["p99"] * 1000, 3),
                    "errorRate": st["errorRate"],
                    "degraded": st["degraded"],
                    "healthScore": st["healthScore"],
                }
            if peers:
                self.set("ReplicaLatency", peers)
        events = getattr(self.server, "events", None)
        if events is not None and getattr(events, "enabled", False):
            recent = events.recent(limit=16)
            self.set("ControlEvents", [
                {"kind": e["kind"], "ts": e["ts"], "id": e["id"]}
                for e in recent])
            self.set("ControlEventCounts",
                     events.snapshot().get("counts", {}))

    def enrich_with_profiler(self):
        """Continuous-profiler digest (observe/profiler.py): top-10
        folded stacks and per-subsystem wall-clock shares, so the
        hourly JSONL record answers "where was this process spending
        its time" without a live /debug/profile scrape. Unset when the
        profiler is disabled."""
        from pilosa_tpu.observe import profiler as profiler_mod

        prof = profiler_mod.ACTIVE
        if prof.enabled:
            self.set("ProfileDigest", prof.digest(k=10))

    def payload(self):
        with self._mu:
            out = dict(self._props)
        out["Version"] = __version__
        out["Time"] = time.time()
        if self.server is not None:
            out["NumNodes"] = len(self.server.cluster.nodes)
        return out

    def flush(self):
        """Write one report to the sink (ref: Diagnostics.Flush)."""
        self.enrich_with_os_info()
        self.enrich_with_schema_properties()
        self.enrich_with_perf_summary()
        self.enrich_with_process_telemetry()
        self.enrich_with_flight_recorder()
        self.enrich_with_profiler()
        if not self.sink_path:
            return None
        record = self.payload()
        with open(self.sink_path, "a") as f:
            f.write(json.dumps(record) + "\n")
        return record

    def open(self):
        if not self.sink_path:
            return self  # disabled
        t = threading.Thread(target=self._loop, daemon=True)
        t.start()
        return self

    def close(self):
        self._closing.set()

    def _loop(self):
        while not self._closing.wait(self.interval):
            try:
                self.flush()
            except OSError:
                pass
