"""Epoch-validated slice-plan cache — walk-free large-index serving.

At 10B columns an index spans ~9,540 slices, and before this tier
every query re-derived the same per-(index, slice-range) facts on a
pure-Python walk before any device work ran: the slice universe
(``idx.max_slice()`` iterates every view of every frame), the
fragment window layout and device/host residency (``_leaf_frags`` +
``_union_window``), the batched-dispatch plan (``_batched_plan``),
and the owner-host sets — plus O(slices) ``tuple(slices)`` memo keys
whose hashing alone cost ~0.5 ms/query at that scale. The roaring
line (arXiv:1402.6407) wins by computing per-container structural
metadata ONCE and reusing it; this module is the equivalent for the
executor's per-(index, slice-range) plan.

One cache, one validity protocol:

- **Keys** are ``(kind, index, slice-key, ...call shape)`` tuples.
  Kinds are caller-defined and need no registration here — the
  executor's memos ("plan", "row", "bsi", "topn1", ...)
  and the adaptive planner's ``("planner", index, ast, slice-key)``
  decision memos (planner.py) share one LRU and show up separately
  in the snapshot's ``entriesByKind``. The slice-key is COMPACT: a verified-contiguous slice list keys as
  ``("#range", first, last)`` (O(1) to hash) instead of a 9,540-int
  tuple; only genuinely ragged lists (failover remap subsets) fall
  back to the exact tuple. ``SliceList`` carries the key it was built
  with so the hot path never re-derives it.
- **Validity** is a per-entry token the CALLER computes, in the same
  shapes the executor's memos already use: the scoped process-local
  mutation epoch (``storage/fragment.py``) for entries derived from
  local fragment state, the cluster topology state for owner sets,
  and PR 5's distributed epoch-vector tokens (``cluster/epochs.py``)
  where an entry covers remote data. A ``None`` token means
  unverifiable — the cache computes without storing: cold, never
  stale (the PR 5 contract). Any write on any node reaches this node
  as a local mutation (client write, relayed write, anti-entropy
  merge, hinted replay) and bumps the scoped epoch; fragment
  fail-stop and ``.corrupt`` quarantine bump it too (storage layer),
  so exactly the affected index's entries drop.
- **Real LRU**, configurable capacity (``[executor]
  plan-cache-entries`` / ``PILOSA_PLAN_CACHE_ENTRIES``; 0 = off —
  every lookup misses and nothing is stored), hit/miss/invalidation
  counters per index, exposed on ``/metrics``
  (``pilosa_plan_cache_*``) and ``GET /debug/plans``.

This subsumes the executor's former ad-hoc tiers: the FIFO 64-entry
``_owner_hosts_cache``, the FIFO ``_prelude_cache``, and the
per-query ``max_slice()`` walk (the slice-universe memo below).
"""
import os
import threading
from collections import OrderedDict

import numpy as np

from pilosa_tpu.storage import fragment as _frag
from pilosa_tpu import lockcheck

# Default entry budget: preludes/owner sets/plans are a few hundred
# host bytes each (stacks live in the byte-budgeted stack cache, NOT
# here), so a few hundred entries cover every realistic dashboard mix
# while bounding shape-churning clients.
DEFAULT_ENTRIES = 512

# Marker for compact contiguous slice keys. A real slices tuple holds
# only ints, so no exact-tuple fallback key can ever collide with
# ("#range", first, last).
RANGE_MARK = "#range"


class SliceList(list):
    """A slice list that remembers its compact cache key, so hot
    paths pay one attribute read instead of an O(n) re-derivation.
    Treated as IMMUTABLE by convention: the executor shares one
    instance across concurrent queries (every consumer copies before
    mutating, as ``_map_reduce`` always has)."""

    __slots__ = ("skey",)


def slice_key(slices):
    """Compact, exact cache key for a slice list: the precomputed key
    for a ``SliceList``; ``("#range", first, last)`` for a verified
    contiguous run; the exact tuple otherwise. The contiguity check is
    exact (numpy element compare in C) — span/length alone is NOT
    sufficient (e.g. [0, 2, 2] spans like [0, 1, 2])."""
    k = getattr(slices, "skey", None)
    if k is not None:
        return k
    n = len(slices)
    if n > 32 and slices[0] + n - 1 == slices[-1]:
        arr = np.asarray(slices)
        if bool(np.array_equal(arr, np.arange(arr[0], arr[-1] + 1))):
            return (RANGE_MARK, int(slices[0]), int(slices[-1]))
    return tuple(slices)


def as_slice_list(slices):
    """Wrap a plain list as a SliceList with its key computed once.
    The key is derived from the materialized copy, so one-shot
    iterables are safe."""
    out = SliceList(slices)
    out.skey = slice_key(out)
    return out


class PlanCache:
    """LRU of epoch-validated slice-plan entries + the per-index
    slice-universe memo. Thread-safe; every operation is a few dict
    moves under one short lock (token COMPUTATION stays with the
    caller — a cluster vector validation may probe a peer and must
    never run under this lock)."""

    def __init__(self, capacity=None):
        if capacity is None:
            env = os.environ.get("PILOSA_PLAN_CACHE_ENTRIES")
            if env:
                try:
                    capacity = max(0, int(env))
                except ValueError:
                    capacity = DEFAULT_ENTRIES
            else:
                capacity = DEFAULT_ENTRIES
        self.capacity = int(capacity)
        self._mu = lockcheck.register("plancache.PlanCache._mu",
                                      threading.Lock())
        self._entries = OrderedDict()   # key -> (token, value)
        self._universe = {}             # index -> (token, std, inv)
        self.hits = 0
        self.misses = 0
        self.invalidations = 0
        self._by_index = {}             # index -> [hits, misses]

    def set_capacity(self, capacity):
        """Resize (config path); shrinking evicts LRU-first, 0 wipes
        and disables."""
        with self._mu:
            self.capacity = max(0, int(capacity))
            if self.capacity == 0:
                self._entries.clear()
                self._universe.clear()
                return
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)

    # ------------------------------------------------------------ entries

    def _note(self, index, hit):
        """Per-index hit/miss tally. Caller holds self._mu."""
        st = self._by_index.get(index)
        if st is None:
            st = self._by_index[index] = [0, 0]
        st[0 if hit else 1] += 1

    def get(self, key, token, record=True):
        """Value for ``key`` when its stored token equals ``token``
        (LRU-refreshing); None on miss or staleness. A stale entry is
        dropped eagerly — epochs are monotone, it can never validate
        again — and counts as an invalidation. ``token=None`` (caller
        could not verify) is always a miss and never drops: the entry
        may validate once visibility returns. ``record=False`` skips
        the hit/miss counters (invalidations still count) — for
        callers whose lookup only succeeds after a second resolution
        step (prelude memos resolving device stacks), who call
        ``record()`` with the true outcome instead."""
        index = key[1]
        with self._mu:
            ent = self._entries.get(key)
            if ent is None:
                if record:
                    self.misses += 1
                    self._note(index, False)
                return None
            if token is None or ent[0] != token:
                if token is not None:
                    del self._entries[key]
                    self.invalidations += 1
                if record:
                    self.misses += 1
                    self._note(index, False)
                return None
            self._entries.move_to_end(key)
            if record:
                self.hits += 1
                self._note(index, True)
            return ent[1]

    def peek(self, key, token):
        """Pure read: the value for ``key`` when its stored token
        equals ``token``, else None — NO LRU refresh, NO hit/miss
        accounting, NO stale-entry drop. The explain-only surface
        (observe/explain.py) reports plan-cache state through this so
        planning a query without executing it provably mutates
        nothing."""
        with self._mu:
            ent = self._entries.get(key)
            if ent is None or token is None or ent[0] != token:
                return None
            return ent[1]

    def record(self, index, hit):
        """Count a deferred lookup outcome (see ``get(record=False)``)."""
        with self._mu:
            if hit:
                self.hits += 1
            else:
                self.misses += 1
            self._note(index, hit)

    def put(self, key, token, value):
        """Store (no-op when disabled or the token is unverifiable —
        cold, never stale)."""
        if token is None or self.capacity == 0:
            return
        with self._mu:
            # Re-check under the lock: a concurrent set_capacity(0)
            # (live reconfiguration) must not revive entries — and the
            # eviction loop would popitem() an emptied dict (0 >= 0).
            if self.capacity == 0:
                return
            self._entries.pop(key, None)
            while len(self._entries) >= self.capacity and self._entries:
                self._entries.popitem(last=False)
            self._entries[key] = (token, value)

    def entries_view(self, kinds=None):
        """Snapshot mapping of entry key -> stored value (optionally
        filtered by kind = key[0]) — introspection and tests."""
        with self._mu:
            return {k: v[1] for k, v in self._entries.items()
                    if kinds is None or k[0] in kinds}

    # ----------------------------------------------------------- universe

    @staticmethod
    def _fresh_universe(idx):
        """Build the (std, inv) shared SliceLists from a max_slice()
        walk — ONE constructor for both the memoizing and the
        read-only paths, so their universes can never drift."""
        std = SliceList(range(idx.max_slice() + 1))
        std.skey = (RANGE_MARK, 0, len(std) - 1)
        inv = SliceList(range(idx.max_inverse_slice() + 1))
        inv.skey = (RANGE_MARK, 0, len(inv) - 1)
        return std, inv

    def slice_universe(self, index, idx):
        """The index's full (standard, inverse) slice lists as shared
        ``SliceList``s, memoized against the scoped mutation epoch
        plus the peer-reported max slices (``set_remote_max_slice``
        moves without an epoch bump — heartbeats widen the range).
        This replaces the per-query ``max_slice()`` walk over every
        view of every frame (~0.24 ms at 9,540 slices)."""
        token = (_frag.mutation_epoch(index), idx.remote_max_slice,
                 idx.remote_max_inverse_slice)
        if self.capacity != 0:
            with self._mu:
                ent = self._universe.get(index)
                if ent is not None and ent[0] == token:
                    self.hits += 1
                    self._note(index, True)
                    return ent[1], ent[2]
                self.misses += 1
                self._note(index, False)
        std, inv = self._fresh_universe(idx)
        if self.capacity != 0:
            # Token captured BEFORE the max_slice walk: a write landing
            # mid-walk makes the memo stale-on-arrival, never wrong.
            # Capacity re-checked under the lock so a concurrent
            # set_capacity(0) can't be re-populated behind its back.
            with self._mu:
                if self.capacity != 0:
                    self._universe[index] = (token, std, inv)
        return std, inv

    def universe_peek(self, index, idx):
        """(std, inv, memo-hit?) — the read-only twin of
        ``slice_universe``: a memo hit returns the shared lists; a
        miss computes fresh ones WITHOUT storing (and without
        hit/miss accounting). The explain-only surface."""
        token = (_frag.mutation_epoch(index), idx.remote_max_slice,
                 idx.remote_max_inverse_slice)
        with self._mu:
            ent = self._universe.get(index)
            if ent is not None and ent[0] == token:
                return ent[1], ent[2], True
        std, inv = self._fresh_universe(idx)
        return std, inv, False

    def drop_index(self, index):
        """Explicitly drop every entry AND the per-index stats for
        ``index`` (index deletion — the name may never be queried
        again, so lazy epoch invalidation would retain them forever)."""
        with self._mu:
            self._universe.pop(index, None)
            self._by_index.pop(index, None)
            dead = [k for k in self._entries if k[1] == index]
            for k in dead:
                del self._entries[k]
            self.invalidations += len(dead)

    # -------------------------------------------------------------- intro

    def metrics(self):
        """Flat dict for the /metrics ``pilosa_plan_cache_*`` group.
        ``entries`` is LRU occupancy only (comparable to
        ``capacity``); universe memos — one per live index, outside
        the LRU — report separately, and both surfaces (here and
        ``snapshot``) agree on the split."""
        with self._mu:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "invalidations": self.invalidations,
                "entries": len(self._entries),
                "universe_entries": len(self._universe),
                "capacity": self.capacity,
            }

    def snapshot(self):
        """GET /debug/plans payload: totals, per-index hit rates and
        current validity epochs, per-kind entry counts, and the
        universe memo state."""
        with self._mu:
            total = self.hits + self.misses
            kinds = {}
            for k in self._entries:
                kinds[k[0]] = kinds.get(k[0], 0) + 1
            per_index = {}
            for index, (h, m) in self._by_index.items():
                per_index[index] = {
                    "hits": h, "misses": m,
                    "hitRate": round(h / (h + m), 4) if h + m else 0.0,
                    "validityEpoch": _frag.mutation_epoch(index),
                }
            universe = {
                index: {"slices": len(std), "inverseSlices": len(inv),
                        "token": list(tok)}
                for index, (tok, std, inv) in self._universe.items()}
            return {
                "enabled": self.capacity != 0,
                "capacity": self.capacity,
                "entries": len(self._entries),
                "hits": self.hits,
                "misses": self.misses,
                "invalidations": self.invalidations,
                "hitRate": round(self.hits / total, 4) if total else 0.0,
                "entriesByKind": kinds,
                "perIndex": per_index,
                "universe": universe,
            }
