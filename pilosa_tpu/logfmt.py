"""Structured logging (``log-format = "json"`` / PILOSA_LOG_FORMAT).

Every record renders as one JSON object per line with the fields log
pipelines expect (ts, level, logger, msg, exc) — and, when the calling
thread is inside an active trace (tracing.py), the record is stamped
with that trace's ``trace_id``/``span_id``, so a grep for a trace id
from ``/debug/traces`` or an ``X-Pilosa-Trace-Id`` response header
lands on exactly the log lines that query produced. The plain text
formatter stays the default; JSON is opt-in per node.
"""
import json
import logging
import sys
import time

from pilosa_tpu import tracing


class JSONFormatter(logging.Formatter):
    """One JSON object per record; trace context stamped when a span
    is active on the emitting thread."""

    def format(self, record):
        out = {
            "ts": round(record.created, 6),
            "time": time.strftime("%Y-%m-%dT%H:%M:%S",
                                  time.gmtime(record.created))
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        sp = tracing.active_span()
        if sp is not None and sp is not tracing.NOP_SPAN:
            out["trace_id"] = sp.trace.trace_id
            out["span_id"] = sp.span_id
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


def setup_logging(log_format="", log_path="", level=logging.INFO):
    """Install the configured formatter on the root logger: JSON when
    ``log_format == "json"``, classic text otherwise; records go to
    ``log_path`` when set, stderr otherwise. Idempotent enough for the
    CLI entrypoint (replaces handlers this function installed before,
    never third-party ones). Returns the handler."""
    if log_path:
        handler = logging.FileHandler(log_path)
    else:
        handler = logging.StreamHandler(sys.stderr)
    if log_format == "json":
        handler.setFormatter(JSONFormatter())
    else:
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s"))
    handler._pilosa_log = True  # marker for idempotent reinstall
    root = logging.getLogger()
    for h in list(root.handlers):
        if getattr(h, "_pilosa_log", False):
            root.removeHandler(h)
    root.addHandler(handler)
    root.setLevel(level)
    return handler
