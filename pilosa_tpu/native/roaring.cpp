// Native host runtime for pilosa_tpu: roaring file codec, xxhash64,
// and bit-position extraction.
//
// The TPU owns the query compute; this library owns the host-side hot
// paths around it — the at-rest roaring format (serialize/deserialize
// between dense 2^16-bit blocks and the reference file layout,
// roaring/roaring.go:560-738), anti-entropy block hashing (xxhash64),
// and set-bit position extraction for block data / export. Exposed as a
// C ABI consumed via ctypes; the Python implementations remain as
// fallback when the shared object is unavailable.
//
// Build: g++ -O3 -march=native -shared -fPIC -o libpilosa_native.so roaring.cpp

#include <cstdint>
#include <cstring>
#include <cstddef>

extern "C" {

// ---------------------------------------------------------------- xxhash64

static inline uint64_t rotl64(uint64_t x, int r) {
    return (x << r) | (x >> (64 - r));
}

static const uint64_t P1 = 0x9E3779B185EBCA87ULL;
static const uint64_t P2 = 0xC2B2AE3D27D4EB4FULL;
static const uint64_t P3 = 0x165667B19E3779F9ULL;
static const uint64_t P4 = 0x85EBCA77C2B2AE63ULL;
static const uint64_t P5 = 0x27D4EB2F165667C5ULL;

static inline uint64_t xx_round(uint64_t acc, uint64_t lane) {
    acc += lane * P2;
    return rotl64(acc, 31) * P1;
}

static inline uint64_t xx_merge(uint64_t acc, uint64_t val) {
    acc ^= xx_round(0, val);
    return acc * P1 + P4;
}

static inline uint64_t read64(const uint8_t* p) {
    uint64_t v;
    memcpy(&v, p, 8);
    return v;  // little-endian hosts only (x86-64 / aarch64)
}

static inline uint32_t read32(const uint8_t* p) {
    uint32_t v;
    memcpy(&v, p, 4);
    return v;
}

uint64_t pn_xxhash64(const uint8_t* data, size_t n, uint64_t seed) {
    const uint8_t* p = data;
    const uint8_t* end = data + n;
    uint64_t h;
    if (n >= 32) {
        uint64_t v1 = seed + P1 + P2, v2 = seed + P2, v3 = seed,
                 v4 = seed - P1;
        const uint8_t* limit = end - 32;
        do {
            v1 = xx_round(v1, read64(p));
            v2 = xx_round(v2, read64(p + 8));
            v3 = xx_round(v3, read64(p + 16));
            v4 = xx_round(v4, read64(p + 24));
            p += 32;
        } while (p <= limit);
        h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
        h = xx_merge(h, v1);
        h = xx_merge(h, v2);
        h = xx_merge(h, v3);
        h = xx_merge(h, v4);
    } else {
        h = seed + P5;
    }
    h += (uint64_t)n;
    while (p + 8 <= end) {
        h ^= xx_round(0, read64(p));
        h = rotl64(h, 27) * P1 + P4;
        p += 8;
    }
    if (p + 4 <= end) {
        h ^= (uint64_t)read32(p) * P1;
        h = rotl64(h, 23) * P2 + P3;
        p += 4;
    }
    while (p < end) {
        h ^= (uint64_t)(*p) * P5;
        h = rotl64(h, 11) * P1;
        p++;
    }
    h ^= h >> 33;
    h *= P2;
    h ^= h >> 29;
    h *= P3;
    h ^= h >> 32;
    return h;
}

// ----------------------------------------------------------------- fnv1a32

uint32_t pn_fnv32a(const uint8_t* data, size_t n) {
    uint32_t h = 2166136261u;
    for (size_t i = 0; i < n; i++) {
        h ^= data[i];
        h *= 16777619u;
    }
    return h;
}

// ----------------------------------------------------- position extraction

// Extract set-bit positions from packed little-endian u64 words.
// out must hold at least popcount(words) entries. Returns count written.
// Positions are absolute: word_index*64 + bit.
int64_t pn_extract_positions(const uint64_t* words, int64_t n_words,
                             uint64_t base, uint64_t* out) {
    int64_t k = 0;
    for (int64_t w = 0; w < n_words; w++) {
        uint64_t x = words[w];
        uint64_t off = base + (uint64_t)w * 64;
        while (x) {
            out[k++] = off + (uint64_t)__builtin_ctzll(x);
            x &= x - 1;
        }
    }
    return k;
}

int64_t pn_popcount(const uint64_t* words, int64_t n_words) {
    int64_t total = 0;
    for (int64_t w = 0; w < n_words; w++)
        total += __builtin_popcountll(words[w]);
    return total;
}

// Per-row popcount over a dense row-major matrix: out[i] = popcount of
// row rows[i]. The host analog of the per-row cardinality recount after
// a bulk import (ref: fragment.go:1266-1333 cache rebuild).
void pn_popcount_rows(const uint64_t* matrix, int64_t words_per_row,
                      const int64_t* rows, int64_t n_rows, int64_t* out) {
    for (int64_t i = 0; i < n_rows; i++) {
        const uint64_t* row = matrix + rows[i] * words_per_row;
        int64_t total = 0;
        for (int64_t w = 0; w < words_per_row; w++)
            total += __builtin_popcountll(row[w]);
        out[i] = total;
    }
}

// Scatter-OR a batch of bits into a dense row-major matrix:
// matrix[phys[i]][cols[i] >> 6] |= 1 << (cols[i] & 63). Duplicates are
// naturally idempotent; no sort or dedup pass needed.
void pn_scatter_or(uint64_t* matrix, int64_t words_per_row,
                   const int64_t* phys, const uint64_t* cols, int64_t n) {
    for (int64_t i = 0; i < n; i++) {
        uint64_t c = cols[i];
        matrix[phys[i] * words_per_row + (int64_t)(c >> 6)] |=
            (uint64_t)1 << (c & 63);
    }
}

// ------------------------------------------------------------ roaring file
//
// Layout (roaring/roaring.go:560-738):
//   cookie u32 = 12348 | version<<16; count u32
//   per container: key u64, type u16, n-1 u16   (12 bytes)
//   per container: offset u32
//   container payloads: array u16[n] | bitmap u64[1024] |
//                       run { u16 count; (u16 start, u16 last)[count] }
//   trailing 13-byte op log records (handled in Python)

static const uint32_t MAGIC = 12348;
static const int BITMAP_N = 1024;       // u64 words per container
static const int ARRAY_MAX = 4096;
static const int RUN_MAX = 2048;
static const int T_ARRAY = 1, T_BITMAP = 2, T_RUN = 3;

struct BlockStats {
    int32_t n;       // cardinality
    int32_t runs;    // run count
};

static BlockStats block_stats(const uint64_t* block, int64_t words) {
    BlockStats s = {0, 0};
    uint64_t prev_msb = 0;  // bit 63 of previous word
    for (int64_t w = 0; w < words; w++) {
        uint64_t x = block[w];
        s.n += __builtin_popcountll(x);
        // run starts = bits set whose predecessor bit is clear
        uint64_t starts = x & ~((x << 1) | prev_msb);
        s.runs += __builtin_popcountll(starts);
        prev_msb = x >> 63;
    }
    return s;
}

// Compute the serialized size for keys/blocks (first pass).
// keys: u64[n_blocks]; blocks: u64[n_blocks * stride], each block's
// words beyond the stride implicitly zero (narrow-window fragments
// store only their span — scanning their true width instead of a
// zero-padded 1024 words is up to 16x less memory bandwidth, the
// dominant snapshot cost on row-heavy data).
// Returns total byte size; fills per-block type+size temp arrays.
int64_t pn_serialized_size_w(const uint64_t* blocks, int64_t n_blocks,
                             int64_t stride, uint8_t* types,
                             int32_t* sizes, int32_t* cards) {
    int64_t total = 8;  // cookie + count
    for (int64_t i = 0; i < n_blocks; i++) {
        BlockStats s = block_stats(blocks + i * stride, stride);
        cards[i] = s.n;
        if (s.n == 0) {
            types[i] = 0;
            sizes[i] = 0;
            continue;
        }
        int32_t run_size = (s.runs <= RUN_MAX) ? 2 + 4 * s.runs : INT32_MAX;
        int32_t arr_size = (s.n <= ARRAY_MAX) ? 2 * s.n : INT32_MAX;
        int32_t bmp_size = BITMAP_N * 8;
        if (run_size <= arr_size && run_size <= bmp_size) {
            types[i] = T_RUN;
            sizes[i] = run_size;
        } else if (arr_size <= bmp_size) {
            types[i] = T_ARRAY;
            sizes[i] = arr_size;
        } else {
            types[i] = T_BITMAP;
            sizes[i] = bmp_size;
        }
        total += 12 + 4 + sizes[i];
    }
    return total;
}

int64_t pn_serialized_size(const uint64_t* blocks, int64_t n_blocks,
                           uint8_t* types, int32_t* sizes, int32_t* cards) {
    return pn_serialized_size_w(blocks, n_blocks, BITMAP_N, types, sizes,
                                cards);
}

static inline void put16(uint8_t*& p, uint16_t v) { memcpy(p, &v, 2); p += 2; }
static inline void put32(uint8_t*& p, uint32_t v) { memcpy(p, &v, 4); p += 4; }
static inline void put64(uint8_t*& p, uint64_t v) { memcpy(p, &v, 8); p += 8; }

// Second pass: write the file into out (size from pn_serialized_size_w).
int64_t pn_serialize_w(const uint64_t* keys, const uint64_t* blocks,
                       int64_t n_blocks, int64_t stride,
                       const uint8_t* types, const int32_t* sizes,
                       const int32_t* cards, uint8_t* out) {
    int64_t live = 0;
    for (int64_t i = 0; i < n_blocks; i++)
        if (types[i]) live++;

    uint8_t* p = out;
    put32(p, MAGIC);
    put32(p, (uint32_t)live);
    for (int64_t i = 0; i < n_blocks; i++) {
        if (!types[i]) continue;
        put64(p, keys[i]);
        put16(p, (uint16_t)types[i]);
        put16(p, (uint16_t)(cards[i] - 1));
    }
    uint32_t offset = (uint32_t)(8 + live * 16);
    for (int64_t i = 0; i < n_blocks; i++) {
        if (!types[i]) continue;
        put32(p, offset);
        offset += (uint32_t)sizes[i];
    }
    for (int64_t i = 0; i < n_blocks; i++) {
        if (!types[i]) continue;
        const uint64_t* blk = blocks + i * stride;
        if (types[i] == T_BITMAP) {
            memcpy(p, blk, stride * 8);
            if (stride < BITMAP_N)
                memset(p + stride * 8, 0, (BITMAP_N - stride) * 8);
            p += BITMAP_N * 8;
        } else if (types[i] == T_ARRAY) {
            for (int64_t w = 0; w < stride; w++) {
                uint64_t x = blk[w];
                while (x) {
                    put16(p, (uint16_t)(w * 64 + __builtin_ctzll(x)));
                    x &= x - 1;
                }
            }
        } else {  // T_RUN
            uint8_t* count_pos = p;
            p += 2;
            uint16_t runs = 0;
            int32_t start = -1;
            const int64_t nbits = stride * 64;
            for (int64_t bit = 0; bit < nbits; bit++) {
                bool set = (blk[bit >> 6] >> (bit & 63)) & 1;
                if (set && start < 0) start = (int32_t)bit;
                if (!set && start >= 0) {
                    put16(p, (uint16_t)start);
                    put16(p, (uint16_t)(bit - 1));
                    runs++;
                    start = -1;
                }
            }
            if (start >= 0) {
                put16(p, (uint16_t)start);
                put16(p, (uint16_t)(nbits - 1));
                runs++;
            }
            memcpy(count_pos, &runs, 2);
        }
    }
    return p - out;
}

int64_t pn_serialize(const uint64_t* keys, const uint64_t* blocks,
                     int64_t n_blocks, const uint8_t* types,
                     const int32_t* sizes, const int32_t* cards,
                     uint8_t* out) {
    return pn_serialize_w(keys, blocks, n_blocks, BITMAP_N, types, sizes,
                          cards, out);
}

// Parse header: returns container count, or -1 on bad magic/-2 bad version.
int64_t pn_header_info(const uint8_t* data, int64_t n) {
    if (n < 8) return -1;
    uint16_t magic, version;
    memcpy(&magic, data, 2);
    memcpy(&version, data + 2, 2);
    if (magic != MAGIC) return -1;
    if (version != 0) return -2;
    uint32_t count;
    memcpy(&count, data + 4, 4);
    return (int64_t)count;
}

// Deserialize containers into dense blocks.
// keys_out: u64[count]; blocks_out: u64[count*1024] (zeroed by caller).
// Returns byte offset where the op log begins, or -1 on error.
int64_t pn_deserialize(const uint8_t* data, int64_t n, int64_t count,
                       uint64_t* keys_out, uint64_t* blocks_out) {
    int64_t hdr = 8;
    int64_t off_section = hdr + count * 12;
    int64_t data_end = off_section + count * 4;
    if (data_end > n) return -1;

    for (int64_t i = 0; i < count; i++) {
        const uint8_t* meta = data + hdr + i * 12;
        uint64_t key;
        uint16_t type, n_minus1;
        memcpy(&key, meta, 8);
        memcpy(&type, meta + 8, 2);
        memcpy(&n_minus1, meta + 10, 2);
        int32_t card = (int32_t)n_minus1 + 1;
        uint32_t coff;
        memcpy(&coff, data + off_section + i * 4, 4);
        if (coff >= (uint64_t)n) return -1;

        keys_out[i] = key;
        uint64_t* blk = blocks_out + i * BITMAP_N;
        const uint8_t* payload = data + coff;
        if (type == T_ARRAY) {
            if (coff + 2 * card > n) return -1;
            for (int32_t j = 0; j < card; j++) {
                uint16_t pos;
                memcpy(&pos, payload + 2 * j, 2);
                blk[pos >> 6] |= 1ULL << (pos & 63);
            }
            if (coff + 2 * card > data_end) data_end = coff + 2 * card;
        } else if (type == T_BITMAP) {
            if (coff + BITMAP_N * 8 > n) return -1;
            memcpy(blk, payload, BITMAP_N * 8);
            if (coff + BITMAP_N * 8 > data_end)
                data_end = coff + BITMAP_N * 8;
        } else if (type == T_RUN) {
            uint16_t run_n;
            if (coff + 2 > (uint64_t)n) return -1;
            memcpy(&run_n, payload, 2);
            if (coff + 2 + 4 * run_n > (uint64_t)n) return -1;
            for (int32_t r = 0; r < run_n; r++) {
                uint16_t start, last;
                memcpy(&start, payload + 2 + 4 * r, 2);
                memcpy(&last, payload + 2 + 4 * r + 2, 2);
                for (int32_t bit = start; bit <= last; bit++)
                    blk[bit >> 6] |= 1ULL << (bit & 63);
            }
            int64_t end = coff + 2 + 4 * run_n;
            if (end > data_end) data_end = end;
        } else {
            return -1;
        }
    }
    return data_end;
}

// ---------------------------------------------------------------- CSV parse
// Numeric CSV fast path for the import pipeline (ref: ctl/import.go:146
// bufferBits parses "row,col[,ts]" / "col,value" lines in the CLI hot
// loop). Parses up to 3 signed int64 fields per line into out[rec*3+f];
// missing fields stay 0. Tolerates \r\n, spaces around numbers, and
// blank lines. Returns record count, or -(line_number) on a malformed
// line so the caller can report it.
int64_t pn_parse_csv(const uint8_t* data, int64_t n, int64_t* out,
                     int64_t max_records) {
    const int64_t OVF = INT64_MAX / 10;
    int64_t rec = 0, line_no = 1;
    int64_t i = 0;
    while (i < n && rec < max_records) {
        // skip blank lines
        while (i < n && (data[i] == '\n' || data[i] == '\r')) {
            if (data[i] == '\n') line_no++;
            i++;
        }
        if (i >= n) break;
        int64_t* fields = out + rec * 3;
        fields[0] = fields[1] = fields[2] = 0;
        int f = 0;
        bool line_ok = true;
        bool pending = true;  // a field is required (start of line / after ',')
        while (i < n && data[i] != '\n') {
            while (i < n && data[i] == ' ') i++;
            bool neg = false;
            if (i < n && (data[i] == '-' || data[i] == '+')) {
                neg = data[i] == '-';
                i++;
            }
            if (i >= n || data[i] < '0' || data[i] > '9') {
                line_ok = false;  // empty field ("1,,2"), junk, or lone sign
                break;
            }
            int64_t v = 0;
            while (i < n && data[i] >= '0' && data[i] <= '9') {
                int d = data[i] - '0';
                if (v > OVF || (v == OVF && d > 7)) return -line_no;
                v = v * 10 + d;
                i++;
            }
            while (i < n && data[i] == ' ') i++;
            if (f < 3) fields[f] = neg ? -v : v;
            f++;
            pending = false;
            if (i < n && data[i] == ',') { i++; pending = true; continue; }
            if (i < n && data[i] == '\r') { i++; }
            break;
        }
        // `pending` rejects trailing commas ("1,2,\n") the same way the
        // Python csv+int() path does.
        if (!line_ok || pending || (i < n && data[i] != '\n'))
            return -line_no;
        if (i < n) { i++; line_no++; }  // consume \n
        rec++;
    }
    return rec;
}

// ------------------------------------------------------------ op-log batch
// Encode n op records (13 bytes each: typ u8, value u64 LE, fnv1a-32 of
// the first 9 bytes) in one pass — the batch form of op.WriteTo
// (roaring.go:2852-2867) for bulk SetBit storms.
void pn_encode_ops(const uint8_t* typs, const uint64_t* values, int64_t n,
                   uint8_t* out) {
    for (int64_t i = 0; i < n; i++) {
        uint8_t* p = out + i * 13;
        p[0] = typs[i];
        memcpy(p + 1, &values[i], 8);
        uint32_t h = 2166136261u;
        for (int j = 0; j < 9; j++) {
            h ^= p[j];
            h *= 16777619u;
        }
        memcpy(p + 9, &h, 4);
    }
}

}  // extern "C"

