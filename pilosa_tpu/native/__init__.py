"""ctypes loader for the native host runtime (roaring.cpp).

Compiles on demand with g++ (cached beside the source); every consumer
falls back to the pure-Python implementation when the toolchain or the
shared object is unavailable, so the native layer is a transparent
accelerator, never a hard dependency.
"""
import ctypes
import os
import subprocess
import threading

from pilosa_tpu import lockcheck

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "roaring.cpp")
_SO = os.path.join(_HERE, "libpilosa_native.so")

_lock = lockcheck.register("native._lock", threading.Lock())
_lib = None
_tried = False


def _build(out=_SO):
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           "-o", out, _SRC]
    subprocess.run(cmd, check=True, capture_output=True)


def load():
    """Return the loaded library or None."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                _build()
            lib = ctypes.CDLL(_SO)
            lib.pn_serialize_w  # newest symbol: stale .so (equal mtimes
        except AttributeError:  # after checkout) -> force one rebuild
            # dlopen dedups by path against the stale handle already
            # mapped above, so the rebuild must load from a fresh
            # path; the fresh build also replaces _SO for next time.
            rebuilt = _SO + ".rebuild.so"
            try:
                _build(rebuilt)
                lib = ctypes.CDLL(rebuilt)
                lib.pn_serialize_w
                os.replace(rebuilt, _SO)
            except (OSError, subprocess.CalledProcessError,
                    AttributeError):
                try:
                    os.unlink(rebuilt)
                except OSError:
                    pass
                return None
        except (OSError, subprocess.CalledProcessError):
            return None

        u8p = ctypes.POINTER(ctypes.c_uint8)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i32p = ctypes.POINTER(ctypes.c_int32)

        lib.pn_xxhash64.argtypes = [u8p, ctypes.c_size_t, ctypes.c_uint64]
        lib.pn_xxhash64.restype = ctypes.c_uint64
        lib.pn_fnv32a.argtypes = [u8p, ctypes.c_size_t]
        lib.pn_fnv32a.restype = ctypes.c_uint32
        lib.pn_extract_positions.argtypes = [u64p, ctypes.c_int64,
                                             ctypes.c_uint64, u64p]
        lib.pn_extract_positions.restype = ctypes.c_int64
        lib.pn_popcount.argtypes = [u64p, ctypes.c_int64]
        lib.pn_popcount.restype = ctypes.c_int64
        lib.pn_serialized_size_w.argtypes = [u64p, ctypes.c_int64,
                                             ctypes.c_int64, u8p, i32p,
                                             i32p]
        lib.pn_serialized_size_w.restype = ctypes.c_int64
        lib.pn_serialize_w.argtypes = [u64p, u64p, ctypes.c_int64,
                                       ctypes.c_int64, u8p, i32p,
                                       i32p, u8p]
        lib.pn_serialize_w.restype = ctypes.c_int64
        lib.pn_header_info.argtypes = [u8p, ctypes.c_int64]
        lib.pn_header_info.restype = ctypes.c_int64
        lib.pn_deserialize.argtypes = [u8p, ctypes.c_int64, ctypes.c_int64,
                                       u64p, u64p]
        lib.pn_deserialize.restype = ctypes.c_int64
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.pn_parse_csv.argtypes = [u8p, ctypes.c_int64, i64p,
                                     ctypes.c_int64]
        lib.pn_parse_csv.restype = ctypes.c_int64
        lib.pn_encode_ops.argtypes = [u8p, u64p, ctypes.c_int64, u8p]
        lib.pn_encode_ops.restype = None
        lib.pn_popcount_rows.argtypes = [u64p, ctypes.c_int64, i64p,
                                         ctypes.c_int64, i64p]
        lib.pn_popcount_rows.restype = None
        lib.pn_scatter_or.argtypes = [u64p, ctypes.c_int64, i64p, u64p,
                                      ctypes.c_int64]
        lib.pn_scatter_or.restype = None
        _lib = lib
        return _lib


def available():
    return load() is not None


# ------------------------------------------------------- numpy front-ends

def _u8(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _u64(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


def xxhash64(data: bytes, seed: int = 0):
    lib = load()
    if lib is None:
        return None
    buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data) if data else \
        (ctypes.c_uint8 * 1)()
    return int(lib.pn_xxhash64(
        ctypes.cast(buf, ctypes.POINTER(ctypes.c_uint8)), len(data), seed))


def extract_positions(words, base=0):
    """np.uint64 packed words -> np.uint64 sorted set-bit positions."""
    import numpy as np

    lib = load()
    if lib is None:
        return None
    words = np.ascontiguousarray(words, dtype=np.uint64)
    n = int(lib.pn_popcount(_u64(words), words.size))
    out = np.empty(n, dtype=np.uint64)
    k = int(lib.pn_extract_positions(_u64(words), words.size, base,
                                     _u64(out)))
    return out[:k]


def serialize(keys, blocks):
    """(np.uint64[n], np.uint64[n, stride]) -> roaring file bytes.

    ``blocks`` may be NARROW (stride < 1024 words per container):
    words beyond the stride are implicitly zero, and the native side
    scans only the true span — on row-heavy narrow fragments the
    zero-padded scan was up to 16x the memory bandwidth of the data.
    """
    import numpy as np

    lib = load()
    if lib is None:
        return None
    keys = np.ascontiguousarray(keys, dtype=np.uint64)
    blocks = np.ascontiguousarray(blocks, dtype=np.uint64)
    n = keys.size
    stride = blocks.shape[1] if blocks.ndim == 2 and n else 1024
    if stride > 1024:
        # A wider-than-container block would overrun the 8 KiB bitmap
        # payload slot in the native writer — reject loudly rather
        # than corrupt the heap.
        raise ValueError(f"container blocks are at most 1024 words, "
                         f"got {stride}")
    types = np.zeros(n, dtype=np.uint8)
    sizes = np.zeros(n, dtype=np.int32)
    cards = np.zeros(n, dtype=np.int32)
    total = int(lib.pn_serialized_size_w(
        _u64(blocks), n, stride, _u8(types),
        sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        cards.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))))
    out = np.empty(total, dtype=np.uint8)
    written = int(lib.pn_serialize_w(
        _u64(keys), _u64(blocks), n, stride, _u8(types),
        sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        cards.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), _u8(out)))
    return out[:written].tobytes()


def deserialize(data: bytes):
    """roaring file bytes -> (keys np.uint64[n], blocks np.uint64[n,1024],
    oplog_offset) or None (fallback) ; raises ValueError on bad file."""
    import numpy as np

    lib = load()
    if lib is None:
        return None
    buf = np.frombuffer(data, dtype=np.uint8)
    count = int(lib.pn_header_info(_u8(buf), buf.size))
    if count == -1:
        raise ValueError("invalid roaring file, magic number mismatch")
    if count == -2:
        raise ValueError("wrong roaring version")
    keys = np.zeros(count, dtype=np.uint64)
    blocks = np.zeros((count, 1024), dtype=np.uint64)
    end = int(lib.pn_deserialize(_u8(buf), buf.size, count, _u64(keys),
                                 _u64(blocks)))
    if end < 0:
        raise ValueError("corrupt roaring container data")
    return keys, blocks, end


def parse_csv(data: bytes):
    """Numeric CSV bytes -> np.int64[n, 3] (missing fields 0), or None
    (no native lib). Raises ValueError with the 1-based line number on
    a malformed line — matching the CLI's int() failure behavior."""
    import numpy as np

    lib = load()
    if lib is None:
        return None
    buf = np.frombuffer(data, dtype=np.uint8)
    # upper bound: one record per line
    max_rec = int(np.count_nonzero(buf == ord("\n"))) + 1
    out = np.zeros((max_rec, 3), dtype=np.int64)
    n = int(lib.pn_parse_csv(
        _u8(buf), buf.size,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), max_rec))
    if n < 0:
        raise ValueError(f"malformed CSV at line {-n}")
    return out[:n]


def encode_ops(typs, values):
    """Batch-encode op-log records: (np.uint8[n], np.uint64[n]) ->
    13n bytes, or None (no native lib)."""
    import numpy as np

    lib = load()
    if lib is None:
        return None
    typs = np.ascontiguousarray(typs, dtype=np.uint8)
    values = np.ascontiguousarray(values, dtype=np.uint64)
    out = np.empty(13 * typs.size, dtype=np.uint8)
    lib.pn_encode_ops(_u8(typs), _u64(values), typs.size, _u8(out))
    return out.tobytes()


def _i64(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def popcount_rows(matrix, rows):
    """Per-row popcount of a C-contiguous np.uint64[cap, W] matrix:
    returns np.int64[len(rows)], or None (no native lib)."""
    import numpy as np

    # gate on available(): it is the monkeypatch seam the fallback
    # tests use to force-disable the native layer (load() is cached,
    # so the extra call is a dict check)
    lib = load() if available() else None
    if (lib is None or not matrix.flags["C_CONTIGUOUS"]
            or matrix.dtype != np.uint64):
        return None
    rows = np.ascontiguousarray(rows, dtype=np.int64)
    out = np.empty(rows.size, dtype=np.int64)
    lib.pn_popcount_rows(_u64(matrix), matrix.shape[-1], _i64(rows),
                         rows.size, _i64(out))
    return out


def scatter_or(matrix, phys, cols):
    """matrix[phys[i]][cols[i]>>6] |= 1 << (cols[i]&63), in place.
    Returns False (caller must fall back) when the lib is missing or
    the matrix is not C-contiguous."""
    import numpy as np

    # available() is the test seam; see popcount_rows
    lib = load() if available() else None
    if (lib is None or not matrix.flags["C_CONTIGUOUS"]
            or matrix.dtype != np.uint64):
        return False
    phys = np.ascontiguousarray(phys, dtype=np.int64)
    cols = np.ascontiguousarray(cols, dtype=np.uint64)
    lib.pn_scatter_or(_u64(matrix), matrix.shape[-1], _i64(phys),
                      _u64(cols), phys.size)
    return True
